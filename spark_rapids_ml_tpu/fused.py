#
# Fused stage-and-solve engine — the one-pass sufficient-statistics
# estimators (PCA, LinearRegression) solve WHILE they stage.  The
# two-phase path pays stage + solve strictly additively (BENCH_r05:
# refconfig PCA = 220 s stage + 193 s solve); here each host chunk's
# Gram/moment/cross contribution is folded into a donated device
# accumulator the moment the chunk lands on the mesh, with the host
# producer thread (utils.prefetch_iter — the PR-2 staging pipeline's
# overlap primitive) prepping chunk N+1 while the mesh accumulates chunk
# N.  The full staged array never exists: HBM holds one sharded chunk +
# the (d,d)-class accumulator, and wall time collapses toward
# max(stage, solve).  The "Parallel-and-stream accelerator" overlap
# pattern and Snap ML's chunk-local host/accelerator accumulate
# (PAPERS.md) are the templates.
#
# Routing lives in core.py (`fused_stage_solve` conf: auto|on|off);
# the chunk update math lives in ops/stats.py (shared with the
# multi-pass streaming fits, incl. the Kahan-compensated
# `stats_precision="high_compensated"` level); the randomized PCA
# range-finder (ops/pca.py) composes: each of its tall-skinny passes is
# one stage-overlapped accumulation here.
#
from __future__ import annotations

import functools
import time
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import numpy as np

from .config import get_config
from .telemetry.registry import dict_view as _dict_view
from .telemetry.utilization import (
    interval_overlap_s as _interval_overlap_s,
    merge_intervals as _merge_intervals,
)
from .utils import get_logger

logger = get_logger("spark_rapids_ml_tpu.fused")

# last fused run (read by bench.py's `fused_pca` section, the refconfig
# stage/solve split, and the per-fit telemetry report — the report copies
# these keys only when `stamp` lands inside the fit's window):
#   host_prep_s   chunk decode/cast/slice time on the reader thread(s)
#   device_acc_s  device_put + accumulate time on the consumer thread
#   overlap_s     measured wall-clock INTERSECTION of the prep intervals
#                 with the device-busy intervals (_interval_overlap_s)
#   overlap_fraction  overlap_s / min(prep_s, acc_s) in [0, 1]
FUSED_METRICS = _dict_view(
    "fused_last",
    "Last fused stage-and-solve run (prep/accumulate/overlap seconds)",
)

# `fused_stage_solve="auto"` fuses once the estimated staged bytes reach
# this floor: below it one plain staging beats the per-chunk dispatch
# overhead and the two-phase path keeps its exact single-matmul stats
_AUTO_MIN_BYTES = 64 * 1024 * 1024

# aim for at least this many chunks per pass so the producer thread has
# something to run ahead on (one-chunk passes cannot overlap)
_MIN_CHUNKS = 8
_MIN_CHUNK_ROWS = 1024


def fused_mode() -> str:
    mode = str(get_config("fused_stage_solve")).lower()
    if mode not in ("auto", "on", "off"):
        raise ValueError(
            f"fused_stage_solve must be auto|on|off, got {mode!r}"
        )
    return mode


def fused_enabled(est_bytes: float) -> bool:
    """Whether the conf routes an ELIGIBLE fit (dense, statistics-capable
    — the caller checks those) through the fused engine: "on" always,
    "auto" once the staged-bytes estimate clears `_AUTO_MIN_BYTES`,
    "off" never.  Multi-process fits run fused too: each rank folds its
    ingest share on its LOCAL devices and the partials meet in one
    reduction at pass_complete (parallel/context.py) — the gate only
    drops to the two-phase paths when no reduce seam is available
    (jax.distributed not initialized)."""
    mode = fused_mode()
    if mode == "off":
        return False
    import jax

    if jax.process_count() > 1:
        from .parallel.context import cross_process_reduce_ready

        if not cross_process_reduce_ready():
            return False
    if mode == "on":
        return True
    return float(est_bytes) >= _AUTO_MIN_BYTES


@functools.lru_cache(maxsize=32)
def _jitted_steps(
    kind: str, d: int, l: int, dtype_str: str,
    precision: str, compensated: bool,
):
    """(weighted, unweighted) donated jitted accumulator steps per
    (kind, shape, dtype, precision) — repeated fused fits at the same
    shape reuse the compiled programs instead of re-tracing a fresh
    closure every fit (measured ~80 ms/fit of re-lowering on the CPU
    mesh).  The unweighted variant skips the `X * w` chunk-sized
    materialization for full chunks of weightless fits (ops/stats.py).
    `precision`/`compensated` key the conf values baked in at trace
    time; the initial zeros accumulator is built FRESH per fit (it is
    donated into the first step and must never be reused).

    The specs resolve through the statistic-program registry
    (stats/programs.py STAT_PROGRAMS) — `kind` IS the registered
    program name, so the fused estimators and any other registry
    consumer share one owner for the update math (the PR-8 specs,
    migrated)."""
    import jax

    from .stats.programs import get_program

    dtype = np.dtype(dtype_str)
    step, unw = get_program(kind).make_step(d, dtype, {"l": l})
    return (
        jax.jit(step, donate_argnums=0),
        jax.jit(unw, donate_argnums=0),
    )


def _acc_spec(kind: str, d: int, l: int, dtype):
    """(fresh initial accumulator, cached (weighted, unweighted) jitted
    steps) for the registered statistic program `kind`."""
    from .ops.precision import stats_compensated
    from .stats.programs import get_program

    dtype = np.dtype(dtype)
    acc = get_program(kind).init(d, dtype, {"l": l})
    steps = _jitted_steps(
        kind, d, l, dtype.str,
        str(get_config("stats_precision")).lower(), stats_compensated(),
    )
    return acc, steps


def fused_chunk_rows(n: int, d: int, itemsize: int, n_dev: int) -> int:
    """Rows per fused chunk: bounded by `staging_chunk_bytes` clamped to
    the transfer-RPC ceiling (the same sizing rule as the staging
    pipeline's pieces — mesh._staging_chunk_rows), floored so a pass
    still yields >= `_MIN_CHUNKS` chunks to overlap, and device-aligned
    so every chunk shards evenly over the mesh."""
    from .parallel.mesh import _MAX_PUT_BYTES

    row_bytes = max(d * itemsize, 1)
    budget = max(
        1,
        min(int(get_config("staging_chunk_bytes")), _MAX_PUT_BYTES)
        // row_bytes,
    )
    rows = min(budget, max(-(-n // _MIN_CHUNKS), _MIN_CHUNK_ROWS))
    rows = min(rows, max(n, 1))
    return -(-rows // n_dev) * n_dev


def iter_host_chunks(
    X: np.ndarray,
    y: Optional[np.ndarray],
    weight: Optional[np.ndarray],
    chunk_rows: int,
    dtype: np.dtype,
    label_dtype: Optional[np.dtype] = None,
) -> Iterable[Tuple[np.ndarray, Optional[np.ndarray], np.ndarray]]:
    """Fixed-shape `(X_chunk, y_chunk, w_chunk)` host chunks of an
    in-memory batch, fully PREPARED (cast + zero-padded tail + validity
    weights) inside `__next__` — on the fused pipeline this runs on the
    producer thread, overlapped with the device accumulate.  Mirrors
    `streaming.iter_chunks` semantics: padding rows carry weight 0, so
    they are mathematically absent from every statistic."""
    dtype = np.dtype(dtype)
    ldt = np.dtype(label_dtype) if label_dtype is not None else dtype
    n = int(X.shape[0])
    for lo in range(0, n, chunk_rows):
        hi = min(lo + chunk_rows, n)
        rows = hi - lo
        if rows == chunk_rows:
            cX = np.ascontiguousarray(X[lo:hi], dtype=dtype)
            # None = full unweighted chunk: the engine dispatches the
            # unweighted step (skips the X*w chunk copy entirely)
            cw = (
                None
                if weight is None
                else np.asarray(weight[lo:hi], dtype)
            )
            cy = (
                None if y is None
                else np.ascontiguousarray(
                    np.asarray(y[lo:hi]).reshape(-1), dtype=ldt
                )
            )
        else:  # zero-padded tail chunk (padding weight stays 0)
            cX = np.zeros((chunk_rows,) + X.shape[1:], dtype)
            cX[:rows] = X[lo:hi]
            cw = np.zeros((chunk_rows,), dtype)
            cw[:rows] = 1.0 if weight is None else np.asarray(
                weight[lo:hi], dtype
            )
            cy = None
            if y is not None:
                cy = np.zeros((chunk_rows,), ldt)
                cy[:rows] = np.asarray(y[lo:hi]).reshape(-1)
        yield cX, cy, cw


# last resolve_parquet_readers decision (stamped), copied into the fit
# report's solver_decision section by telemetry/report.py — "why did
# this fit decode with N readers" must be answerable from the artifact
LAST_READER_DECISION: dict = {}

# measured single-reader decode throughput (updated by `_range_chunks`
# after every un-cached single-reader pass): the `auto` reader count is
# sink-bounded by it — decode only needs to outrun the device transfer
_DECODE_RATE: dict = {}

_MAX_AUTO_READERS = 16


def resolve_parquet_readers(path: Optional[str] = None) -> int:
    """Effective parallel-reader count from the `fused_parquet_readers`
    conf.  Explicit ints pin the count (back-compat); "auto" probes the
    host: os.cpu_count() capped at `_MAX_AUTO_READERS`, then bounded by
    the measured decode-vs-sink rates when both are on record (readers
    beyond sink_rate/decode_rate + 1 only contend for memory
    bandwidth).  Row-group availability clamps later, in
    `_partition_row_groups`.  The decision (mode, count, reason) lands
    in `LAST_READER_DECISION` for the fit report."""
    import os

    raw = get_config("fused_parquet_readers")
    mode = str(raw).strip().lower()
    if mode == "auto":
        cores = os.cpu_count() or 1
        readers = max(1, min(int(cores), _MAX_AUTO_READERS))
        reason = f"cpu_count={cores}"
        decode_mbs = _DECODE_RATE.get("mb_per_s")
        if decode_mbs:
            reason += f", measured_decode={decode_mbs:.0f}MB/s"
            from .parallel.mesh import STAGE_METRICS

            sink_mbs = STAGE_METRICS.get("mb_per_s")
            if sink_mbs:
                need = int(np.ceil(
                    float(sink_mbs) / max(float(decode_mbs), 1e-9)
                )) + 1
                if need < readers:
                    readers = max(1, need)
                    reason += f", sink-bounded at {sink_mbs:.0f}MB/s put"
    else:
        readers = max(1, int(raw))
        mode = "explicit"
        reason = "pinned by conf"
    LAST_READER_DECISION.clear()
    LAST_READER_DECISION.update(
        stamp=round(time.time(), 3),
        parquet_readers=int(readers),
        parquet_readers_mode=mode,
        parquet_readers_reason=reason,
    )
    return readers


def _partition_row_groups(path: str, readers: int) -> Optional[list]:
    """Split a single parquet FILE's row groups into `readers`
    row-balanced contiguous shares.  None when the path is a dataset
    directory or has too few groups to split — the caller then runs one
    in-order reader."""
    import os

    if readers <= 1 or os.path.isdir(path):
        return None
    import pyarrow.parquet as pq

    md = pq.ParquetFile(path).metadata
    sizes = [md.row_group(i).num_rows for i in range(md.num_row_groups)]
    if len(sizes) < 2:
        return None
    readers = min(readers, len(sizes))
    total = sum(sizes)
    shares, cur, acc = [], [], 0
    per = -(-total // readers)
    for i, s in enumerate(sizes):
        cur.append(i)
        acc += s
        if acc >= per and len(shares) < readers - 1:
            shares.append(cur)
            cur, acc = [], 0
    if cur:
        shares.append(cur)
    return shares if len(shares) > 1 else None


def process_row_group_shares(path: str, n_proc: int) -> Optional[list]:
    """Partition a parquet FILE's row groups into exactly `n_proc`
    contiguous row-balanced shares — the per-PROCESS ingest split of the
    fused producer (each host decodes only its share; the commutative
    accumulators make arrival order irrelevant).  Deterministic: pure
    arithmetic over the file metadata, identical on every rank.
    Coverage-asserted: the shares concatenate to every row group exactly
    once.  None when the path is a dataset directory or has fewer groups
    than processes — the caller then falls back to the chunk-index
    modulo split."""
    import os

    if n_proc <= 1 or os.path.isdir(path):
        return None
    import pyarrow.parquet as pq

    md = pq.ParquetFile(path).metadata
    sizes = [md.row_group(i).num_rows for i in range(md.num_row_groups)]
    if len(sizes) < n_proc:
        return None
    total = sum(sizes)
    per = -(-total // n_proc)
    shares, cur, acc = [], [], 0
    for i, s in enumerate(sizes):
        cur.append(i)
        acc += s
        if acc >= per and len(shares) < n_proc - 1:
            shares.append(cur)
            cur, acc = [], 0
    if cur:
        shares.append(cur)
    while len(shares) < n_proc:
        shares.append([])
    flat = [g for sh in shares for g in sh]
    if flat != list(range(len(sizes))):  # pragma: no cover - invariant
        raise AssertionError(
            f"process row-group shares do not cover {path} exactly once: "
            f"{shares}"
        )
    return shares


def _share_row_starts(path: str, shares: list) -> list:
    """Global first-row offset of each contiguous row-group share (the
    `_partition_row_groups` / `process_row_group_shares` output): prefix
    sums over the file's row-group sizes — pure metadata arithmetic,
    identical on every rank, same determinism contract as the split
    itself.  Empty shares get 0 (they yield no chunks anyway)."""
    import pyarrow.parquet as pq

    md = pq.ParquetFile(path).metadata
    sizes = [md.row_group(i).num_rows for i in range(md.num_row_groups)]
    starts = np.concatenate(([0], np.cumsum(sizes)))
    return [int(starts[sh[0]]) if sh else 0 for sh in shares]


def _reader_batches(path: str, columns, chunk_rows: int, groups=None):
    """Arrow record batches for the fused producer: a row-group-pruned
    `ParquetFile` reader for single files (measurably leaner than the
    dataset scanner on this path, and `groups` lets a parallel range
    reader decode ONLY its share — never scan-and-skip), with the
    dataset-scanner fallback for directory datasets."""
    import os

    if not os.path.isdir(path):
        import pyarrow.parquet as pq

        pf = pq.ParquetFile(path)
        kw = {} if groups is None else {"row_groups": list(groups)}
        yield from pf.iter_batches(
            batch_size=chunk_rows, columns=columns, **kw
        )
        return
    import pyarrow.dataset as pads

    yield from pads.dataset(path, format="parquet").to_batches(
        columns=columns, batch_size=chunk_rows
    )


def _range_chunks(
    path: str,
    features_col,
    features_cols,
    label_col,
    weight_col,
    chunk_rows: int,
    dtype: np.dtype,
    ldt: np.dtype,
    groups,
    base_offset: Optional[int] = None,
) -> Iterable[Tuple[np.ndarray, Optional[np.ndarray], Optional[np.ndarray]]]:
    """One reader's share of the fused parquet producer: decode + prepare
    `(X, y, w)` chunks of its row-group share
    (`streaming.chunks_from_batches` — the exact iter_chunks decode and
    fixed-shape chunking).  `w` is None for full unweighted chunks (the
    engine's fast step) and the zero-weighted padding vector on the
    share's tail chunk.

    With `base_offset` (the GLOBAL row index of this share's first row),
    chunks yield as 4-tuples `(X, y, w, global_offset)` — the exact
    first-row offset of each chunk in the whole FILE, tracked through
    valid-row counts so a partial tail chunk cannot skew later offsets.
    Offset-addressed accumulators (the kmeans_sample reservoir) need
    this to place rows identically no matter which rank decodes them."""
    from .streaming import _scan_columns, _weights_host, chunks_from_batches

    columns = _scan_columns(features_col, features_cols, label_col, weight_col)
    it = iter(chunks_from_batches(
        _reader_batches(path, columns, chunk_rows, groups),
        features_col, features_cols, label_col, weight_col,
        chunk_rows, np.dtype(dtype),
    ))
    off = None if base_offset is None else int(base_offset)
    decode_s = 0.0
    rows = 0
    nbytes = 0
    while True:
        t0 = time.perf_counter()
        try:
            cX, cy, cw, n_c = next(it)
        except StopIteration:
            break
        decode_s += time.perf_counter() - t0
        rows += int(n_c)
        nbytes += cX.nbytes
        if cw is None and n_c == chunk_rows:
            w_host = None  # full unweighted chunk -> unweighted step
        else:
            w_host = np.asarray(_weights_host(cw, n_c, chunk_rows, dtype))
        cy_out = None
        if cy is not None:
            cy_out = np.zeros((chunk_rows,), ldt)
            cy_out[:n_c] = np.asarray(cy[:n_c]).reshape(-1)
        if off is None:
            yield cX, cy_out, w_host
        else:
            yield cX, cy_out, w_host, off
            off += int(n_c)
    # single-reader decode rate feeds resolve_parquet_readers("auto");
    # too-short passes are scheduler noise, not a measurement
    if groups is None and decode_s > 0.02 and rows:
        _DECODE_RATE.update(
            rows_per_s=rows / decode_s, mb_per_s=nbytes / decode_s / 1e6,
        )


def iter_parquet_chunks(
    path: str,
    features_col,
    features_cols,
    label_col,
    weight_col,
    chunk_rows: int,
    dtype: np.dtype,
    label_dtype: Optional[np.dtype] = None,
    readers: Optional[int] = None,
    prep: Optional[Dict[str, Any]] = None,
    with_offsets: bool = False,
) -> Iterable[Tuple[np.ndarray, Optional[np.ndarray], Optional[np.ndarray]]]:
    """Parquet producer for the fused engine: the chunk decode (the
    dominant host cost of the refconfig fits) runs through a row-group-
    pruned reader, optionally split across `readers` PARALLEL range-
    reader threads (`fused_parquet_readers` conf), each decoding ONLY
    its own row-group share.  Chunk ARRIVAL ORDER is then arbitrary —
    which is exactly why this lives on the fused path only: the
    statistics accumulators are commutative sums, so order is
    irrelevant, while the two-phase staging path must place rows at
    their global offsets and keeps its single in-order scan.  Parallel
    readers pay off when the scan has idle time to recover (real IO, a
    multi-core host — the parallel-sharded-reader direction of ROADMAP
    item 4); the 1-core CI box measured the Arrow scan CPU-bound with
    readers=2 ~= readers=1, hence the conservative default of 1.

    When `prep` is given, each reader's decode time and wall intervals
    accumulate there ({"s": float, "iv": [(t0, t1)]}) — the engine's
    overlap measurement; interval lists from concurrent readers overlap
    and are union-merged by the consumer.

    The whole producer runs through the chunk cache: the first pass of
    a (path-stamp, scan-params) stream decodes parquet and records the
    prepared chunks; every later identical pass — the randomized PCA
    range-finder re-streaming the SAME file 2+power_iters times within
    one fit is the headline consumer — replays them without touching
    disk or the reader pool.  Replayed feature blocks may arrive
    device-resident (the engine's `device_put` reshards them in place);
    on a replayed pass the serve time is what lands in `prep`.

    `with_offsets=True` yields 4-tuples `(X, y, w, global_offset)`:
    each chunk carries the GLOBAL first-row index of its rows in the
    file, exact under every split mode (row-group shares, chunk-modulo
    fallback, parallel range readers) — what lets offset-addressed
    accumulators (the kmeans_sample reservoir) place rows identically
    at any process count.  The offset variant keys a DISTINCT cache
    stream: its cached tuples have four parts."""
    ldt = np.dtype(label_dtype) if label_dtype is not None else np.dtype(dtype)
    if readers is None:
        readers = resolve_parquet_readers(path)

    from .parallel.device_cache import (
        cached_chunk_stream,
        chunk_stream_complete,
    )
    from .streaming import _chunk_stream_key

    tag = ("fused+goff:" if with_offsets else "fused:") + ldt.str
    key = _chunk_stream_key(
        path, features_col, features_cols, label_col, weight_col,
        chunk_rows, dtype, None, tag=tag,
    )

    def _timed(it):
        if prep is None:
            return it
        from .parallel.mesh import timed_iter

        return timed_iter(it, prep)

    from .parallel.context import process_topology
    from .resilience.pod import active_recovery_plan, record_pass_manifest

    # the TOPOLOGY view, not jax.process_count(): after a rank loss the
    # pod layer shrinks the reduce group without tearing down the jax
    # backend, and the ingest partition must follow the survivors
    n_proc, pid = process_topology()

    plan = active_recovery_plan()
    plan_shares = (
        process_row_group_shares(path, plan.share_n)
        if plan is not None else None
    )
    if plan is not None and plan_shares is not None:
        # RESUME under a rank-loss recovery plan: this survivor decodes
        # the ORIGINAL share_n-way layout's shares the plan assigned it —
        # its own pre-loss share (same stream key as the interrupted
        # pass, so it replays from the chunk cache at epoch-2 cost) plus
        # any share inherited from a dead rank (cache miss on first
        # post-loss pass: parquet decode, cached for later passes).
        # Every row of the file is covered exactly once across the
        # survivors, which is all the commutative accumulators need for
        # byte parity with a fault-free fit.
        plan_starts = (
            _share_row_starts(path, plan_shares) if with_offsets else None
        )
        entries = plan.assignments.get(pid, ())
        record_pass_manifest(
            path=str(path), tag=tag, share_n=plan.share_n,
            generation=plan.generation,
            assignments={
                str(r): [list(e) for e in v]
                for r, v in plan.assignments.items()
            },
        )

        def _share_stream(share_idx: int, owner_boot: int):
            # keyed by the ORIGINAL topology slot (share_n, owner boot
            # rank): the survivor's own share reuses its pre-loss cache
            # entries byte-for-byte
            skey = _chunk_stream_key(
                path, features_col, features_cols, label_col,
                weight_col, chunk_rows, dtype, None, tag=tag,
                topology=(plan.share_n, owner_boot),
            )
            groups = plan_shares[share_idx]

            def _ssource():
                if not groups:
                    return iter(())
                base = (
                    plan_starts[share_idx] if with_offsets else None
                )
                return _range_chunks(
                    path, features_col, features_cols, label_col,
                    weight_col, chunk_rows, dtype, ldt, groups,
                    base_offset=base,
                )

            # ordered=True: a vanished spill blob mid-serve degrades to
            # source replay at the failed position instead of forcing a
            # restart of an already-part-folded recovery pass
            return cached_chunk_stream(
                skey, _ssource, device_elem=0, serve_device=True,
                ordered=True,
            )

        def _plan_chained():
            for share_idx, owner_boot in entries:
                yield from _share_stream(int(share_idx), int(owner_boot))

        yield from _timed(_plan_chained())
        return

    if n_proc > 1:
        # multi-host ingest partition: this process decodes ONLY its
        # deterministic row-group share (coverage-asserted); the
        # commutative accumulators make the resulting arbitrary global
        # chunk order irrelevant, and the per-rank chunk-stream key
        # keeps each host's cache holding only its own slice
        record_pass_manifest(
            path=str(path), tag=tag, share_n=n_proc, generation=None,
            assignments={str(pid): [[pid, pid]]},
        )
        shares = process_row_group_shares(path, n_proc)

        def _source():
            if shares is not None:
                if not shares[pid]:
                    return iter(())
                # global offset of the share's first row: prefix sum of
                # the row-group sizes ahead of it — every rank's chunks
                # land at the same indices a single-process scan gives
                base = (
                    _share_row_starts(path, shares)[pid]
                    if with_offsets else None
                )
                return _timed(_range_chunks(
                    path, features_col, features_cols, label_col,
                    weight_col, chunk_rows, dtype, ldt, shares[pid],
                    base_offset=base,
                ))

            # no row groups to split (directory dataset / single
            # group): every rank decodes the scan but FOLDS only
            # chunks congruent to its rank — disjoint exact cover,
            # no decode scaling.  The serial scan's own offset
            # tracking (base 0) is already global here.
            def _mod_filter():
                for i, item in enumerate(_range_chunks(
                    path, features_col, features_cols, label_col,
                    weight_col, chunk_rows, dtype, ldt, None,
                    base_offset=0 if with_offsets else None,
                )):
                    if i % n_proc == pid:
                        yield item

            return _timed(_mod_filter())

    else:
        def _source():
            return _parquet_reader_pool(
                path, features_col, features_cols, label_col, weight_col,
                chunk_rows, dtype, ldt, readers, _timed,
                with_offsets=with_offsets,
            )

    # NOTE: checked before iterating (benign race: a stream completed by
    # a concurrent fit in this window serves untimed; a mid-serve source
    # fallback would double-time the remainder — both observability-only
    # skews on rare interleavings, never data errors).  ordered=False:
    # the reader pool's merge order is nondeterministic, so a mid-serve
    # cache failure must restart the pass rather than position-resume
    served_from_cache = chunk_stream_complete(key) is not None
    stream = cached_chunk_stream(
        key, _source, device_elem=0, serve_device=True, ordered=False,
    )
    if served_from_cache:
        # replay: no reader threads run, so the serve cost is the prep
        stream = _timed(stream)
    yield from stream


def _parquet_reader_pool(
    path, features_col, features_cols, label_col, weight_col,
    chunk_rows, dtype, ldt, readers, _timed,
    with_offsets: bool = False,
):
    """The live (non-cached) fused producer: one in-order pruned reader,
    or `readers` parallel range-reader threads merged through a bounded
    queue.  With `with_offsets`, every reader carries its share's global
    first-row base, so the merged (arbitrary-order) stream still labels
    each chunk with its exact position in the file."""
    shares = _partition_row_groups(path, readers)
    if shares is None:
        yield from _timed(
            _range_chunks(
                path, features_col, features_cols, label_col, weight_col,
                chunk_rows, dtype, ldt, None,
                base_offset=0 if with_offsets else None,
            )
        )
        return

    import queue
    import threading
    q: "queue.Queue" = queue.Queue(maxsize=len(shares) + 1)
    _DONE = object()
    stop = threading.Event()

    def _put(item) -> bool:
        # bounded puts (the utils.prefetch_iter discipline): an abandoned
        # consumer must not pin reader threads + chunk copies forever
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _run(groups, base) -> None:
        try:
            # per-reader interval tracking shares the one `prep` dict:
            # "s" additions race benignly under the GIL (a lost update
            # drops a timing sample, never chunk data); list.append is
            # atomic
            for item in _timed(
                _range_chunks(
                    path, features_col, features_cols, label_col,
                    weight_col, chunk_rows, dtype, ldt, groups,
                    base_offset=base,
                )
            ):
                if not _put(item):
                    return
            _put(_DONE)
        except BaseException as e:  # surface reader errors on the consumer
            _put(e)

    starts = (
        _share_row_starts(path, shares) if with_offsets
        else [None] * len(shares)
    )
    threads = [
        threading.Thread(target=_run, args=(g, b), daemon=True)
        for g, b in zip(shares, starts)
    ]
    for t in threads:
        t.start()
    try:
        done = 0
        while done < len(threads):
            item = q.get()
            if item is _DONE:
                done += 1
                continue
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()


# The interval math this engine introduced is now owned by
# telemetry/utilization.py (the whole-run idle-gap attribution surface);
# these aliases keep the engine's (and stats/engine.py's) call sites —
# the overlap measure is unchanged: chunk-prep intervals (producer
# thread) intersected with device-busy intervals, so 'the solve ran
# inside the stage window' is read off the clock directly instead of
# inferred from duration sums (which a time-sliced single-core host
# systematically under-attributes).



def accumulate_chunks(
    acc: Dict[str, Any],
    step: Callable,
    chunks: Iterable,
    mesh,
    *,
    has_y: bool = False,
    extra_args: Tuple = (),
    prep: Optional[Dict[str, Any]] = None,
) -> Tuple[Dict[str, Any], Dict[str, float]]:
    """Drive one fused pass: fold every prepared host chunk into the
    donated device accumulator as it lands, with chunk prep running
    `staging_pipeline_depth` items ahead on a producer thread.

    `acc`/`step` come from `_acc_spec` (`step` is the CACHED
    (weighted, unweighted) jitted donated step pair — `_jitted_steps`);
    the accumulator replicates over
    `mesh`, each chunk is `device_put` row-SHARDED (one transfer per
    device — no GSPMD replication: the put happens outside any jitted
    program), and the jitted step's matmuls psum over the mesh.
    `extra_args` (e.g. the randomized range-finder's Omega) replicate
    once up front.

    Returns (host float64 stats with Kahan carries folded, pass metrics:
    wall_s/host_prep_s/device_acc_s/chunks/bytes)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from .ops.stats import acc_to_host_f64
    from .parallel.mesh import DATA_AXIS, _staging_depth, data_pspec, timed_iter
    from .resilience import maybe_inject
    from .telemetry.compile import compile_label
    from .utils import prefetch_iter

    if jax.process_count() > 1:
        # multi-process: fold on the LOCAL devices only — chunks and the
        # accumulator never leave this host, every collective in the
        # jitted step stays intra-process, and the per-rank partials
        # meet in ONE cross-process reduction at pass_complete below
        # (psum on collective-capable backends, the coordination-service
        # wire on CPU builds)
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(jax.local_devices()), (DATA_AXIS,))

    mat_sh = NamedSharding(mesh, data_pspec(2))
    row_sh = NamedSharding(mesh, PartitionSpec(DATA_AXIS))
    rep_sh = NamedSharding(mesh, PartitionSpec())

    acc = jax.device_put(acc, rep_sh)
    extra_dev = tuple(jax.device_put(a, rep_sh) for a in extra_args)
    step_w, step_unw = step if isinstance(step, tuple) else (step, None)

    # drift-baseline capture (monitor/baseline.py): when a collector is
    # armed for this fit, the decoded host chunks ALSO fold into the
    # baseline fingerprint — zero extra data passes, host tier only.
    # begin_pass resets a half-folded retried pass; pass_complete after
    # the loop freezes the capture so the later passes of a multi-pass
    # fit (randomized PCA re-streams) fold nothing
    from .monitor import baseline as _baseline
    from .stats.engine import _device_step_lock

    _baseline.begin_pass()
    # pod observatory (telemetry/fleet.py): one pod-global pass id per
    # accumulate pass — rank 0 mints, the broadcast seam distributes,
    # every rank's spans and reduce-wait intervals carry it until the
    # pass report closes below.  SPMD site, like begin_pass itself
    from .telemetry import fleet as _fleet

    _fleet.begin_pod_pass()

    t0 = time.perf_counter()
    # a producer that tracks its own prep (the parallel parquet readers)
    # passes the shared dict in; otherwise the chunk iterator is wrapped
    # here and prep time is measured on the consumer's pull
    self_timed = prep is not None
    if prep is None:
        prep = {"s": 0.0, "iv": []}
        chunks = timed_iter(chunks, prep)

    depth = _staging_depth()
    acc_s = 0.0
    acc_iv = []
    n_chunks = 0
    nbytes = 0
    # the accumulate is synced per chunk: the donated accumulator
    # serializes steps on device anyway, and the sync (a) bounds
    # in-flight device memory to one chunk + the accumulator and (b)
    # keeps device_acc_s honest — the producer thread keeps decoding the
    # NEXT chunks through the whole blocked window, which is exactly the
    # overlap the engine exists to create
    with compile_label("fused_stats"):
        for cX, cy, cw in prefetch_iter(chunks, depth):
            # the fused-path fault site: an injected OOM/device_lost here
            # fails the WHOLE pass, and the retry (core.py fused_fit
            # dispatch) restarts it with FRESH accumulators — re-creatable
            # state, never resumed mid-pass, so chunks cannot double-count
            maybe_inject("fused_accumulate")
            ta = time.perf_counter()
            # dispatch-to-sync under the shared one-pass statistics
            # device lock (stats/engine.py _device_step_lock):
            # concurrent mesh-sharded accumulator dispatches — a fused
            # fit racing another fused fit or a Summarizer pass — can
            # interleave per-device executions into a runtime deadlock;
            # the baseline fold rides inside the held region like the
            # engine's host sketches, overlapped with the async device
            # execution
            with _device_step_lock:
                args = [jax.device_put(cX, mat_sh)]
                if cw is not None:
                    args.append(jax.device_put(cw, row_sh))
                if has_y:
                    args.append(jax.device_put(cy, row_sh))
                args.extend(extra_dev)
                step_j = step_w if cw is not None else (step_unw or step_w)
                acc = step_j(acc, *args)
                _baseline.fold_chunk(cX, cw)
                jax.block_until_ready(acc)
            tb = time.perf_counter()
            acc_s += tb - ta
            acc_iv.append((ta, tb))
            n_chunks += 1
            nbytes += (
                cX.nbytes
                + (cw.nbytes if cw is not None else 0)
                + (cy.nbytes if has_y else 0)
            )
    _baseline.pass_complete()
    host = acc_to_host_f64(acc)
    from .parallel.context import process_topology

    if process_topology()[0] > 1:
        # the pass_complete reduction: one global fold of the per-rank
        # f64 partials (rank-agreement-checked); everything downstream —
        # finalize, the solve — sees the same global statistics a
        # single-process pass over the full data would produce.  Gated
        # on the TOPOLOGY view so a post-rank-loss survivor group of one
        # skips the reduce instead of waiting on the dead
        from .parallel.context import reduce_host_arrays

        host = reduce_host_arrays(host, "fused_pass")
    wall = time.perf_counter() - t0
    prep_iv = _merge_intervals(prep["iv"]) if self_timed else prep["iv"]
    # feed the run's utilization timeline (telemetry/utilization.py):
    # the same intervals the overlap fraction is computed from become
    # the fit report's device-busy / gap-attribution evidence
    from .telemetry import utilization

    utilization.note_intervals("device", acc_iv, cause="fused_accumulate")
    utilization.note_intervals("host_prep", prep_iv, cause="chunk_prep")
    # close the pod pass AFTER the intervals land: the straggler blob
    # is computed from the timeline, and its reduce_blob_list exchange
    # is the pass's last SPMD site (every rank reaches it after the
    # fold above succeeded)
    from .tracing import current_run_id

    _fleet.complete_pod_pass(run_id=current_run_id())
    return host, {
        "wall_s": wall,
        "host_prep_s": prep["s"],
        "device_acc_s": acc_s,
        "overlap_s": _interval_overlap_s(prep_iv, acc_iv),
        "chunks": n_chunks,
        "bytes": nbytes,
    }


def _record_metrics(
    label: str, kind: str, passes: int, totals: Dict[str, float],
    solver: Optional[str] = None,
) -> None:
    """Fold one fused fit's (possibly multi-pass) totals into
    `FUSED_METRICS` + a trace event.  overlap_s is the measured
    wall-clock intersection of the chunk-prep intervals (producer
    thread) with the device-busy intervals (`_interval_overlap_s`);
    overlap_fraction normalizes it by the smaller phase (1.0 = the
    cheaper phase ran entirely inside the other's window)."""
    wall = totals.get("wall_s", 0.0)
    prep_s = totals.get("host_prep_s", 0.0)
    acc_s = totals.get("device_acc_s", 0.0)
    overlap_s = max(totals.get("overlap_s", 0.0), 0.0)
    overlap = 0.0
    if min(prep_s, acc_s) > 1e-9:
        overlap = max(0.0, min(overlap_s / min(prep_s, acc_s), 1.0))
    FUSED_METRICS.clear()
    FUSED_METRICS.update(
        stamp=round(time.time(), 3),
        label=label,
        kind=kind,
        passes=int(passes),
        chunks=int(totals.get("chunks", 0)),
        bytes=int(totals.get("bytes", 0)),
        wall_s=round(wall, 4),
        host_prep_s=round(prep_s, 4),
        device_acc_s=round(acc_s, 4),
        overlap_s=round(overlap_s, 4),
        overlap_fraction=round(overlap, 4),
    )
    if solver is not None:
        FUSED_METRICS["solver"] = solver
    from .tracing import event

    event(
        f"fused_stats[{label}]",
        detail=(
            f"{kind} passes={passes} chunks={totals.get('chunks', 0)} "
            f"{totals.get('bytes', 0) / 1e6:.1f}MB wall={wall:.2f}s "
            f"overlap={overlap:.2f}"
        ),
    )


def _merge_totals(totals: Dict[str, float], m: Dict[str, float]) -> None:
    for k, v in m.items():
        totals[k] = totals.get(k, 0.0) + v


def _resolve_producer(produced):
    """A producer factory returns either a plain chunk iterable (the
    engine times prep on its pull) or `(iterable, prep_dict)` when the
    producer tracks its own decode time (the parallel parquet
    readers)."""
    if isinstance(produced, tuple):
        return produced
    return produced, None


def fused_linreg_stats(
    producer_factory: Callable[[int], Iterable],
    d: int,
    dtype,
    label: str = "linreg",
) -> Dict[str, Any]:
    """One fused pass of the weighted Gram/moment/cross statistics
    (ops/stats.py `linreg_acc`).  `producer_factory(n_dev)` yields
    prepared `(X, y, w)` chunks.  Returns host float64 stats in the
    exact shape `LinearRegression._attrs_from_stats` consumes."""
    from .parallel.mesh import get_mesh

    dtype = np.dtype(dtype)
    mesh = get_mesh()
    acc, step = _acc_spec("linreg", d, 0, dtype)
    chunks, prep = _resolve_producer(producer_factory(mesh.devices.size))
    host, m = accumulate_chunks(
        acc, step, chunks, mesh, has_y=True, prep=prep,
    )
    _record_metrics(label, "linreg", 1, m)
    return host


def fused_pca_stats(
    producer_factory: Callable[[int], Iterable],
    d: int,
    k: int,
    dtype,
    label: str = "pca",
) -> Dict[str, Any]:
    """Fused PCA statistics with solver dispatch (ops/pca.py
    `resolve_pca_solver`):

    - "full": one pass of the exact second moments ->
      {"kind": "moments", "S", "s1", "sw"} (the shape
      `PCA._attrs_from_moments` consumes).
    - "randomized": the Halko range-finder run STAGE-OVERLAPPED — each
      tall-skinny product (sketch, power iterations, final projection)
      is one fused O(n d l) pass re-streamed through
      `producer_factory` -> {"kind": "projected", "Q", "SQ", "s1",
      "ssq", "sw"} for `ops.pca.pca_attrs_from_projected`.

    `producer_factory(n_dev)` must return a FRESH chunk iterator per
    call (multi-pass re-reads the source)."""
    from .ops.pca import resolve_pca_solver
    from .parallel.mesh import get_mesh

    dtype = np.dtype(dtype)
    mesh = get_mesh()
    n_dev = mesh.devices.size
    solver, l, power_iters, _reason = resolve_pca_solver(d, k, streamed=True)
    if solver == "full":
        acc, step = _acc_spec("pca_moments", d, 0, dtype)
        chunks, prep = _resolve_producer(producer_factory(n_dev))
        host, m = accumulate_chunks(acc, step, chunks, mesh, prep=prep)
        _record_metrics(label, "pca_moments", 1, m, solver="full")
        host["kind"] = "moments"
        return host

    totals: Dict[str, float] = {}

    def projected_pass(omega: np.ndarray) -> Dict[str, Any]:
        acc, step = _acc_spec("pca_projected", d, l, dtype)
        chunks, prep = _resolve_producer(producer_factory(n_dev))
        host, m = accumulate_chunks(
            acc, step, chunks, mesh,
            extra_args=(np.asarray(omega, dtype),), prep=prep,
        )
        _merge_totals(totals, m)
        return host

    # deterministic sketch (same data -> same components across refits)
    omega = np.random.default_rng(0).standard_normal((d, l)).astype(dtype)
    st = projected_pass(omega)
    sw = float(st["sw"])
    mean = st["s1"] / sw

    def centered(SOm: np.ndarray, om: np.ndarray) -> np.ndarray:
        # (A^T A) om from the raw projected moments: Σ w x (xᵀom) −
        # sw·mean·(meanᵀom)
        return np.asarray(SOm, np.float64) - sw * np.outer(mean, mean @ om)

    Y = centered(st["SOm"], omega)
    for _ in range(power_iters):
        Q, _r = np.linalg.qr(Y)
        Y = centered(projected_pass(Q.astype(dtype))["SOm"], Q)
    Q, _r = np.linalg.qr(Y)
    final = projected_pass(Q.astype(dtype))
    passes = 2 + power_iters
    _record_metrics(label, "pca_projected", passes, totals, solver="randomized")
    return {
        "kind": "projected",
        "Q": Q,
        "SQ": final["SOm"],
        "s1": final["s1"],
        "ssq": final["ssq"],
        "sw": final["sw"],
        "k": k,
    }
