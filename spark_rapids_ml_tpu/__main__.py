#
# `python -m spark_rapids_ml_tpu script.py [args...]` — the analog of
# reference __main__.py (63 LoC, cudf.pandas-style runner): installs the
# zero-import-change accelerator, then executes the target script (or -m
# module) unmodified with TPU-backed estimators in place of sklearn's.
#
from __future__ import annotations

import runpy
import sys


_USAGE = (
    "usage: python -m spark_rapids_ml_tpu [--pyspark] (script.py | -m module)"
    " [args...]\n"
    "Run a Python script with sklearn (default) or pyspark.ml (--pyspark,\n"
    "the spark-rapids-ml-tpu-submit driver mode) transparently accelerated\n"
    "by spark_rapids_ml_tpu (reference: python -m spark_rapids_ml)."
)


def main() -> None:
    # manual parsing (argparse would claim the target's own -x/--x options)
    argv = sys.argv[1:]
    if not argv or argv[0] in ("-h", "--help"):
        print(_USAGE)
        raise SystemExit(0 if argv else 2)

    if argv[0] == "--pyspark":
        argv = argv[1:]
        if not argv:
            print(_USAGE)
            raise SystemExit(2)
        from .spark_interop import install as install_pyspark

        install_pyspark()
    else:
        from .install import install

        install()

    if argv[0] == "-m":
        if len(argv) < 2:
            print(_USAGE)
            raise SystemExit(2)
        module, rest = argv[1], argv[2:]
        sys.argv[:] = [module] + rest
        runpy.run_module(module, run_name="__main__", alter_sys=True)
    else:
        script, rest = argv[0], argv[1:]
        sys.argv[:] = [script] + rest
        runpy.run_path(script, run_name="__main__")


if __name__ == "__main__":
    main()
