#
# Retry policies — declarative recovery for dispatch failures.  One
# classifier set replaces the scattered hand-rolled handlers (the inline
# `_is_oom` special case in core.py, the per-site halving loops): every
# failure maps to an ACTION, and the action — not the call site — decides
# the recovery:
#
#   oom         drop the poisoned buffers (site hook: shrink the batch /
#               gc the staged arrays) and re-dispatch
#   transient   RPC/DEADLINE/tunnel errors: exponential backoff + jitter,
#               then re-dispatch
#   preemption  a TPU worker went away: re-init `jax.distributed`
#               (parallel/context.py `reinit_distributed`) and resume —
#               iterative solvers pick their checkpoint back up
#               (resilience/checkpoint.py)
#   device_loss one or more DEVICES vanished but the process lives: the
#               elastic recovery layer (resilience/elastic.py) shrinks
#               the mesh to the survivors, the caller re-stages, and
#               checkpointed solvers resume at iteration k on the
#               smaller mesh (falls back to the preemption repair when
#               elastic is off / too few survivors)
#   rank_loss   a peer PROCESS died mid-reduction (typed RankLost /
#               ReduceTimeout from the pod layer's bounded waits): with
#               `pod_elastic` on, resilience/pod.py shrinks the quorum
#               to the survivors under a bumped generation and the pass
#               restarts on the reassigned share layout; with it off the
#               typed error is FATAL — bounded timeout, then propagate
#   fatal       everything else propagates unchanged on the FIRST raise
#
from __future__ import annotations

import gc
import random
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple

from ..config import get_config
from ..telemetry.registry import counter as _counter
from ..utils import get_logger

logger = get_logger("spark_rapids_ml_tpu.resilience")

# one counter family for every policy-driven recovery, labeled by the
# dispatch site and the classified action — the queryable form of the
# `retry[<label>]` trace events (core.py's inline transform retry loop
# bumps the same family so the two paths never diverge in the metrics)
RETRIES = _counter(
    "retries_total", "Policy-driven dispatch retries by site and action"
)


def is_oom(e: BaseException) -> bool:
    """XLA device-memory exhaustion (moved from core.py `_is_oom`)."""
    s = str(e)
    return (
        "RESOURCE_EXHAUSTED" in s
        or "Out of memory" in s
        or "out of memory" in s
    )


def is_preemption(e: BaseException) -> bool:
    """A TPU worker/coordinator went away mid-fit (maintenance event,
    spot reclaim): the runtime must re-bootstrap before any retry.

    Beyond the obvious 'preempted' strings, the coordination service
    surfaces worker death as status-code / transport errors that never
    say "preempted": `DATA_LOSS` (a restarted worker lost its state),
    heartbeat timeouts ('... heartbeat timed out' / 'Heartbeat request
    failed'), and the coordination channel's socket closing under it.
    Each of those is pinned by a test (tests/test_resilience.py).  Plain
    user RuntimeErrors that merely mention sockets stay in the
    `transient` family, and everything unmatched stays fatal."""
    from .faults import SimulatedPreemption

    if isinstance(e, SimulatedPreemption):
        return True
    s = str(e)
    low = s.lower()
    return (
        "preempted" in s
        or "PREEMPTED" in s
        or "DATA_LOSS" in s
        or "coordinator disconnected" in s
        or "worker has been restarted" in s
        or ("heartbeat" in low and ("timed out" in low or "failed" in low))
        or ("coordination" in low and "socket closed" in low)
    )


def is_device_loss(e: BaseException) -> bool:
    """One or more DEVICES vanished mid-execution (spot reclaim of a
    worker's chips, an ICI/PCIe failure) — distinct from a whole-worker
    preemption because the surviving devices can keep working: the
    elastic recovery layer (resilience/elastic.py) shrinks the mesh and
    resumes instead of blind-retrying.  Matches the typed
    `parallel.context.DeviceLoss` (duck-typed on `lost_devices`, so this
    module never imports jax) and runtime errors that name a DEVICE as
    lost or invalid ('INTERNAL: failed to execute XLA Runtime
    executable: device N has been lost', 'device is in an invalid
    state').  Deliberately NOT a match on 'failed to execute' alone:
    that wrapper also carries deterministic internal failures (a custom
    call rejecting, a lowering bug), which must stay fatal on the first
    raise rather than burn retry rounds re-bootstrapping a healthy
    runtime.  The misclassification that remains possible (a transient
    error naming a 'lost device') is recoverable: the health probe finds
    every device answering and the recovery falls back."""
    if getattr(e, "lost_devices", None) is not None:
        return True
    low = str(e).lower()
    return "device" in low and (
        "lost" in low or "is in an invalid state" in low
    )


def is_remote_compile_flake(e: BaseException) -> bool:
    """Transient failure of the tunneled compile service itself: the
    remote_compile RPC answering HTTP 5xx / resetting mid-flight
    (`JaxRuntimeError: INTERNAL: ... remote_compile: HTTP 500` killed the
    r05 UMAP bench on the FIRST dispatch of a fresh program).  These are
    server-side flakes — the same program compiles fine seconds later —
    so they classify as 'transient' (backoff + re-dispatch), NOT fatal.
    A remote_compile failure that is the compiler rejecting the program
    (HTTP 4xx, lowering errors) stays fatal: retrying a genuinely
    uncompilable program would just burn the backoff budget.  Note the
    match is on the flake MARKERS, never on the bare 'INTERNAL:' status
    prefix — JaxRuntimeError stamps that prefix on deterministic
    rejections too ('INTERNAL: ... remote_compile: HTTP 400'), which must
    stay fatal."""
    s = str(e)
    if "remote_compile" not in s and "remote compile" not in s:
        return False
    return (
        "HTTP 5" in s
        or "UNAVAILABLE" in s
        or "Connection reset" in s
        or "Socket closed" in s
        or "timed out" in s
    )


def is_transient(e: BaseException) -> bool:
    """Retryable without state repair: tunnel/RPC deadline and
    availability errors, including the guard's typed DispatchTimeout and
    remote-compile service flakes."""
    from .guard import DispatchTimeout

    if isinstance(e, DispatchTimeout):
        return True
    if is_remote_compile_flake(e):
        return True
    s = str(e)
    return (
        "DEADLINE_EXCEEDED" in s
        or "UNAVAILABLE" in s
        or "Socket closed" in s
        or "RPC failed" in s
        or "Connection reset" in s
    )


def is_rank_loss(e: BaseException) -> bool:
    """A typed pod-layer failure: a peer PROCESS declared dead
    (`RankLost`) or a bounded cross-process wait that expired
    (`ReduceTimeout`).  Both come from resilience/pod.py's `kv_wait`
    seam — string matching is unnecessary, the types are ours."""
    from .pod import RankLost, ReduceTimeout

    return isinstance(e, (RankLost, ReduceTimeout))


def classify_error(e: BaseException) -> str:
    """Map an exception to its recovery action: 'rank_loss' |
    'device_loss' | 'preemption' | 'oom' | 'transient' | 'fatal'.
    Rank loss classifies FIRST — the exceptions are typed, and their
    messages deliberately carry DEADLINE/lost markers that the string
    classifiers below would mis-route.  With `pod_elastic` off the same
    typed errors are FATAL: the bounded timeout already did its job
    (never hang), and there is no recovery to drive."""
    if is_rank_loss(e):
        from .pod import pod_elastic_enabled

        return "rank_loss" if pod_elastic_enabled() else "fatal"
    if is_device_loss(e):
        return "device_loss"
    if is_preemption(e):
        return "preemption"
    if is_oom(e):
        return "oom"
    if is_transient(e):
        return "transient"
    return "fatal"


def _default_oom_hook() -> None:
    # free the failed dispatch's temporaries before re-dispatching; the
    # caller's staged inputs (deliberately still referenced) survive
    gc.collect()


def _default_device_loss_hook() -> None:
    # the elastic state machine (resilience/elastic.py): shrink the mesh
    # to the survivors when allowed, else fall back to the preemption
    # repair — either way the retry loop re-dispatches afterwards.
    # Callers whose inputs must move to the degraded mesh (core.py
    # _run_fit_kernel) pass their own hook that ALSO re-stages.
    from .elastic import recover_from_device_loss

    recover_from_device_loss(logger)


def _default_rank_loss_hook(exc: Optional[BaseException] = None) -> None:
    # the pod recovery state machine (resilience/pod.py): shrink the
    # quorum to the survivors under a bumped generation when a dead rank
    # is identifiable, else fall back to the preemption repair (a
    # straggler timeout or a dead coordinator — only a full re-bootstrap
    # can help).  Either way the retry loop re-dispatches afterwards and
    # the pass restarts with fresh accumulators.
    from .pod import recover_from_rank_loss

    if not recover_from_rank_loss(exc, log=logger):
        _default_preemption_hook()


def _default_preemption_hook() -> None:
    # best-effort: on a single-controller process whose XLA backend is
    # already live, re-bootstrapping jax.distributed may itself fail (the
    # runtime only accepts distributed init before backend init on some
    # versions).  The retry must then still run — a failed repair must
    # surface the ORIGINAL preemption on the next attempt, not a
    # confusing bootstrap error from inside the hook.
    from ..parallel.context import reinit_distributed

    try:
        reinit_distributed()
    except Exception as e:
        logger.warning(
            f"jax.distributed re-init after preemption failed ({e}); "
            "retrying on the existing runtime"
        )


@dataclass
class RetryPolicy:
    """Declarative retry: total attempts, exponential backoff + jitter,
    and the retryable-action set.  `classify` maps an exception to an
    action name; actions outside `retryable` (and 'fatal') propagate."""

    max_attempts: int = 3
    backoff_s: float = 0.5
    backoff_mult: float = 2.0
    jitter: float = 0.25
    classify: Callable[[BaseException], str] = classify_error
    retryable: Tuple[str, ...] = (
        "oom", "transient", "preemption", "device_loss", "rank_loss",
    )
    # OOM gets a TIGHTER budget than max_attempts: one gc'd re-dispatch
    # recovers fragmentation/injected faults, but a dataset that genuinely
    # exceeds HBM fails every attempt after minutes of device work each —
    # the caller's fallback (e.g. _stage_or_stream's streamed-statistics
    # path) must engage after a single repair attempt, not attempt N
    oom_attempts: int = 1

    @classmethod
    def from_config(cls) -> "RetryPolicy":
        return cls(
            max_attempts=int(get_config("retry_max_attempts")),
            backoff_s=float(get_config("retry_backoff_s")),
            backoff_mult=float(get_config("retry_backoff_mult")),
            jitter=float(get_config("retry_jitter")),
        )

    def backoff(self, attempt: int) -> float:
        """Delay before retry number `attempt` (1-based)."""
        delay = self.backoff_s * self.backoff_mult ** (attempt - 1)
        return delay * (1.0 + random.uniform(0.0, self.jitter))


def retry_call(
    fn: Callable[[], Any],
    label: str = "dispatch",
    policy: Optional[RetryPolicy] = None,
    log: Optional[object] = None,
    on_oom: Optional[Callable[[], None]] = None,
    on_preemption: Optional[Callable[[], None]] = None,
    on_device_loss: Optional[Callable[[], None]] = None,
    on_rank_loss: Optional[Callable[[], None]] = None,
) -> Any:
    """Run `fn` under `policy` (default: `RetryPolicy.from_config()`).

    Each recovery is surfaced as a `retry[label]` trace event.  `on_oom` /
    `on_preemption` / `on_device_loss` / `on_rank_loss` override the
    default repair hooks (gc-collect / `reinit_distributed` / the elastic
    mesh recovery / the pod quorum shrink — resilience/elastic.py and
    resilience/pod.py).  Callers whose recovery mutates loop state the
    policy cannot see (the transform chunk loop in core.py: chunk halving,
    resume-row tracking across a pipelined pending dispatch) apply the
    SAME policy — `RetryPolicy.from_config()`, `classify`, `backoff`, and
    `_default_preemption_hook` — inline instead of through this wrapper,
    so classification and attempt semantics never diverge.
    """
    if policy is None:
        policy = RetryPolicy.from_config()
    lg = log or logger
    attempt = 1
    oom_count = 0
    while True:
        action = None
        err_desc = ""
        rank_loss_exc = None
        try:
            return fn()
        except Exception as e:
            action = policy.classify(e)
            if (
                action == "fatal"
                or action not in policy.retryable
                or attempt >= policy.max_attempts
                or (action == "oom" and oom_count >= policy.oom_attempts)
            ):
                if action != "fatal" and action in policy.retryable:
                    # a RECOVERABLE failure class exhausted its attempt
                    # budget — the fit is about to die with its evidence:
                    # dump the flight-recorder black box before the raise
                    # (fatal errors propagate on the FIRST raise and are
                    # the caller's bug to read from the traceback)
                    from ..telemetry.flight_recorder import note_failure

                    note_failure(
                        "retry_exhausted",
                        detail=(
                            f"label={label} action={action} "
                            f"attempt={attempt} "
                            f"error={type(e).__name__}: {e}"
                        ),
                        log=lg,
                    )
                raise
            err_desc = f"{type(e).__name__}: {e}"
            if action == "rank_loss":
                # the recovery hook needs the typed exception (it names
                # the dead ranks); safe to carry outside the except
                # block — pod errors are host-side, their tracebacks pin
                # no device buffers
                rank_loss_exc = e
        # the retry runs OUTSIDE the except block: while handling, the
        # interpreter's exception state pins the failed dispatch's frames
        # via the traceback, whose locals reference the device buffers we
        # are trying to free (the poisoned-buffer lesson recorded at
        # core.py _stage_or_stream / BENCH_r05) — leaving the block pops
        # the exception and releases them before the repair hook runs
        from ..tracing import event

        RETRIES.inc(label=label, action=action)
        event(
            f"retry[{label}]",
            detail=f"attempt={attempt} action={action}",
            log=lg,
        )
        lg.warning(
            f"Dispatch '{label}' failed ({err_desc}); recovery={action}, "
            f"attempt {attempt + 1}/{policy.max_attempts}"
        )
        if action == "oom":
            oom_count += 1
            (on_oom or _default_oom_hook)()
        elif action == "preemption":
            (on_preemption or _default_preemption_hook)()
        elif action == "device_loss":
            (on_device_loss or _default_device_loss_hook)()
        elif action == "rank_loss":
            if on_rank_loss is not None:
                on_rank_loss()
            else:
                _default_rank_loss_hook(rank_loss_exc)
        else:  # transient
            time.sleep(policy.backoff(attempt))
        attempt += 1
