#
# Pod-scale fault domain — rank-loss detection, quorum shrink, and pass
# resume.  PR 17-18 made fit-time ingest process-parallel with a single
# pass-complete reduction (parallel/context.py), but a rank that died
# mid-pass left every survivor blocked inside `allgather_bytes` on KV
# keys that would never arrive.  This module lifts the single-process
# elastic contract (resilience/elastic.py: detect -> shrink -> resume)
# to the pod:
#
#   DETECT   every cross-process wait routes through `kv_wait`, a
#            bounded deadline honoring `multiproc_reduce_timeout_s` that
#            raises typed `ReduceTimeout`/`RankLost` instead of hanging.
#            A per-rank liveness heartbeat in the coordination-service
#            KV namespace (`srmt/hb/<rank>/<n>`, monotonic keys because
#            the KV store is write-once) lets survivors name WHICH rank
#            died, and the `pod_death_grace_s` straggler grace
#            distinguishes dead-rank from slow-rank: a peer that still
#            heartbeats is waited on to the full deadline.
#   SHRINK   `recover_from_rank_loss` bumps the reduction GENERATION
#            (every KV key is generation-prefixed, so a zombie rank's
#            delayed writes land in the dead generation's namespace and
#            are never merged — no split brain), clears the per-tag
#            sequence counters, and installs a surviving-quorum topology
#            override (parallel/context.py `process_topology`) under
#            which the dead rank's row-group shares are deterministically
#            reassigned across survivors (fused.py consumes the
#            `RecoveryPlan`).
#   RESUME   the retry loop restarts the interrupted pass with fresh
#            accumulators on the new share layout (restart-not-double-
#            count, the same contract as every fused fault site);
#            survivors replay their OWN shares from the chunk cache at
#            epoch-2 cost while only the reassigned shares pay parquet;
#            checkpointed solvers resume at iteration k exactly as
#            single-process elastic does.
#
# Gated behind the `pod_elastic` conf: off restores the prior behavior
# — a bounded, typed timeout and then a fatal classification, never a
# hang.  The whole state machine is drivable on one box via the
# `rank_lost`/`kv_timeout` fault kinds (faults.py), which follow the
# `device_lost` simulated-loss pattern: `simulate_rank_loss` installs an
# implicit 2-rank simulated topology when run single-process.
#
# Like the rest of the resilience layer, no jax/numpy at module scope.
#
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from ..config import get_config
from ..telemetry.locks import named_lock
from ..telemetry.registry import dict_view as _dict_view
from ..utils import get_logger

logger = get_logger("spark_rapids_ml_tpu.resilience")

_lock = named_lock("pod_state")

# cumulative pod-recovery counters (tests, the chaos smoke, operators):
#   rank_losses_detected  peer ranks declared dead (liveness or typed)
#   shares_reassigned     row-group shares moved off dead ranks
#   pod_recoveries_total  successful shrink-to-survivors recoveries
#   reduce_timeouts       bounded cross-process waits that expired
#   generation            the current reduction generation number
POD_METRICS = _dict_view(
    "pod_recovery",
    "Pod rank-loss recovery counters (losses/reassignment/generation)",
    initial={
        "rank_losses_detected": 0,
        "shares_reassigned": 0,
        "pod_recoveries_total": 0,
        "reduce_timeouts": 0,
        "generation": 0,
    },
)

# how long a single KV probe for a peer's next heartbeat key blocks: one
# miss per dead peer per liveness check, so this stays small
_HB_PROBE_MS = 50


class ReduceTimeout(RuntimeError):
    """A bounded cross-process wait expired: the peer's KV payload (or
    the psum dispatch) never arrived within `multiproc_reduce_timeout_s`.
    Typed — carrying the reduce tag, the KV key, and the waited time —
    so the retry classifier can route it (pod_elastic on: liveness-
    driven recovery; off: fatal) instead of the pass hanging forever."""

    def __init__(self, tag: str, key: str = "", waited_s: float = 0.0) -> None:
        self.tag = tag
        self.key = key
        self.waited_s = float(waited_s)
        super().__init__(
            f"cross-process reduce {tag!r} timed out after "
            f"{self.waited_s:.1f}s waiting on {key or tag!r} "
            "(DEADLINE_EXCEEDED); peer slow, dead, or diverged — see "
            "multiproc_reduce_timeout_s"
        )


class RankLost(RuntimeError):
    """One or more peer PROCESSES are gone mid-pass (their liveness
    heartbeat stopped for longer than `pod_death_grace_s`, or the loss
    was injected).  Typed — carrying the lost boot ranks and the
    generation they died under — so `recover_from_rank_loss` can shrink
    the quorum to the survivors instead of treating the failure as an
    opaque crash.  `lost_ranks` are BOOT ranks (the jax.distributed
    process ids), stable across topology shrinks."""

    def __init__(
        self, lost_ranks, tag: str = "", generation: int = 0
    ) -> None:
        self.lost_ranks = sorted(int(r) for r in lost_ranks)
        self.tag = tag
        self.generation = int(generation)
        super().__init__(
            f"rank(s) {self.lost_ranks} lost during cross-process "
            f"reduce {tag!r} (generation {self.generation}): liveness "
            "heartbeat stopped past pod_death_grace_s — peer process is "
            "dead, not slow"
        )


class RecoveryPlan:
    """The shrink decision, consumed by the data path (fused.py): which
    row-group SHARES (indices under the original `share_n`-way
    `process_row_group_shares` partition) this process must cover on the
    recovered pass, and which cache identity each share can replay from.

    `assignments[new_rank]` is a tuple of `(share_idx, owner_boot_rank)`
    entries: a survivor's own share keeps its original owner (so the
    chunk cache replays it at epoch-2 cost); a reassigned share keeps
    the DEAD owner's identity — the local cache has no stream under it,
    so the first recovered pass decodes parquet and caches it for
    epochs 2+.  `boot_ranks[new_rank]` maps post-shrink topology ranks
    back to jax.distributed process ids (heartbeat identity)."""

    __slots__ = (
        "prior_n",
        "prior_rank",
        "dead_ranks",
        "survivors",
        "boot_ranks",
        "share_n",
        "assignments",
        "generation",
    )

    def __init__(
        self,
        prior_n: int,
        prior_rank: int,
        dead_ranks: Tuple[int, ...],
        survivors: Tuple[int, ...],
        boot_ranks: Tuple[int, ...],
        share_n: int,
        assignments: Dict[int, Tuple[Tuple[int, int], ...]],
        generation: int,
    ) -> None:
        self.prior_n = int(prior_n)
        self.prior_rank = int(prior_rank)
        self.dead_ranks = tuple(int(r) for r in dead_ranks)
        self.survivors = tuple(int(r) for r in survivors)
        self.boot_ranks = tuple(int(r) for r in boot_ranks)
        self.share_n = int(share_n)
        self.assignments = {
            int(k): tuple((int(s), int(o)) for s, o in v)
            for k, v in assignments.items()
        }
        self.generation = int(generation)

    def as_dict(self) -> Dict:
        return {
            "prior_n": self.prior_n,
            "prior_rank": self.prior_rank,
            "dead_ranks": list(self.dead_ranks),
            "survivors": list(self.survivors),
            "boot_ranks": list(self.boot_ranks),
            "share_n": self.share_n,
            "assignments": {
                str(k): [list(e) for e in v]
                for k, v in self.assignments.items()
            },
            "generation": self.generation,
        }


_generation = 0
_active_plan: Optional[RecoveryPlan] = None
_sim_dead: set = set()
_pass_manifest: Dict = {}

# liveness bookkeeping: per-peer next-unseen heartbeat index, and the
# monotonic time each peer's beat was last observed to ADVANCE (seeded
# at first probe, so a rank killed before its first beat still ages out
# after the grace window)
_hb_next: Dict[int, int] = {}
_hb_seen: Dict[int, float] = {}
_hb_thread: Optional[threading.Thread] = None
_hb_stop: Optional[threading.Event] = None

# in-flight cross-process waits by thread id, for the hang doctor's
# stall attribution (which reduce tag, which peer rank) and the
# `reduce_wait` utilization intervals
_live_waits: Dict[int, Dict] = {}


def pod_elastic_enabled() -> bool:
    return str(get_config("pod_elastic")).lower() == "on"


def heartbeat_interval_s() -> float:
    return max(0.05, float(get_config("pod_heartbeat_interval_s")))


def death_grace_s() -> float:
    return max(0.1, float(get_config("pod_death_grace_s")))


def generation() -> int:
    """The current reduction generation.  Every coordination-service KV
    key is prefixed with it (parallel/context.py), so payloads written
    by a rank that died under generation g are invisible to the quorum
    recovered under g+1 — zombie-rank partials can never split-brain
    into a recovered pass."""
    with _lock:
        return _generation


def advance_generation(reason: str = "") -> int:
    """Bump the reduction generation and reset the per-tag KV sequence
    counters: the recovered quorum starts a fresh, disjoint key
    namespace.  Called by `recover_from_rank_loss` and by every
    `reinit_distributed` re-bootstrap."""
    global _generation
    with _lock:
        _generation += 1
        gen = _generation
        POD_METRICS["generation"] = gen
    try:
        from ..parallel.context import reset_kv_epoch

        reset_kv_epoch()
    except Exception:  # pragma: no cover - import-order defensive
        pass
    from ..tracing import event

    event(
        "pod_recovery[generation]",
        detail=f"gen={gen} reason={reason}",
        log=logger,
    )
    return gen


def active_recovery_plan() -> Optional[RecoveryPlan]:
    with _lock:
        return _active_plan


def record_pass_manifest(**fields) -> None:
    """Data-path breadcrumbs (path, share layout, generation) updated by
    `iter_parquet_chunks` at pass start; attached verbatim to the
    `reason="rank_loss"` flight-recorder bundle so the operator can see
    WHAT pass the pod was in when the rank died."""
    with _lock:
        _pass_manifest.update(fields)


def pass_manifest() -> Dict:
    with _lock:
        return dict(_pass_manifest)


def simulated_dead_ranks() -> frozenset:
    with _lock:
        return frozenset(_sim_dead)


def _current_boot_ranks() -> List[int]:
    """Topology-rank -> boot-rank map for the CURRENT effective
    topology: the plan's surviving boot ranks after a recovery, the
    identity range under a plain (or simulated) override, the jax view
    otherwise."""
    plan = active_recovery_plan()
    if plan is not None:
        return list(plan.boot_ranks)
    from ..parallel.context import process_topology, topology_overridden

    n, _ = process_topology()
    if topology_overridden():
        return list(range(n))
    import jax

    return list(range(jax.process_count()))


def _my_boot_rank() -> int:
    from ..parallel.context import process_topology

    boots = _current_boot_ranks()
    _, rank = process_topology()
    return boots[rank] if rank < len(boots) else int(rank)


# ---------------------------------------------------------------------------
# Liveness heartbeat
# ---------------------------------------------------------------------------


def _hb_loop(client, boot_rank: int, stop: threading.Event) -> None:
    n = 0
    while not stop.is_set():
        try:
            # the KV store is write-once across the jaxlib versions we
            # support, so the beat is a monotonic KEY, not a mutated
            # value.  The VALUE is the sender's wall clock at write time:
            # liveness only checks key existence (any value works), while
            # the pod trace merger (telemetry/fleet.py) reads it as a
            # clock-offset sample — (send stamp, receive stamp) pairs
            # bound each peer's skew to within one heartbeat interval.
            client.key_value_set(
                f"srmt/hb/{boot_rank}/{n}", repr(time.time())
            )
            n += 1
        except Exception:  # pragma: no cover - client teardown races
            pass
        stop.wait(heartbeat_interval_s())


def maybe_start_heartbeat() -> bool:
    """Start this rank's liveness publisher (idempotent).  No-op when
    `pod_elastic` is off, single-process, or outside distributed mode.
    Called from `init_distributed` and from every allgather, so a rank
    beats from bootstrap — a peer killed before its FIRST reduction is
    still detectable."""
    global _hb_thread, _hb_stop
    if not pod_elastic_enabled():
        return False
    with _lock:
        if _hb_thread is not None and _hb_thread.is_alive():
            return True
    import jax

    if jax.process_count() <= 1:
        return False
    from ..parallel.context import _coordination_client

    client = _coordination_client()
    if client is None:
        return False
    boot = int(jax.process_index())
    stop = threading.Event()
    t = threading.Thread(
        target=_hb_loop, args=(client, boot, stop),
        name="pod-heartbeat", daemon=True,
    )
    with _lock:
        if _hb_thread is not None and _hb_thread.is_alive():
            return True
        _hb_thread, _hb_stop = t, stop
    t.start()
    return True


def stop_heartbeat() -> None:
    global _hb_thread, _hb_stop
    with _lock:
        t, stop = _hb_thread, _hb_stop
        _hb_thread = _hb_stop = None
    if stop is not None:
        stop.set()
    if t is not None and t.is_alive():
        t.join(timeout=1.0)


def _probe_liveness(client, boot_ranks, my_boot: int) -> Dict[int, float]:
    """Advance the last-seen table by draining each peer's new heartbeat
    keys (tiny bounded gets); returns seconds since each peer's beat
    last advanced.  A peer never probed before is seeded NOW, so its
    grace window starts at first suspicion, not at minus infinity."""
    now = time.monotonic()
    ages: Dict[int, float] = {}
    for r in boot_ranks:
        if r == my_boot:
            continue
        with _lock:
            nxt = _hb_next.get(r, 0)
        advanced = False
        while True:
            try:
                beat = client.blocking_key_value_get(
                    f"srmt/hb/{r}/{nxt}", _HB_PROBE_MS
                )
            except Exception:
                break
            nxt += 1
            advanced = True
            # the beat value is the sender's wall clock at write time
            # (see _hb_loop): feed it to the fleet clock-offset
            # estimator.  Legacy "1" values (pre-timestamp peers) parse
            # as implausible and are rejected there; never raises.
            try:
                from ..telemetry import fleet as _fleet

                _fleet.note_clock_sample(r, float(beat), time.time())
            except (TypeError, ValueError):
                pass
            except Exception:  # pragma: no cover - telemetry never raises
                pass
        with _lock:
            _hb_next[r] = nxt
            if advanced or r not in _hb_seen:
                _hb_seen[r] = now
            ages[r] = now - _hb_seen[r]
    return ages


def liveness_table() -> Dict[str, Dict]:
    """The per-peer liveness snapshot (beats observed, seconds since the
    last advance) attached to every rank_loss bundle."""
    now = time.monotonic()
    with _lock:
        return {
            str(r): {
                "beats": _hb_next.get(r, 0),
                "age_s": round(now - _hb_seen[r], 3) if r in _hb_seen else None,
                "simulated_dead": r in _sim_dead,
            }
            for r in sorted(set(_hb_next) | set(_hb_seen) | set(_sim_dead))
        }


def _check_dead(client) -> List[int]:
    """Boot ranks currently considered dead: simulated losses plus every
    peer whose heartbeat has not advanced within `pod_death_grace_s`."""
    boots = _current_boot_ranks()
    my = _my_boot_rank()
    dead = {b for b in simulated_dead_ranks() if b in boots and b != my}
    if client is not None:
        try:
            ages = _probe_liveness(client, boots, my)
        except Exception:  # pragma: no cover - client teardown races
            ages = {}
        grace = death_grace_s()
        dead |= {r for r, age in ages.items() if age > grace}
    return sorted(dead)


# ---------------------------------------------------------------------------
# The bounded cross-process wait
# ---------------------------------------------------------------------------


def live_reduce_waits() -> List[Dict]:
    """Snapshot of in-flight cross-process waits (thread, reduce tag,
    peer rank, waited seconds) — the hang doctor's stall-attribution
    input."""
    now = time.monotonic()
    with _lock:
        return [
            {**w, "waited_s": round(now - w["since"], 3)}
            for w in _live_waits.values()
        ]


def kv_wait(
    client,
    key: str,
    timeout_ms: int,
    tag: str = "",
    peer: Optional[int] = None,
) -> str:
    """THE bounded cross-process wait: every KV get in
    parallel/context.py routes through here (a unit test asserts no raw
    `blocking_key_value_get` remains there).  Waits at most `timeout_ms`
    and raises typed `ReduceTimeout` at the deadline — never hangs.
    With `pod_elastic` on, the wait is sliced at the heartbeat cadence
    and peer liveness is checked between slices: a peer whose heartbeat
    stopped past `pod_death_grace_s` raises `RankLost` EARLY (naming the
    dead boot ranks), while a slow-but-beating straggler is waited on to
    the full deadline.  The wait is registered for hang-doctor
    attribution and lands on the utilization timeline as a
    `reduce_wait` interval."""
    from .faults import maybe_inject

    maybe_inject("kv_wait")
    t0 = time.monotonic()
    t0_abs = time.time()
    tid = threading.get_ident()
    entry = {
        "thread": threading.current_thread().name,
        "thread_id": tid,
        "tag": tag or key,
        "peer": peer,
        "key": key,
        "since": t0,
    }
    with _lock:
        _live_waits[tid] = entry
    liveness = pod_elastic_enabled()
    deadline = t0 + max(1, int(timeout_ms)) / 1000.0
    slice_s = heartbeat_interval_s() if liveness else None
    try:
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                with _lock:
                    POD_METRICS["reduce_timeouts"] += 1
                raise ReduceTimeout(
                    tag or key, key=key, waited_s=time.monotonic() - t0
                )
            wait_s = min(remaining, slice_s) if liveness else remaining
            try:
                return client.blocking_key_value_get(
                    key, max(1, int(wait_s * 1000))
                )
            except Exception as e:
                if not liveness:
                    with _lock:
                        POD_METRICS["reduce_timeouts"] += 1
                    raise ReduceTimeout(
                        tag or key, key=key, waited_s=time.monotonic() - t0
                    ) from e
                dead = _check_dead(client)
                if dead:
                    raise RankLost(
                        dead, tag=tag or key, generation=generation()
                    ) from e
                # peer still beats (or liveness is inconclusive): a
                # straggler, not a corpse — keep waiting to the deadline
    finally:
        with _lock:
            _live_waits.pop(tid, None)
        try:
            from ..telemetry.utilization import note_interval

            cause = f"{tag or key}:rank{peer}" if peer is not None else (tag or key)
            note_interval(
                "reduce_wait", t0, time.monotonic(), cause=cause, domain="any"
            )
            # non-trivial waits also land as trace spans (run id + the
            # pod-global pass id), so a merged pod trace SHOWS which
            # rank was parked on which peer during a correlated pass
            waited = time.monotonic() - t0
            if waited >= 0.001:
                from ..tracing import record_span

                record_span(
                    f"reduce_wait[{tag or key}]", t0_abs, t0_abs + waited,
                    detail=(
                        f"peer=rank{peer}" if peer is not None else ""
                    ),
                )
        except Exception:  # pragma: no cover - telemetry must never raise
            pass


# ---------------------------------------------------------------------------
# Simulated losses (the one-box test hook, `device_lost` pattern)
# ---------------------------------------------------------------------------


def simulate_rank_loss(
    site: str = "", rank: Optional[int] = None
) -> RankLost:
    """Mark a peer rank dead WITHOUT real processes: liveness reports it
    exactly like a stopped heartbeat.  Run single-process, installs an
    implicit simulated 2-rank topology (this process as rank 0, rank 1
    dead) so the whole detect -> shrink -> resume machine is drivable on
    one box.  Called by the `rank_lost` fault kind (faults.py); tests
    may call it directly.  Returns the typed `RankLost` for the caller
    to raise."""
    from ..parallel import context as _pctx

    n, my = _pctx.process_topology()
    if n <= 1:
        _pctx.set_topology_override(2, 0)
        n, my = 2, 0
    boots = _current_boot_ranks()
    my_boot = boots[my] if my < len(boots) else my
    if rank is None:
        candidates = [
            b for b in boots if b != my_boot and b not in _sim_dead
        ]
        if not candidates:
            raise RuntimeError("no live peer rank left to simulate losing")
        rank = candidates[-1]
    with _lock:
        _sim_dead.add(int(rank))
    return RankLost([int(rank)], tag=site or "simulated", generation=generation())


# ---------------------------------------------------------------------------
# The recovery state machine
# ---------------------------------------------------------------------------


def recover_from_rank_loss(exc=None, log=None) -> bool:
    """Handle a failure classified `rank_loss`: name the dead ranks
    (from the typed exception, the simulated registry, and a final
    liveness probe), then SHRINK the quorum to the survivors — bump the
    generation, install the survivor topology override, and record a
    `RecoveryPlan` reassigning the dead ranks' row-group shares — and
    return True (the retry loop restarts the pass with fresh
    accumulators on the new layout).  Returns False when recovery is
    impossible and the caller should fall back to the full re-bootstrap
    path: `pod_elastic` off, no dead rank identifiable (a straggler
    timeout), or the coordinator rank itself died (the KV store died
    with it — only `reinit_distributed` against a restarted coordinator
    can help)."""
    from ..tracing import event

    lg = log or logger
    if not pod_elastic_enabled():
        return False
    from ..parallel import context as _pctx

    n, rank = _pctx.process_topology()
    boots = _current_boot_ranks()
    my_boot = boots[rank] if rank < len(boots) else rank
    dead_boot = set(getattr(exc, "lost_ranks", None) or ())
    dead_boot |= set(simulated_dead_ranks())
    client = _pctx._coordination_client()
    if client is not None:
        try:
            ages = _probe_liveness(client, boots, my_boot)
            grace = death_grace_s()
            dead_boot |= {r for r, age in ages.items() if age > grace}
        except Exception:  # pragma: no cover - client teardown races
            pass
    dead_boot = {b for b in dead_boot if b in boots and b != my_boot}
    if not dead_boot:
        event(
            "pod_recovery[inconclusive]",
            detail=f"tag={getattr(exc, 'tag', '')!r} no dead rank found",
            log=lg,
        )
        lg.warning(
            "reduce failure with no identifiable dead rank (straggler "
            "timeout?); falling back to the full re-bootstrap path"
        )
        return False
    with _lock:
        POD_METRICS["rank_losses_detected"] += len(dead_boot)
    from ..telemetry.flight_recorder import note_failure

    # ONE incident id per pod event: deterministic over (reason, the
    # detection generation, the dead set), so every survivor computes
    # the SAME id without communicating — their bundles correlate, and
    # aggregate.py fleet sums group per incident instead of counting one
    # death N times.  The survivors also swap their recent recorder
    # rings over the KV seam (deadline-bounded, absent peers named) so
    # the dumping rank writes ONE bundle carrying the whole pod's
    # timeline.
    incident_id = ""
    ring_attachments: Dict = {}
    try:
        from ..telemetry import fleet as _fleet

        incident_id = _fleet.mint_incident_id(
            "rank_loss", f"dead={sorted(dead_boot)}", generation=generation()
        )
        ring_attachments = _fleet.exchange_incident_rings(
            incident_id, dead=dead_boot
        )
    except Exception:  # pragma: no cover - telemetry must never block recovery
        pass

    if my_boot != 0 and 0 in dead_boot:
        # the coordinator process hosts the KV store: with it gone the
        # wire has nothing to reduce over — the only sound answer is a
        # full reinit_distributed against a restarted coordinator
        note_failure(
            "rank_loss",
            detail=f"coordinator (boot rank 0) dead; dead={sorted(dead_boot)}",
            attachments={
                "pass_manifest": pass_manifest(),
                "liveness": liveness_table(),
                **ring_attachments,
            },
            incident_id=incident_id,
            log=lg,
        )
        lg.warning(
            "pod recovery: the coordinator rank died — survivors cannot "
            "regroup over the dead KV store; falling back to full "
            "re-bootstrap"
        )
        return False

    dead = sorted(boots.index(b) for b in dead_boot)
    survivors = [r for r in range(n) if r not in dead]
    new_rank = survivors.index(rank)
    new_boots = tuple(boots[s] for s in survivors)

    # share bookkeeping: first loss partitions under the pre-loss
    # topology size; a chained loss inherits the original share_n and
    # redistributes the newly-dead survivors' entries
    prev = active_recovery_plan()
    if prev is None:
        share_n = n
        base_assign = {r: ((r, boots[r]),) for r in range(n)}
    else:
        share_n = prev.share_n
        base_assign = dict(prev.assignments)
    dead_entries = [e for d in dead for e in base_assign.get(d, ())]
    assignments = {
        i: tuple(base_assign.get(s, ())) for i, s in enumerate(survivors)
    }
    for j, ent in enumerate(dead_entries):
        i = j % len(survivors)
        assignments[i] = assignments[i] + (ent,)

    gen = advance_generation("rank_loss")
    plan = RecoveryPlan(
        prior_n=n,
        prior_rank=rank,
        dead_ranks=tuple(dead),
        survivors=tuple(survivors),
        boot_ranks=new_boots,
        share_n=share_n,
        assignments=assignments,
        generation=gen,
    )
    global _active_plan
    with _lock:
        _active_plan = plan
        POD_METRICS["pod_recoveries_total"] += 1
        POD_METRICS["shares_reassigned"] += len(dead_entries)
    _pctx.set_topology_override(len(survivors), new_rank)
    detail = (
        f"dead={sorted(dead_boot)} survivors={list(new_boots)} "
        f"gen={gen} shares_reassigned={len(dead_entries)} "
        f"rank={my_boot}"
        + (f" incident={incident_id}" if incident_id else "")
    )
    note_failure(
        "rank_loss",
        detail=detail,
        attachments={
            "pass_manifest": pass_manifest(),
            "liveness": liveness_table(),
            "recovery_plan": plan.as_dict(),
            **ring_attachments,
        },
        incident_id=incident_id,
        log=lg,
    )
    event("pod_recovery[shrink]", detail=detail, log=lg)
    lg.warning(
        f"pod recovery: rank(s) {sorted(dead_boot)} dead; continuing as "
        f"rank {new_rank}/{len(survivors)} under generation {gen} "
        f"({len(dead_entries)} share(s) reassigned); the interrupted "
        "pass restarts with fresh accumulators on the new layout"
    )
    return True


def on_reinit() -> int:
    """A full `reinit_distributed` re-bootstrap starts a fresh world:
    drop the recovery plan and topology override, clear simulated deaths
    and liveness history, stop the (stale-client) heartbeat, and bump
    the generation so no KV key from the previous bootstrap can bleed
    into the new one."""
    global _active_plan
    stop_heartbeat()
    with _lock:
        _active_plan = None
        _sim_dead.clear()
        _hb_next.clear()
        _hb_seen.clear()
        _pass_manifest.clear()
    try:
        # clock-offset samples and incident dedupe are per-bootstrap
        # state too: a new world's peers have new clocks
        from ..telemetry import fleet as _fleet

        _fleet.on_reinit()
    except Exception:  # pragma: no cover - import-order defensive
        pass
    from ..parallel.context import clear_topology_override

    clear_topology_override()
    return advance_generation("reinit")


def reset_pod() -> None:
    """Full reset of the pod layer (tests): everything `on_reinit` drops
    plus the metrics and the generation counter itself."""
    global _generation
    on_reinit()
    with _lock:
        _generation = 0
        for k in POD_METRICS:
            POD_METRICS[k] = 0
        _live_waits.clear()


__all__ = [
    "POD_METRICS",
    "RankLost",
    "RecoveryPlan",
    "ReduceTimeout",
    "active_recovery_plan",
    "advance_generation",
    "generation",
    "kv_wait",
    "live_reduce_waits",
    "liveness_table",
    "maybe_start_heartbeat",
    "on_reinit",
    "pass_manifest",
    "pod_elastic_enabled",
    "record_pass_manifest",
    "recover_from_rank_loss",
    "reset_pod",
    "simulate_rank_loss",
    "simulated_dead_ranks",
    "stop_heartbeat",
]
