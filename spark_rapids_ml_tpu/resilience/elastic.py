#
# Elastic mesh recovery — shrink, re-stage, resume.  On shared TPU
# fleets the dominant mid-fit failure is a device going away (spot
# reclaim of one worker's chips, an ICI link dying): PR-1's resilience
# layer could only answer with a blind `reinit_distributed` + FULL
# retry, re-running every completed iteration and assuming the same
# device count comes back.  Elastic execution frameworks (DrJAX's
# re-planning over a changed device set; Snap ML keeping partial solver
# state local so node loss never restarts global work — PAPERS.md) show
# the better contract, implemented here as a three-step state machine:
#
#   DETECT   the retry classifier types the failure (`is_device_loss`,
#            retry.py) and the post-dispatch health probe
#            (parallel/context.py `probe_device_health`) names WHICH
#            devices are gone — an opaque crash becomes a plan input;
#   SHRINK   `recover_from_device_loss` removes the lost devices from
#            service (parallel/mesh.py `exclude_devices` — every future
#            `get_mesh` builds from the survivors), drops the compiled
#            staging programs bound to the dead chips
#            (`drop_staging_programs`) so donated-buffer updaters
#            re-lower for the new shard count, and invalidates resident
#            cache entries staged over them
#            (parallel/device_cache.py `invalidate_for_devices`) so the
#            next consumer re-stages through the pipelined engine;
#   RESUME   the retry loop re-dispatches: the caller re-stages its
#            inputs onto the degraded mesh (core.py `_run_fit_kernel`'s
#            restage hook) and the checkpointed iterative solvers
#            (KMeans Lloyd, L-BFGS, FISTA, epoch streaming) reload
#            their last `resilience/checkpoint.py` state — the tags are
#            mesh-layout-independent by construction — and continue
#            from iteration k on the smaller mesh instead of restarting
#            at 0.
#
# Gates: the `elastic` conf ("off" restores the PR-1 full-retry path
# unchanged) and `elastic_min_devices` (shrinking below it falls back —
# a fit squeezed onto too few chips is worse than waiting for
# capacity).  Every transition emits an `elastic_recovery[...]` trace
# event and bumps `RECOVERY_METRICS`.
#
# Testability: the whole state machine is drivable on the CPU test mesh
# — the `device_lost` fault kind (faults.py) raises the jaxlib-shaped
# error AND registers a simulated loss here, so `probe_lost_devices`
# reports it exactly like a failed hardware probe.  No wall clocks, no
# real hardware.
#
# Real-hardware caveat: on current TPU runtimes a physically lost chip
# often poisons the whole backend client; the shrink path then engages
# after the runtime re-bootstrap (the preemption hook runs first on
# those error shapes).  The state machine itself is runtime-agnostic —
# it plans from whatever the probe reports.
#
from __future__ import annotations

from typing import List, Optional

from ..config import get_config
from ..telemetry.locks import named_lock
from ..utils import get_logger

logger = get_logger("spark_rapids_ml_tpu.resilience")

_lock = named_lock("elastic")

# cumulative process-wide recovery counters (tests, bench, operators):
#   losses_detected      devices the probe confirmed gone
#   meshes_rebuilt       successful shrink-to-survivors recoveries
#   iterations_salvaged  solver iterations a post-recovery checkpoint
#                        resume did NOT have to re-run
#   full_retry_fallbacks losses handled by the PR-1 full-retry path
#                        (elastic off / below elastic_min_devices)
# Now a VIEW over the telemetry registry (telemetry/registry.py): the
# same mapping surface, exported as the `recovery{key=...}` Prometheus
# family so `dump_prometheus()` always matches these counters.
from ..telemetry.registry import dict_view as _dict_view

RECOVERY_METRICS = _dict_view(
    "recovery",
    "Elastic mesh recovery counters (losses/rebuilds/salvage/fallbacks)",
    initial={
        "losses_detected": 0,
        "meshes_rebuilt": 0,
        "iterations_salvaged": 0,
        "full_retry_fallbacks": 0,
        "remote_host_losses": 0,
    },
)

# device ids the `device_lost` fault kind has marked lost — the CPU test
# mesh has no hardware to actually kill, so the probe layers this
# registry over the real round-trip probe
_sim_lost: set = set()

# set by a successful mesh rebuild, consumed by the FIRST checkpoint
# resume after it: the bridge that lets `iterations_salvaged` attribute
# resumed iterations to the recovery that made them possible
_recovery_pending = False


def elastic_enabled() -> bool:
    return str(get_config("elastic")).lower() == "on"


def elastic_min_devices() -> int:
    return max(1, int(get_config("elastic_min_devices")))


# ---------------------------------------------------------------------------
# Simulated losses (the CPU-mesh test hook)
# ---------------------------------------------------------------------------


def simulate_device_loss(device_id: Optional[int] = None) -> int:
    """Mark a device lost WITHOUT real hardware: the probe reports it
    exactly like a failed round-trip.  Default: the last still-active
    device, so repeated injections cascade (8 -> 7 -> 6 ...).  Called by
    the `device_lost` fault kind (faults.py); tests may call it
    directly.  Returns the lost device id."""
    if device_id is None:
        from ..parallel.mesh import active_devices

        devices = active_devices()
        candidates = [d.id for d in devices if d.id not in _sim_lost]
        if not candidates:
            raise RuntimeError("no active device left to simulate losing")
        device_id = candidates[-1]
    with _lock:
        _sim_lost.add(int(device_id))
    return int(device_id)


def simulated_lost_ids() -> frozenset:
    with _lock:
        return frozenset(_sim_lost)


def reset_elastic() -> None:
    """Full reset of the elastic layer (tests; operator reset once lost
    hardware is back): clears simulated losses, restores excluded
    devices to service, zeroes the metrics, and drops any pending
    salvage attribution."""
    global _recovery_pending
    from ..parallel.mesh import restore_devices

    with _lock:
        _sim_lost.clear()
        for k in RECOVERY_METRICS:
            RECOVERY_METRICS[k] = 0
        _recovery_pending = False
    restore_devices()


# ---------------------------------------------------------------------------
# Detection
# ---------------------------------------------------------------------------


def probe_lost_devices(devices=None) -> List:
    """The devices of `devices` (default: the active set) that are gone:
    simulated losses plus every device failing the real health probe
    (parallel/context.py `probe_device_health`)."""
    from ..parallel.context import probe_device_health
    from ..parallel.mesh import active_devices

    devices = list(devices) if devices is not None else active_devices()
    with _lock:
        sim = set(_sim_lost)
    lost = [d for d in devices if d.id in sim]
    lost += probe_device_health([d for d in devices if d.id not in sim])
    return lost


def note_checkpoint_resume(it: int) -> None:
    """Called by `load_checkpoint` on every successful resume carrying
    an iteration counter: the FIRST resume after a mesh rebuild is the
    recovery's payoff, recorded as `iterations_salvaged` (iterations the
    degraded-mesh fit did not have to re-run)."""
    global _recovery_pending
    with _lock:
        if not _recovery_pending:
            return
        _recovery_pending = False
        RECOVERY_METRICS["iterations_salvaged"] += max(int(it), 0)
    from ..tracing import event

    event("elastic_recovery[resumed]", detail=f"it={int(it)}", log=logger)


# ---------------------------------------------------------------------------
# The recovery state machine
# ---------------------------------------------------------------------------


def recover_from_device_loss(logger_=None) -> bool:
    """Handle a dispatch failure classified `device_loss`: probe, then
    either SHRINK the mesh to the survivors (True — the caller should
    re-stage onto the new mesh and re-dispatch; checkpointed solvers
    resume at iteration k) or FALL BACK to the PR-1 full-retry path
    (False — `reinit_distributed` ran, the caller re-dispatches
    unchanged).  Fallback triggers: `elastic=off`, fewer than
    `elastic_min_devices` survivors, or a probe that finds every device
    healthy (a runtime flake that merely looked like a loss)."""
    global _recovery_pending
    from ..tracing import event

    lg = logger_ or logger
    from ..parallel.mesh import active_devices

    devices = active_devices()
    lost = probe_lost_devices(devices)
    event(
        "elastic_recovery[probe]",
        detail=f"n_dev={len(devices)} lost={[d.id for d in lost]}",
        log=lg,
    )
    if not lost:
        # the error string looked like a device loss but every device
        # answers the probe: treat it as the runtime hiccup it was
        lg.warning(
            "device-loss-shaped error but all devices answer the health "
            "probe; falling back to the full-retry (preemption) path"
        )
        _fallback_full_retry(lg)
        return False
    with _lock:
        RECOVERY_METRICS["losses_detected"] += len(lost)
    # a confirmed device loss is a hardware-grade event: dump the flight
    # recorder NOW, before the shrink mutates mesh/cache state — the
    # bundle's trace carries the interrupted fit's spans and run id even
    # when the fit never had telemetry_dir reports enabled
    from ..telemetry.flight_recorder import note_failure

    note_failure(
        "device_lost",
        detail=f"lost={[d.id for d in lost]} n_dev={len(devices)}",
        log=lg,
    )
    # classify the loss: a lost LOCAL chip is recoverable by shrinking
    # this host's meshes, but a lost device on a REMOTE host means a
    # peer PROCESS is gone — the pod's cross-process reduction seam
    # (parallel/context.py) would dead-peer-timeout at the next
    # pass_complete, so the only sound answer is the full re-bootstrap
    # of jax.distributed (which re-reads `coordinator_address` from the
    # live conf, picking up a restarted coordinator)
    import jax

    pid = jax.process_index()
    remote = [d for d in lost if getattr(d, "process_index", pid) != pid]
    if remote:
        with _lock:
            RECOVERY_METRICS["remote_host_losses"] += 1
        detail = (
            f"lost_remote={[(d.id, d.process_index) for d in remote]} "
            f"local_rank={pid}"
        )
        event("elastic_recovery[remote_host_loss]", detail=detail, log=lg)
        # a remote-host device loss means a peer PROCESS is gone.  With
        # `pod_elastic` on, that is exactly the pod fault domain: shrink
        # the quorum to the surviving ranks (resilience/pod.py) and let
        # the pass restart on the reassigned share layout — strictly
        # better than the blind full re-bootstrap, which assumed the
        # dead rank would come back
        from .pod import RankLost, pod_elastic_enabled, recover_from_rank_loss

        if pod_elastic_enabled():
            dead = sorted({int(d.process_index) for d in remote})
            if recover_from_rank_loss(
                RankLost(dead, tag="device_probe"), log=lg
            ):
                return True
        lg.warning(
            f"Device loss includes remote-host device(s) "
            f"{[int(d.id) for d in remote]} (peer process gone); elastic "
            "local shrink cannot recover a dead rank — re-bootstrapping "
            "the distributed runtime instead"
        )
        _fallback_full_retry(lg)
        return False

    lost_id_set = {int(d.id) for d in lost}
    survivors = [d for d in devices if int(d.id) not in lost_id_set]
    if not elastic_enabled() or len(survivors) < elastic_min_devices():
        reason = (
            "elastic=off"
            if not elastic_enabled()
            else f"{len(survivors)} survivor(s) < "
            f"elastic_min_devices={elastic_min_devices()}"
        )
        event("elastic_recovery[fallback]", detail=reason, log=lg)
        lg.warning(
            f"Device loss ({[d.id for d in lost]}) not recovered "
            f"elastically ({reason}); full retry on the unchanged device "
            "set"
        )
        _fallback_full_retry(lg)
        return False

    # -- shrink: survivors-only meshes, re-lowered staging, fresh cache --
    from ..parallel.device_cache import invalidate_for_devices
    from ..parallel.mesh import drop_staging_programs, exclude_devices

    lost_ids = [int(d.id) for d in lost]
    exclude_devices(lost_ids)
    drop_staging_programs()
    evicted = invalidate_for_devices(lost_ids)
    with _lock:
        RECOVERY_METRICS["meshes_rebuilt"] += 1
        _recovery_pending = True
    event(
        "elastic_recovery[mesh_rebuilt]",
        detail=(
            f"lost={lost_ids} n_dev={len(survivors)} "
            f"cache_evicted={evicted}"
        ),
        log=lg,
    )
    lg.warning(
        f"Elastic recovery: lost device(s) {lost_ids}; continuing on "
        f"{len(survivors)} surviving device(s) "
        f"({evicted} resident dataset(s) invalidated for re-staging)"
    )
    return True


def _fallback_full_retry(lg) -> None:
    """The PR-1 behavior: re-bootstrap jax.distributed and let the
    retry loop re-dispatch on the unchanged device set."""
    with _lock:
        RECOVERY_METRICS["full_retry_fallbacks"] += 1
    from .retry import _default_preemption_hook

    _default_preemption_hook()


__all__ = [
    "RECOVERY_METRICS",
    "elastic_enabled",
    "elastic_min_devices",
    "note_checkpoint_resume",
    "probe_lost_devices",
    "recover_from_device_loss",
    "reset_elastic",
    "simulate_device_loss",
    "simulated_lost_ids",
]
