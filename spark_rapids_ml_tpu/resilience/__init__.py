#
# resilience/ — the unified failure-handling layer every fit/transform
# path routes through.  The reference stack survives executor loss because
# Spark re-schedules barrier tasks (reference core.py:742-1013); this
# single-controller JAX runtime has no scheduler above it, so the same
# guarantees live here, in four pieces:
#
#   guard.py       guarded(fn, deadline, label): blocking device work under
#                  a watchdog thread — a hang raises a typed
#                  DispatchTimeout instead of blocking the controller
#                  forever (the axon-tunnel hang class, TPU_STATUS_r05.md).
#   retry.py       RetryPolicy: declarative max-attempts / exponential
#                  backoff + jitter / error classifier.  One classifier
#                  set subsumes the hand-rolled special cases: OOM ->
#                  shrink batch (site-provided hook), transient
#                  RPC/DEADLINE -> backoff + retry, preemption -> re-init
#                  jax.distributed then resume.
#   faults.py      deterministic fault injection at named dispatch sites,
#                  so every recovery path is exercisable on CPU in CI.
#   checkpoint.py  the estimator-wide checkpoint contract (content-tag
#                  naming, atomic tmp + os.replace, rank-0 writer) lifted
#                  out of streaming.py and shared by every iterative
#                  solver loop.
#   elastic.py     elastic mesh recovery: a classified DEVICE LOSS
#                  shrinks the mesh to the survivors
#                  (parallel/mesh.py exclusions), invalidates resident
#                  cache entries for re-staging, and lets checkpointed
#                  solvers resume at iteration k on the smaller mesh —
#                  instead of the blind full retry.
#   pod.py         the same contract at POD scale: bounded, typed
#                  cross-process waits (`kv_wait`), per-rank liveness
#                  heartbeats, and a RANK LOSS recovery that shrinks the
#                  quorum to the survivors under a bumped reduction
#                  generation and reassigns the dead rank's row-group
#                  shares (fused.py consumes the RecoveryPlan).
#
# The layer imports neither jax nor numpy at module scope: arming faults
# or reading a policy must not pay the multi-second jax import.
#
from .checkpoint import (  # noqa: F401
    checkpoint_file_for,
    clear_checkpoint,
    load_checkpoint,
    resolve_checkpoint_dir,
    save_checkpoint,
    sweep_orphaned_tmps,
)
from .elastic import (  # noqa: F401
    RECOVERY_METRICS,
    probe_lost_devices,
    recover_from_device_loss,
    reset_elastic,
    simulate_device_loss,
)
from .faults import SimulatedPreemption, fault_inject, maybe_inject  # noqa: F401
from .guard import DispatchTimeout, guarded  # noqa: F401
from .pod import (  # noqa: F401
    POD_METRICS,
    RankLost,
    ReduceTimeout,
    recover_from_rank_loss,
    reset_pod,
    simulate_rank_loss,
)
from .retry import (  # noqa: F401
    RetryPolicy,
    classify_error,
    is_device_loss,
    is_oom,
    is_preemption,
    is_rank_loss,
    is_remote_compile_flake,
    is_transient,
    retry_call,
)

__all__ = [
    "DispatchTimeout",
    "POD_METRICS",
    "RECOVERY_METRICS",
    "RankLost",
    "ReduceTimeout",
    "RetryPolicy",
    "SimulatedPreemption",
    "checkpoint_file_for",
    "classify_error",
    "clear_checkpoint",
    "fault_inject",
    "guarded",
    "is_device_loss",
    "is_oom",
    "is_preemption",
    "is_rank_loss",
    "is_remote_compile_flake",
    "is_transient",
    "load_checkpoint",
    "maybe_inject",
    "probe_lost_devices",
    "recover_from_device_loss",
    "recover_from_rank_loss",
    "reset_elastic",
    "reset_pod",
    "resolve_checkpoint_dir",
    "retry_call",
    "save_checkpoint",
    "simulate_device_loss",
    "simulate_rank_loss",
    "sweep_orphaned_tmps",
]
