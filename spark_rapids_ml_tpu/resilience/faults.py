#
# Deterministic fault injection — the test harness for every recovery
# path.  Real OOM / tunnel-timeout / TPU-preemption faults only occur on
# hardware under load; CI runs on a CPU mesh, so recovery code would
# otherwise ship unexercised (the reference has the same gap: its barrier
# re-schedule path is only exercised by live executor loss).  Dispatch
# sites call `maybe_inject("<site>")`; tests (or the `fault_inject_spec`
# conf for whole-process runs) arm a site with a fault kind and exact
# occurrence counts, so each injected failure is reproducible down to the
# iteration it fires on.
#
# The instrumented sites are registered in `KNOWN_SITES` below (the
# canonical list docs/resilience.md mirrors and the graft-lint
# fault-site rule enforces).  One contract worth repeating here:
# `fused_accumulate` (the fused stage-and-solve chunk loop, fused.py)
# fires per accumulated chunk; accumulators are RE-CREATABLE state, so
# the recovery contract is restart-the-pass, never resume — tests
# assert a retried pass cannot double-count chunks.
#
from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, List

from ..config import get_config
from ..telemetry.locks import named_lock
from ..utils import get_logger

logger = get_logger("spark_rapids_ml_tpu.resilience")

_lock = named_lock("faults")

# The canonical fault-site registry.  Every `maybe_inject("<site>")`
# literal in the package must be registered here, every registered site
# must be instrumented by at least one dispatch-site call, and
# docs/resilience.md must list each one — all three cross-checked by the
# graft-lint `fault-site` rule (spark_rapids_ml_tpu/analysis/), so the
# site list can no longer silently diverge between code and docs.
# Tests arm ad-hoc sites freely as long as the same file instruments
# them with its own `maybe_inject` call.
KNOWN_SITES = frozenset({
    "fit_kernel",
    "transform_dispatch",
    "stage_parquet",
    "kmeans_lloyd",
    "lbfgs_iteration",
    "linreg_fista",
    "fused_accumulate",
    # the serving dispatcher's coalesced micro-batch dispatch
    # (serving/server.py): an injected OOM shrinks the coalescing batch
    # cap, a device_lost routes through elastic recovery and re-pins
    # every resident model on the shrunken mesh — no queued request is
    # lost either way
    "serving_dispatch",
    # the serving admission gate (serving/server.py submit): fires
    # BEFORE the request touches a queue, so injection drills can drive
    # the admission/shed/brownout paths deterministically — the fault
    # propagates to the submitting caller, never into the dispatcher,
    # and no half-admitted request leaks into the class deques
    "serving_admission",
    # the staged pipeline's collect/scatter phase (serving/server.py
    # collect worker): fires AFTER the batch dispatched, while earlier
    # batches may still be in flight behind it — the drill for
    # mid-pipeline failure.  Recovery requeues every in-flight batch's
    # requests in dispatch order (per-model, per-class FIFO preserved)
    # and the dispatcher re-coalesces; no request is lost or reordered
    "serving_collect",
    # the chunk cache's spill-to-host compression step
    # (parallel/device_cache.py ChunkCache._spill_chunk_locked): fires
    # while an epoch iteration is inserting/evicting chunks mid-stream.
    # The cache drops its half-recorded stream and the error propagates
    # into the consuming fit, whose retry restarts the pass with FRESH
    # accumulators — cached chunks are re-creatable state, so a retried
    # epoch can never double-count (asserted by tests/test_chunk_cache.py)
    "chunk_cache_spill",
    # the statistic-program engine's per-chunk fold (stats/engine.py):
    # same contract as `fused_accumulate` — accumulators are
    # re-creatable state, a mid-pass fault fails the WHOLE pass and the
    # retry restarts it with fresh accumulators, so a retried chunk can
    # never double-count (asserted by tests/test_stat_programs.py)
    "stat_program_step",
    # the pod layer's bounded cross-process wait (resilience/pod.py
    # `kv_wait`): every KV get/allgather/broadcast in
    # parallel/context.py enters here, so arming it drives the
    # rank-loss / reduce-timeout recovery paths at the exact wait a
    # dead peer would have wedged
    "kv_wait",
})

# Injectable fault kinds (`_Fault` validates against this; the docs and
# the `fault_inject_spec` conf comment enumerate the same set)
FAULT_KINDS = (
    "oom",
    "timeout",
    "preemption",
    "hang",
    "device_lost",
    "rank_lost",
    "kv_timeout",
)


class SimulatedPreemption(RuntimeError):
    """An injected TPU-worker preemption (the str carries 'preempted' so
    the retry classifier routes it like the real coordinator error)."""

    def __init__(self, site: str) -> None:
        super().__init__(
            f"injected fault: TPU worker preempted at dispatch site '{site}'"
        )
        self.site = site


class _Fault:
    __slots__ = ("kind", "times", "skip", "seconds")

    def __init__(self, kind: str, times: int, skip: int, seconds: float) -> None:
        if kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind: {kind!r}")
        self.kind = kind
        self.times = int(times)
        self.skip = int(skip)
        self.seconds = float(seconds)


# context-manager-armed faults (tests) and conf-armed faults
# (`fault_inject_spec`, whole-process runs) are tracked separately so a
# config re-parse never clobbers an active `fault_inject` block
_armed: Dict[str, List[_Fault]] = {}
_armed_conf: Dict[str, List[_Fault]] = {}
_conf_spec_seen: str = ""


@contextlib.contextmanager
def fault_inject(
    site: str,
    kind: str,
    times: int = 1,
    skip: int = 0,
    seconds: float = 5.0,
) -> Iterator[None]:
    """Arm `site` to fail deterministically while the block runs.

    `skip` occurrences pass through first (inject mid-fit, e.g. after
    three Lloyd iterations), then the next `times` occurrences fire.
    Kinds: `oom` (a RESOURCE_EXHAUSTED RuntimeError), `timeout` (a typed
    DispatchTimeout), `preemption` (SimulatedPreemption), `hang` (sleeps
    `seconds` so the `guarded` watchdog fires — the only kind that needs
    a positive `dispatch_deadline_s` to become an error), `device_lost`
    (a jaxlib-shaped 'failed to execute ... device' RuntimeError that
    ALSO registers a simulated loss with resilience/elastic.py, so the
    health probe reports the device gone and the whole elastic-recovery
    state machine runs on the CPU test mesh), `rank_lost` (a typed
    `pod.RankLost` that ALSO registers a simulated dead peer with
    resilience/pod.py — single-process it installs an implicit 2-rank
    simulated topology first, so the pod detect/shrink/resume machine
    runs on one box), `kv_timeout` (a typed `pod.ReduceTimeout`, the
    bounded-wait expiry with no identifiable corpse — the straggler
    shape).
    """
    f = _Fault(kind, times, skip, seconds)
    with _lock:
        _armed.setdefault(site, []).append(f)
    try:
        yield
    finally:
        with _lock:
            faults = _armed.get(site, [])
            if f in faults:
                faults.remove(f)
            if not faults:
                _armed.pop(site, None)


def _parse_spec(spec: str) -> Dict[str, List[_Fault]]:
    """`"site:kind[:times[:skip]]"` comma list -> armed-fault table."""
    out: Dict[str, List[_Fault]] = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) < 2:
            raise ValueError(
                f"fault_inject_spec entry {entry!r} is not "
                "'site:kind[:times[:skip]]'"
            )
        site, kind = parts[0], parts[1]
        times = int(parts[2]) if len(parts) > 2 else 1
        skip = int(parts[3]) if len(parts) > 3 else 0
        out.setdefault(site, []).append(_Fault(kind, times, skip, 5.0))
    return out


def _sync_conf_locked() -> None:
    global _conf_spec_seen, _armed_conf
    spec = str(get_config("fault_inject_spec") or "")
    if spec == _conf_spec_seen:
        return
    _armed_conf = _parse_spec(spec)
    _conf_spec_seen = spec


def maybe_inject(site: str) -> None:
    """Fire the armed fault for `site`, if any.  Called at every named
    dispatch site; unarmed sites cost one dict lookup."""
    with _lock:
        _sync_conf_locked()
        # one occurrence counts ONCE against every armed fault's skip
        # window, and the first fault that is ready (skip drained, times
        # left) fires — a fault still skipping must not suppress another
        # fault armed at the same site
        fault = None
        for table in (_armed, _armed_conf):
            for f in table.get(site, []):
                if f.skip > 0:
                    f.skip -= 1
                elif fault is None and f.times > 0:
                    f.times -= 1
                    fault = f
    if fault is None:
        return
    from ..telemetry.registry import counter
    from ..tracing import event

    counter(
        "faults_injected_total", "Deterministic fault injections by site"
    ).inc(site=site, kind=fault.kind)
    event(f"fault_injected[{site}]", detail=fault.kind, log=logger)
    if fault.kind == "oom":
        raise RuntimeError(
            f"RESOURCE_EXHAUSTED: injected OOM fault at dispatch site "
            f"'{site}'"
        )
    if fault.kind == "timeout":
        from .guard import DispatchTimeout

        raise DispatchTimeout(site, fault.seconds)
    if fault.kind == "preemption":
        raise SimulatedPreemption(site)
    if fault.kind == "device_lost":
        # mark the device gone FIRST (so the recovery probe finds it),
        # then fail the dispatch the way jaxlib does when a chip
        # vanishes mid-execution — the string shape `is_device_loss`
        # (retry.py) classifies
        from .elastic import simulate_device_loss

        dev = simulate_device_loss()
        raise RuntimeError(
            "INTERNAL: failed to execute XLA Runtime executable: device "
            f"{dev} has been lost (injected fault at dispatch site "
            f"'{site}')"
        )
    if fault.kind == "rank_lost":
        # register the simulated dead peer FIRST (so liveness and the
        # recovery probe find it), then raise the typed loss the bounded
        # wait would have raised — the `device_lost` pattern at pod scale
        from .pod import simulate_rank_loss

        raise simulate_rank_loss(site)
    if fault.kind == "kv_timeout":
        from .pod import ReduceTimeout

        raise ReduceTimeout(
            site, key=f"injected/{site}", waited_s=fault.seconds
        )
    # "hang": park inside the dispatch so the guarded watchdog fires; on
    # its own (no deadline armed) this is just a stall, never an error
    time.sleep(fault.seconds)
