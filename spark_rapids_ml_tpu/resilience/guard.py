#
# Guarded dispatch — a watchdog for blocking device work.  The hang
# ledger (TPU_STATUS_r05.md) records `block_until_ready` and host fetches
# that never return when the axon tunnel drops a transfer: the controller
# then blocks forever with no exception to recover from.  `guarded` runs
# the blocking call on a worker thread and bounds the wait; past the
# deadline the CALLER gets a typed `DispatchTimeout` (classified transient
# by retry.py, so policy-driven re-dispatch applies) while the abandoned
# worker parks harmlessly until the runtime call returns or the process
# exits.
#
from __future__ import annotations

import threading
from typing import Any, Callable, Optional

from ..config import get_config
from ..telemetry.registry import counter as _counter
from ..utils import get_logger

logger = get_logger("spark_rapids_ml_tpu.resilience")

TIMEOUTS = _counter(
    "dispatch_timeouts_total",
    "Watchdog deadline expiries by dispatch label",
)


class DispatchTimeout(RuntimeError):
    """Blocking device work exceeded its watchdog deadline.

    Typed (instead of a bare hang or a stringly RuntimeError) so
    `retry.classify_error` can route it: transient -> backoff + re-dispatch.
    """

    def __init__(self, label: str, deadline: float) -> None:
        super().__init__(
            f"dispatch '{label}' exceeded its {deadline:.1f}s watchdog "
            "deadline (DEADLINE_EXCEEDED); the device program may still be "
            "in flight"
        )
        self.label = label
        self.deadline = deadline


def guarded(
    fn: Callable[[], Any],
    deadline: Optional[float] = None,
    label: str = "dispatch",
    log: Optional[object] = None,
) -> Any:
    """Run `fn` (blocking device work) under a watchdog deadline.

    `deadline=None` reads the `dispatch_deadline_s` conf; `<= 0` disables
    the watchdog entirely — `fn` runs inline on the calling thread with
    zero overhead (the default, and the tier-1 test configuration).

    With a positive deadline the call runs on a daemon worker thread and
    the caller waits at most `deadline` seconds: completion returns the
    value (or re-raises the worker's exception); expiry records a
    `dispatch_timeout[label]` trace event carrying the deadline and raises
    `DispatchTimeout`.  The worker is NOT killed — Python cannot interrupt
    a thread blocked inside the runtime — but the caller regains control,
    which is the property the hang ledger shows we lose today.
    """
    if deadline is None:
        deadline = float(get_config("dispatch_deadline_s") or 0.0)
    if deadline <= 0:
        return fn()

    result: list = []
    failure: list = []
    # the worker adopts the caller's trace context: tracing storage is
    # thread-local, so without this every trace()/event() recorded inside
    # the guarded dispatch (stage timings, resume/fault markers) would be
    # invisible to the caller whenever the watchdog is enabled
    from ..tracing import adopt_trace_context

    adopt = adopt_trace_context()

    def _worker() -> None:
        adopt()
        try:
            result.append(fn())
        except BaseException as e:  # surfaced on the caller below
            failure.append(e)

    t = threading.Thread(
        target=_worker, name=f"guarded[{label}]", daemon=True
    )
    t.start()
    t.join(deadline)
    if t.is_alive():
        from ..tracing import event

        TIMEOUTS.inc(label=label)
        event(
            f"dispatch_timeout[{label}]",
            detail=f"deadline={deadline:.1f}s",
            log=log or logger,
        )
        # the watchdog firing is exactly the moment evidence is about to
        # be lost (the runtime may never return): leave the black box
        from ..telemetry.flight_recorder import note_failure

        note_failure(
            "dispatch_timeout",
            detail=f"label={label} deadline={deadline:.1f}s",
            log=log or logger,
        )
        raise DispatchTimeout(label, deadline)
    if failure:
        raise failure[0]
    return result[0]
