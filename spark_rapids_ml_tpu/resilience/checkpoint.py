#
# Estimator-wide checkpoint/resume — the contract lifted out of
# streaming.py (which grew it for epoch-streaming fits) so EVERY iterative
# solver loop shares it: content-tag filenames, atomic tmp + os.replace
# writes, a rank-0-only writer, and an in-file tag check that refuses a
# checkpoint belonging to a different fit.  Solvers wired today: the
# host-dispatched KMeans Lloyd (ops/kmeans.py), host L-BFGS/OWL-QN
# (ops/lbfgs.py — in-memory host-dispatch AND epoch-streaming), the FISTA
# elastic-net loop (ops/linear.py), and the epoch-streaming Lloyd
# (streaming.py).  Any estimator with the `checkpoint_dir` conf set
# resumes after a crash instead of restarting at iteration 0.
#
from __future__ import annotations

import os
from typing import Dict, Optional

from ..config import get_config
from ..utils import get_logger

logger = get_logger("spark_rapids_ml_tpu.resilience")


# a *.tmp.npz younger than this is presumed to be a CONCURRENT save
# still between its np.savez and os.replace — sweeping it would break
# that save; anything older is a crash leftover (the replace is
# milliseconds after the savez finishes)
_TMP_SWEEP_AGE_S = 60.0


def sweep_orphaned_tmps(ckpt_dir: str) -> int:
    """Remove orphaned `*.tmp.npz` files from a checkpoint dir: a crash
    BETWEEN `np.savez` and `os.replace` (save_checkpoint) leaks the tmp
    forever — nothing ever resolves to the `.tmp.npz` name, so without
    this sweep a long-lived shared checkpoint dir accretes dead files on
    every unlucky crash.  Age-guarded (`_TMP_SWEEP_AGE_S`) so an
    in-flight save from another rank/process is never swept; writer rank
    only, like every other mutation of the shared dir.  Returns the
    number of files removed."""
    if not ckpt_dir or not _is_writer():
        return 0
    import glob
    import time

    removed = 0
    for tmp in glob.glob(os.path.join(ckpt_dir, "*.tmp.npz")):
        try:
            if time.time() - os.path.getmtime(tmp) >= _TMP_SWEEP_AGE_S:
                os.remove(tmp)
                removed += 1
        except OSError:
            continue  # another sweeper/raced writer got there first
    if removed:
        logger.info(
            f"Swept {removed} orphaned checkpoint tmp file(s) from "
            f"{ckpt_dir}"
        )
    return removed


def resolve_checkpoint_dir(streaming: bool = False) -> str:
    """The effective checkpoint directory; empty string = off.

    The older `streaming_checkpoint_dir` alias applies ONLY to streaming
    fits (`streaming=True`) — its documented scope.  In-memory fits read
    just the estimator-wide `checkpoint_dir`: honoring the alias there
    would silently reroute every small fit of an existing
    streaming-checkpoint user onto the slower per-iteration host-dispatched
    solvers (`checkpoint_dir` forces stepwise, see ops/kmeans.py
    `kmeans_fit_auto`).

    Resolution also sweeps orphaned `*.tmp.npz` leftovers (a crash
    between savez and replace) — every fit resolves its dir before
    touching it, so the sweep needs no separate maintenance hook."""
    d = get_config("checkpoint_dir")
    if not d and streaming:
        d = get_config("streaming_checkpoint_dir")
    d = str(d or "")
    if d and os.path.isdir(d):
        sweep_orphaned_tmps(d)
    return d


def checkpoint_file_for(ckpt_dir: str, tag: str) -> str:
    """Deterministic checkpoint filename from the solver's content tag
    (dataset identity, shape, hyperparams).  A preempted process RESTARTS
    with fresh Python state, so the name must not depend on anything
    per-process (estimator uid counters made a restarted fit silently
    miss its checkpoint); the tag is identical across restarts of the
    same fit by construction, and the in-file tag check still guards
    against hash collisions/config drift."""
    import hashlib

    h = hashlib.sha1(tag.encode()).hexdigest()[:16]
    kind = tag.split("|", 1)[0]
    return os.path.join(ckpt_dir, f"{kind}-{h}.npz")


def _is_writer() -> bool:
    # multi-process pods run solver loops in lockstep on every process
    # (the oracle all-reduces); only rank 0 writes the shared file to
    # avoid concurrent savez/replace races
    try:
        import jax

        return jax.process_index() == 0
    except Exception:
        return True


def save_checkpoint(path: str, tag: str, state: Dict[str, object]) -> None:
    """Atomically persist `state` ({name: array-like}) under `tag`.
    Non-writer ranks no-op; the tmp + `os.replace` pair guarantees a
    reader never observes a torn file."""
    if not path or not _is_writer():
        return
    import numpy as np

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp.npz"
    np.savez(tmp, tag=np.asarray(tag), **{k: np.asarray(v) for k, v in state.items()})
    os.replace(tmp, path)
    from ..telemetry.registry import counter

    counter(
        "checkpoint_saves_total", "Solver-state checkpoint writes"
    ).inc()


def load_checkpoint(path: str, tag: str) -> Optional[Dict[str, object]]:
    """Load a checkpoint IF it exists and belongs to this fit.  A tag
    mismatch (different dataset/hyperparams hashed to the same name, or
    config drift) warns and returns None — the fit starts fresh rather
    than resuming someone else's trajectory."""
    if not path or not os.path.exists(path):
        return None
    import numpy as np

    with np.load(path, allow_pickle=False) as z:
        state = {k: z[k] for k in z.files}
    if str(state.pop("tag", "")) != tag:
        import warnings

        warnings.warn(
            f"Ignoring checkpoint {path}: it belongs to a different fit "
            "(tag mismatch)"
        )
        return None
    from ..telemetry.registry import counter

    counter(
        "checkpoint_resumes_total", "Solver fits resumed from checkpoint"
    ).inc()
    if "it" in state:
        # the first resume after an elastic mesh rebuild is the
        # recovery's payoff — attribute the salvaged iterations
        # (resilience/elastic.py gates on its own pending flag, so
        # ordinary crash-restart resumes cost one no-op call)
        from .elastic import note_checkpoint_resume

        note_checkpoint_resume(int(np.asarray(state["it"])))
    return state


def clear_checkpoint(path: str) -> None:
    """Remove a completed fit's checkpoint (writer rank only).  Missing
    files are fine — a resumed fit that never re-saved has nothing to
    clear."""
    if not path or not _is_writer():
        return
    try:
        os.remove(path)
    except FileNotFoundError:
        pass
