#
# Evaluators — pyspark.ml.evaluation-compatible surface consumed by
# CrossValidator (the reference CV is driven by Spark's
# MulticlassClassificationEvaluator / RegressionEvaluator /
# BinaryClassificationEvaluator, tuning.py:97-130; without Spark the
# equivalent evaluators live here, computing on the metrics/ subsystem).
#
from __future__ import annotations

from typing import Any

import numpy as np

from .metrics import MulticlassMetrics, RegressionMetrics
from .params import Param, Params, TypeConverters


class Evaluator(Params):
    def evaluate(self, dataset: Any) -> float:
        raise NotImplementedError

    def isLargerBetter(self) -> bool:
        return True

    def _col(self, df, name: str) -> np.ndarray:
        if name not in df.columns:
            raise ValueError(f"Column '{name}' not found in dataset")
        col = df[name]
        first = col.iloc[0]
        if np.isscalar(first):
            return col.to_numpy()
        return np.stack([np.asarray(v) for v in col])


class MulticlassClassificationEvaluator(Evaluator):
    """pyspark.ml.evaluation.MulticlassClassificationEvaluator parity."""

    metricName = Param("_", "metricName", "metric name.", TypeConverters.toString)
    labelCol = Param("_", "labelCol", "label column.", TypeConverters.toString)
    predictionCol = Param("_", "predictionCol", "prediction column.",
                          TypeConverters.toString)
    probabilityCol = Param("_", "probabilityCol", "probability column.",
                           TypeConverters.toString)
    weightCol = Param("_", "weightCol", "weight column.", TypeConverters.toString)
    metricLabel = Param("_", "metricLabel", "class for *ByLabel metrics.",
                        TypeConverters.toFloat)
    beta = Param("_", "beta", "beta for weightedFMeasure.", TypeConverters.toFloat)

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(
            metricName="f1",
            labelCol="label",
            predictionCol="prediction",
            probabilityCol="probability",
            metricLabel=0.0,
            beta=1.0,
        )
        self._set(**kwargs)

    def setMetricName(self, value: str) -> "MulticlassClassificationEvaluator":
        self._set(metricName=value)
        return self

    def setLabelCol(self, value: str) -> "MulticlassClassificationEvaluator":
        self._set(labelCol=value)
        return self

    def setPredictionCol(self, value: str) -> "MulticlassClassificationEvaluator":
        self._set(predictionCol=value)
        return self

    def getMetricName(self) -> str:
        return self.getOrDefault("metricName")

    def isLargerBetter(self) -> bool:
        return self.getOrDefault("metricName") not in ("logLoss", "hammingLoss")

    def evaluate(self, dataset: Any) -> float:
        name = self.getOrDefault("metricName")
        labels = self._col(dataset, self.getOrDefault("labelCol"))
        preds = self._col(dataset, self.getOrDefault("predictionCol"))
        probs = None
        if name == "logLoss":
            probs = self._col(dataset, self.getOrDefault("probabilityCol"))
        weights = None
        if self.isSet("weightCol"):
            weights = self._col(dataset, self.getOrDefault("weightCol"))
        m = MulticlassMetrics.from_predictions(labels, preds, weights, probs)
        return m.evaluate(
            name,
            metric_label=self.getOrDefault("metricLabel"),
            beta=self.getOrDefault("beta"),
        )


class RegressionEvaluator(Evaluator):
    """pyspark.ml.evaluation.RegressionEvaluator parity."""

    metricName = Param("_", "metricName", "metric name (rmse/mse/mae/r2/var).",
                       TypeConverters.toString)
    labelCol = Param("_", "labelCol", "label column.", TypeConverters.toString)
    predictionCol = Param("_", "predictionCol", "prediction column.",
                          TypeConverters.toString)
    weightCol = Param("_", "weightCol", "weight column.", TypeConverters.toString)

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(metricName="rmse", labelCol="label",
                         predictionCol="prediction")
        self._set(**kwargs)

    def setMetricName(self, value: str) -> "RegressionEvaluator":
        self._set(metricName=value)
        return self

    def setLabelCol(self, value: str) -> "RegressionEvaluator":
        self._set(labelCol=value)
        return self

    def getMetricName(self) -> str:
        return self.getOrDefault("metricName")

    def isLargerBetter(self) -> bool:
        return self.getOrDefault("metricName") in ("r2", "var")

    def evaluate(self, dataset: Any) -> float:
        labels = self._col(dataset, self.getOrDefault("labelCol"))
        preds = self._col(dataset, self.getOrDefault("predictionCol"))
        weights = None
        if self.isSet("weightCol"):
            weights = self._col(dataset, self.getOrDefault("weightCol"))
        m = RegressionMetrics.from_predictions(labels, preds, weights)
        return m.evaluate(self.getOrDefault("metricName"))


class BinaryClassificationEvaluator(Evaluator):
    """pyspark.ml.evaluation.BinaryClassificationEvaluator parity
    (areaUnderROC / areaUnderPR from raw scores)."""

    metricName = Param("_", "metricName", "areaUnderROC or areaUnderPR.",
                       TypeConverters.toString)
    labelCol = Param("_", "labelCol", "label column.", TypeConverters.toString)
    rawPredictionCol = Param("_", "rawPredictionCol", "raw prediction column.",
                             TypeConverters.toString)

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(
            metricName="areaUnderROC",
            labelCol="label",
            rawPredictionCol="rawPrediction",
        )
        self._set(**kwargs)

    def getMetricName(self) -> str:
        return self.getOrDefault("metricName")

    def evaluate(self, dataset: Any) -> float:
        from sklearn.metrics import average_precision_score, roc_auc_score

        labels = self._col(dataset, self.getOrDefault("labelCol"))
        raw = self._col(dataset, self.getOrDefault("rawPredictionCol"))
        scores = raw[:, 1] if raw.ndim == 2 else raw
        if self.getOrDefault("metricName") == "areaUnderPR":
            return float(average_precision_score(labels, scores))
        return float(roc_auc_score(labels, scores))


__all__ = [
    "Evaluator",
    "MulticlassClassificationEvaluator",
    "RegressionEvaluator",
    "BinaryClassificationEvaluator",
]
