#
# Micro-batched transform server — the online inference front end
# (ROADMAP item 1).  The Snap ML hierarchy (PAPERS.md) applied to this
# runtime: request handling stays on host threads, compute coalesces
# onto the chips.  Concurrent single-row/small-batch requests for one
# model queue per model, a dispatcher thread concatenates them into ONE
# padded micro-batch (Clipper-style adaptive batching under the
# `serving_max_wait_ms` SLO knob), stages it through the small-batch
# direct fast path (parallel/mesh.py `_stage_small_direct`), runs the
# pinned model's `_transform_device` over the mesh, and scatters the
# per-request row slices back to each caller's future.
#
# The dispatcher is a STAGED PIPELINE with a bounded in-flight depth
# (`serving_pipeline_depth`; default auto from the measured idle-gap
# profile): the dispatcher thread coalesces, stages and launches device
# programs while a dedicated collect worker drains finished flights —
# at depth 3, batch N+2 stages while N+1 computes while N's outputs
# scatter.  Within a priority class a round-robin interleave rotates
# which model's due batch dispatches each round
# (`serving_pipeline_interleave`), so hundreds of pinned models share
# the mesh instead of serializing whole dispatch rounds; FIFO within
# each model's class is preserved.  Depth 1 fully serializes — the
# byte-parity baseline the CI overlap gate compares against.
# Admission control bounds the queue (`serving_max_queue` -> typed
# `ServingOverload`), and every failure degrades instead of dropping
# requests: an OOM halves the coalescing cap (floor: one row per
# device), a device loss routes through elastic recovery
# (resilience/elastic.py) and re-pins every resident model on the
# shrunken mesh, transients back off — queued requests survive all
# three, bounded by the retry policy's attempt budget.  A failure
# mid-pipeline hands back EXACTLY the affected flights' requests (the
# collect worker drains every in-flight batch into one fault, the
# dispatcher requeues them in dispatch order), so deeper pipelines
# never widen the blast radius past the batches actually in flight.
#
# Above the queue sits the closed-loop control plane (serving/
# control.py, ROADMAP item 2's actuator half): requests carry a
# priority class (`interactive` | `batch`) with per-class admission and
# weighted dispatch, the dispatcher ticks a per-model AIMD controller
# that scales the coalescing cap and max-wait against the measured
# `slo_burn_rate`, and sustained burn walks a brownout phase machine
# that sheds batch-class load first, then tightens interactive
# admission, re-admitting on recovery.
#
from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future
from typing import Any, Deque, Dict, List, Optional

import numpy as np

from ..config import get_config
from ..telemetry.locks import named_lock
from ..telemetry.registry import counter, gauge, histogram
from ..tracing import (
    adopt_trace_context,
    event,
    get_trace_events,
    mint_run_id,
    run_context,
    trace,
)
from ..utils import get_logger
from .control import PRIORITY_CLASSES, ServingController, resolve_priority
from .registry import ModelRegistry, PinnedModel

logger = get_logger("spark_rapids_ml_tpu.serving")

# sub-millisecond to seconds: serving latencies sit far below the
# default fit-scale buckets
_LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)
_BATCH_BUCKETS = (
    1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0,
    1024.0, 2048.0, 4096.0, 8192.0,
)

LATENCY = histogram(
    "serving_request_latency_seconds",
    "Per-request serving latency by phase (queue|dispatch|total)",
    buckets=_LATENCY_BUCKETS,
)
BATCH_ROWS = histogram(
    "serving_batch_rows",
    "Rows per coalesced serving dispatch",
    buckets=_BATCH_BUCKETS,
)
REQUESTS = counter(
    "serving_requests_total", "Admitted serving requests by model"
)
REJECTIONS = counter(
    "serving_rejections_total",
    "Rejected serving requests by model and reason",
)
SLO_BURN = gauge(
    "slo_burn_rate",
    "Measured over-p99-target request fraction / the 1% error budget, "
    "per model and window",
)
# queueing sensors for ROADMAP item 2's feedback controller (and the
# hang doctor's work-pending check): live queued requests per model,
# and how far past its intended wake deadline the dispatcher loop ran —
# a loop lagging its own deadlines is saturated before p99 shows it
QUEUE_DEPTH = gauge(
    "serving_queue_depth", "Requests queued awaiting dispatch, per model"
)
DISPATCH_LAG = gauge(
    "serving_dispatcher_lag_seconds",
    "Dispatcher wake overshoot past its intended deadline",
)
# staged-pipeline sensors: the resolved depth (conf or auto) and the
# live slot occupancy — occupancy pinned at depth means the pipeline is
# full and depth is the throughput limiter
PIPELINE_DEPTH = gauge(
    "serving_pipeline_depth",
    "Resolved in-flight batch depth of the staged dispatch pipeline",
)
PIPELINE_INFLIGHT = gauge(
    "serving_pipeline_inflight",
    "Dispatched batches currently occupying pipeline slots",
)

# window the report()'s serving utilization summary covers
_UTILIZATION_WINDOW_S = 60.0

# exact per-model latency samples for the p50/p99 report (the registry
# histogram's buckets are for Prometheus; percentiles in the per-model
# report come from real samples, bounded per model)
_REPORT_SAMPLES = 4096

# clean batches between each doubling of an OOM-shrunk coalescing cap
# back toward the configured value
_CAP_REGROW_BATCHES = 32

# hard ceiling on explicit `serving_pipeline_depth` values: past the
# pipeline's own stage count, extra depth only holds more staged
# batches resident in device memory and lengthens the requeue window a
# mid-flight failure must drain
_MAX_PIPELINE_DEPTH = 8
# the auto depth re-resolves from the serving idle-gap profile at most
# this often (the summarize() fold walks the interval deque)
_DEPTH_REFRESH_S = 1.0

# SLO burn-rate windows the sensor gauges report over (label value ->
# seconds); the budget is the 1% a p99 target implies
_SLO_WINDOWS = (("1m", 60.0), ("5m", 300.0))
_SLO_BUDGET = 0.01
# burn gauges refresh at most once per this many seconds per model (the
# window scan walks a bounded deque; no reason to pay it per request)
_SLO_REFRESH_S = 1.0

# slow-request span-tree captures retained (operator post-hoc view; the
# flight recorder keeps the longer process-wide history)
_MAX_SLOW_TRACES = 32

# sustained-overload detection: this many queue_full rejections inside
# the window trips ONE flight-recorder post-mortem (then the recorder's
# own per-reason cooldown applies)
_OVERLOAD_DUMP_COUNT = 20
_OVERLOAD_WINDOW_S = 5.0


class ServingOverload(RuntimeError):
    """Typed admission-control rejection: the request queue is at
    `serving_max_queue` (or the server is not accepting).  Callers shed
    load or retry with backoff; the request was NOT enqueued."""

    def __init__(self, model: str, reason: str, detail: str = "") -> None:
        super().__init__(
            f"serving overloaded ({reason}) for model {model!r}"
            + (f": {detail}" if detail else "")
        )
        self.model = model
        self.reason = reason


class _Request:
    __slots__ = (
        "model", "X", "rows", "t_enqueue", "future", "attempts", "req_id",
        "priority",
    )

    def __init__(
        self, model: str, X: np.ndarray, request_id: Optional[str] = None,
        priority: str = "interactive",
    ) -> None:
        self.model = model
        self.X = X
        # admission/dispatch class (resolved BEFORE construction):
        # decides which per-class deque the request queues on, which
        # admission bound applies, and whether a brownout sheds it
        self.priority = priority
        self.rows = int(X.shape[0])
        self.t_enqueue = time.perf_counter()
        self.future: Future = Future()
        # failed dispatch/collect rounds THIS request has been through:
        # the retry budget is per request, so one model's poisoned batch
        # can neither exhaust another model's attempts nor ride interleaved
        # successes to retry forever
        self.attempts = 0
        # the request's trace identity: minted at ingress (or adopted
        # from the caller's X-Request-Id), carried through the batch
        # dispatch spans and attached to the latency observations as an
        # exemplar — the join key between a latency bucket and a trace
        self.req_id = request_id or mint_run_id("req")


class _InFlight:
    """One dispatched micro-batch riding the async pipeline: the
    requests it carries, the staging layout, and the in-flight device
    outputs (or already-host outputs for host-path models)."""

    __slots__ = ("name", "model", "reqs", "rows", "stager", "dev",
                 "host_outs", "t_dispatch", "batch_id")

    def __init__(self, name, model, reqs, rows, stager, dev, host_outs,
                 t_dispatch, batch_id="") -> None:
        self.name = name
        # the dispatched model rides the flight: collect must fetch with
        # the SAME object the device outputs came from — a registry
        # re-resolve there could re-pin an evicted model (a full weight
        # re-replication on the latency-critical fetch path) or raise
        # for one unregistered between dispatch and collect, failing
        # finished, fetchable work
        self.model = model
        self.reqs = reqs
        self.rows = rows
        self.stager = stager
        self.dev = dev
        self.host_outs = host_outs
        self.t_dispatch = t_dispatch
        # the run id the batch's dispatch/collect spans carry: collect
        # re-enters it so the whole queue->scatter tree of one batch
        # correlates, and the slow-request capture filters by it
        self.batch_id = batch_id


class ServingServer:
    """The in-process serving runtime: a model registry, per-model
    request queues, and one dispatcher thread.  `register` models, then
    `start()`; submit work through a `ServingClient` (or `transform`
    directly).  `stop()` drains the queue before the thread exits."""

    def __init__(self, registry: Optional[ModelRegistry] = None) -> None:
        self.registry = registry or ModelRegistry()
        self._cv = named_lock("serving_dispatch", kind="condition")
        # two-level queues: model -> priority class -> deque.  The take
        # drains interactive heads first; admission bounds each class
        # separately (controller.admit), so _queued_cls tracks the
        # per-class share of the global _queued count
        self._queues: Dict[str, Dict[str, Deque[_Request]]] = {}
        self._queued = 0
        self._queued_cls: Dict[str, int] = {
            c: 0 for c in PRIORITY_CLASSES
        }
        # the feedback controller (serving/control.py): AIMD actuator
        # scales, the brownout phase machine, and the weighted-credit
        # class scheduler — ticked from the dispatcher loop
        self._controller = ServingController()
        self._ctl_last = 0.0
        self._running = False
        self._paused = False
        self._thread: Optional[threading.Thread] = None
        # True once the dispatcher's final cv-guarded exit check passed:
        # start() reads it UNDER the cv to decide revive-vs-spawn, so a
        # stop() whose join timed out mid-drain can never race a SECOND
        # dispatcher onto the same queues
        self._loop_done = True
        self._http = None
        # degradation state: the OOM-shrunk coalescing cap (None = use
        # the configured/byte-model cap), re-grown after sustained clean
        # batches so one transient OOM does not cap QPS for the process
        # lifetime
        self._shrunk_cap: Optional[int] = None
        self._clean_batches = 0
        self._batches = 0
        # staged-pipeline state (all under the dispatch cv): dispatched
        # flights awaiting collect in DISPATCH ORDER (the collect worker
        # drains the left end), whether the worker is mid-collect (that
        # flight still occupies a pipeline slot until its scatter
        # finishes), the fault-handback slot the worker fills for the
        # dispatcher's recovery path, and the worker's stop flag
        self._inflight: Deque[_InFlight] = collections.deque()
        self._collecting = False
        self._pipe_fault: Optional[tuple] = None
        self._collect_stop = False
        # per-class round-robin cursor for the model interleave: the
        # last model name dispatched per priority class
        self._rr_last: Dict[str, str] = {}
        # auto-depth memo (monotonic ts, resolved depth), refreshed at
        # most once per _DEPTH_REFRESH_S; _depth_last de-dups the gauge
        self._auto_memo: tuple = (0.0, 2)
        self._depth_last = 0
        self._lat: Dict[str, Deque[float]] = {}
        # per-INSTANCE request/rejection counts for report(): the
        # registry counters are process-global by Prometheus design, and
        # a fresh server must not report a predecessor's history
        self._req_counts: Dict[str, int] = {}
        self._rej_counts: Dict[str, int] = {}
        # per-instance brownout sheds by model -> class (the registry's
        # serving_shed_total counter is process-global)
        self._shed_counts: Dict[str, Dict[str, int]] = {}
        self._lock = named_lock("serving_report")  # report/latency state
        # request-scoped tracing + SLO sensing state:
        #   _lat_ts     per-model (monotonic_t, total_s) samples feeding
        #               the windowed burn-rate scan (bounded like _lat)
        #   _slo_last   per-model monotonic time of the last burn refresh
        #   _slow       captured span trees of slow requests (bounded)
        #   _overload_ts queue_full rejection timestamps for the
        #               sustained-overload flight-recorder trigger
        self._lat_ts: Dict[str, Deque[tuple]] = {}
        self._slo_last: Dict[str, float] = {}
        self._slow: Deque[Dict[str, Any]] = collections.deque(
            maxlen=_MAX_SLOW_TRACES
        )
        self._overload_ts: Deque[float] = collections.deque(
            maxlen=_OVERLOAD_DUMP_COUNT
        )
        # serving_slo_targets parse memo: (conf string, parsed dict)
        self._slo_targets_memo: tuple = ("", {})

    # -- registration (delegates; kept here so one object serves) ----------

    def register(self, name: str, model: Any, dtype: Any = np.float32,
                 n_features: Optional[int] = None,
                 transform: Any = None,
                 priority: Optional[str] = None) -> None:
        self.registry.register(name, model, dtype=dtype,
                               n_features=n_features, transform=transform,
                               priority=priority)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ServingServer":
        with self._cv:
            if self._running:
                return self
            self._running = True
            spawn = self._loop_done
            if spawn:
                self._loop_done = False
            self._cv.notify_all()
        if not spawn:
            # a previous stop() timed out mid-drain and its dispatcher
            # is still looping: setting _running under the cv revived it
            # (its exit check holds the same lock), so it resumes
            # serving — a second thread would race it on the queues.
            # The HTTP front end was torn down by that stop() and must
            # come back with the revive.
            self._maybe_start_http()
            return self
        # the dispatcher records spans/markers: adopt the starter's trace
        # buffer + run context so serving dispatch timings and resilience
        # markers land where the operator is looking
        adopt = adopt_trace_context()

        def _worker() -> None:
            adopt()
            self._loop()

        self._thread = threading.Thread(
            target=_worker, name="serving-dispatcher", daemon=True
        )
        self._thread.start()
        self._maybe_start_http()
        return self

    def _maybe_start_http(self) -> None:
        port = int(get_config("serving_port") or 0)
        if port > 0 and self._http is None:
            from .http import start_serving_http

            self._http = start_serving_http(self, port)

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        with self._cv:
            if not self._running:
                return
            self._running = False
            if not drain:
                doomed = [
                    r for by_cls in self._queues.values()
                    for q in by_cls.values() for r in q
                ]
                for name, by_cls in self._queues.items():
                    for q in by_cls.values():
                        q.clear()
                    QUEUE_DEPTH.set(0, model=name)
                self._queued = 0
                self._queued_cls = {c: 0 for c in PRIORITY_CLASSES}
            else:
                doomed = []
            self._cv.notify_all()
        for r in doomed:
            REJECTIONS.inc(model=r.model, reason="stopped")
            r.future.set_exception(
                ServingOverload(r.model, "stopped", "server shut down")
            )
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                logger.error(
                    f"serving dispatcher did not exit within {timeout:.0f}s "
                    "(drain backlog or wedged fetch); it will finish "
                    "draining in the background — start() would revive "
                    "it, not spawn a second dispatcher"
                )
            else:
                self._thread = None
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()
            self._http = None

    def pause(self) -> None:
        """Hold dispatch (requests keep queueing) — maintenance windows
        and deterministic coalescing in tests."""
        with self._cv:
            self._paused = True

    def resume(self) -> None:
        with self._cv:
            self._paused = False
            self._cv.notify_all()

    # -- submission ----------------------------------------------------------

    def submit(
        self, name: str, X: Any, request_id: Optional[str] = None,
        priority: Optional[str] = None,
    ) -> Future:
        """Enqueue one transform request; returns a Future resolving to
        `{output_col: np.ndarray}` with one row per input row.  Raises
        `ServingOverload` at the admission gate (never enqueued) and
        KeyError/ValueError for unknown models / wrong feature width /
        unknown priority classes.

        `priority` (`interactive` | `batch`) picks the admission class;
        unset it falls back to the model's registered default, then the
        `serving_priority_default` conf.  Batch-class admission is
        bounded to a `serving_batch_share` slice of the queue and is the
        first load a brownout sheds — background scoring can never
        starve the interactive path.

        Every admitted request gets a REQUEST ID (minted here, or
        `request_id` when the caller/HTTP ingress supplies one):
        exposed as `.request_id` on the returned Future, carried through
        the batch's dispatch spans, and attached to the latency
        observations as an exemplar."""
        from ..resilience import maybe_inject

        info = self.registry.info(name)  # KeyError for unknown models
        cls = resolve_priority(priority, info.get("priority"))
        # deterministic fault hook for the admission path itself
        # (docs/resilience.md `serving_admission`): raises BEFORE the
        # request touches a queue, so injection drills never leak a
        # half-admitted request
        maybe_inject("serving_admission")
        X = np.asarray(X)
        if X.ndim == 1:
            X = X[None, :]
        if X.ndim != 2 or X.shape[0] == 0:
            raise ValueError(
                f"serving input must be a non-empty (rows, features) "
                f"block, got shape {X.shape}"
            )
        want = info.get("n_features")
        if want is None:
            # width-blind registration: the first request's width becomes
            # canonical, so mixed-width traffic is rejected HERE instead
            # of poisoning a coalesced batch at np.concatenate
            want = self.registry.pin_feature_width(name, int(X.shape[1]))
        if int(X.shape[1]) != int(want):
            raise ValueError(
                f"model {name!r} expects {want} features, got {X.shape[1]}"
            )
        req = _Request(name, X, request_id=request_id, priority=cls)
        req.future.request_id = req.req_id
        overload_detail = ""
        with self._cv:
            if not self._running:
                REJECTIONS.inc(model=name, reason="stopped")
                raise ServingOverload(name, "stopped", "server not running")
            admitted, reason, detail = self._controller.admit(
                name, cls, self._queued, self._queued_cls[cls],
                self._max_queue(),
            )
            if not admitted:
                REJECTIONS.inc(model=name, reason=reason)
                with self._lock:
                    if reason == "shed":
                        by_cls = self._shed_counts.setdefault(name, {})
                        by_cls[cls] = by_cls.get(cls, 0) + 1
                    else:
                        self._rej_counts[name] = (
                            self._rej_counts.get(name, 0) + 1
                        )
                if reason == "queue_full":
                    overload_detail = self._note_overload_locked(name)
            else:
                by_cls = self._queues.setdefault(
                    name, {c: collections.deque() for c in PRIORITY_CLASSES}
                )
                by_cls[cls].append(req)
                self._queued += 1
                self._queued_cls[cls] += 1
                QUEUE_DEPTH.set(self._depth_locked(name), model=name)
                self._cv.notify_all()
        if not admitted:
            if reason == "shed":
                # brownout policy rejection: counted per class (the
                # controller's shed counter), never the overload dump —
                # shedding IS the controller working, not a failure
                self._controller.note_shed(name, cls)
            elif overload_detail:
                # the dump runs OUTSIDE the cv (it writes files); the
                # recorder's per-reason cooldown absorbs the rest of the
                # storm racing here
                from ..telemetry.flight_recorder import note_failure

                note_failure(
                    "serving_overload", detail=overload_detail, log=logger
                )
            raise ServingOverload(name, reason, detail)
        REQUESTS.inc(model=name)
        with self._lock:
            self._req_counts[name] = self._req_counts.get(name, 0) + 1
        return req.future

    def _note_overload_locked(self, name: str) -> str:
        """Called (under the cv) on every queue_full rejection: a burst
        of `_OVERLOAD_DUMP_COUNT` rejections inside `_OVERLOAD_WINDOW_S`
        is SUSTAINED overload — the typed failure the flight recorder
        should leave a black box for.  Returns the dump detail string
        when the threshold trips (the caller dumps after releasing the
        cv), else ''."""
        now = time.monotonic()
        self._overload_ts.append(now)
        if (
            len(self._overload_ts) == self._overload_ts.maxlen
            and now - self._overload_ts[0] <= _OVERLOAD_WINDOW_S
        ):
            return (
                f"model={name} queued={self._queued} "
                f"max_queue={self._max_queue()} "
                f"{len(self._overload_ts)} rejections in "
                f"{now - self._overload_ts[0]:.2f}s"
            )
        return ""

    def transform(self, name: str, X: Any,
                  timeout: Optional[float] = None,
                  request_id: Optional[str] = None,
                  priority: Optional[str] = None) -> Dict[str, np.ndarray]:
        """Blocking convenience over `submit`."""
        return self.submit(
            name, X, request_id=request_id, priority=priority
        ).result(timeout=timeout)

    # -- report --------------------------------------------------------------

    def _model_entry(self, name: str) -> Dict[str, Any]:
        """One model's report entry (the shared body of `report()` and
        `model_detail`)."""
        with self._lock:
            lat = list(self._lat.get(name, ()))
            requests = self._req_counts.get(name, 0)
            rejections = self._rej_counts.get(name, 0)
            shed = dict(self._shed_counts.get(name, ()))
        entry: Dict[str, Any] = {
            # per-instance counts: the prometheus families are
            # process-global, a fresh server must not report a
            # predecessor's history
            "requests": requests,
            "rejections_queue_full": rejections,
            # O(1) membership probe — the sorted pinned_names() list
            # costs O(n log n) per poll at hundreds of pinned models
            "pinned": self.registry.is_pinned(name),
        }
        if lat:
            srt = sorted(lat)

            def _pct(p: float) -> float:
                i = min(len(srt) - 1, int(round(p * (len(srt) - 1))))
                return srt[i]

            entry.update(
                latency_samples=len(srt),
                p50_ms=round(_pct(0.50) * 1e3, 3),
                p99_ms=round(_pct(0.99) * 1e3, 3),
                mean_ms=round(sum(srt) / len(srt) * 1e3, 3),
            )
        target_s = self._slo_target_s(name)
        if target_s > 0:
            entry["slo_p99_target_ms"] = round(target_s * 1e3, 3)
            for window, _span in _SLO_WINDOWS:
                burn = SLO_BURN.value(
                    default=None, model=name, window=window
                )
                if burn is not None:
                    entry[f"slo_burn_{window}"] = burn
        # drift summary (monitor/): rows observed, overall score, top
        # drifting columns — absent for models without a registered
        # fit-time baseline
        from ..monitor import MONITOR

        drift = MONITOR.summary(name)
        if drift is not None:
            entry["drift"] = drift
        # the control plane's actuator state for THIS model: the
        # effective (scaled) cap and max-wait the dispatcher uses right
        # now, the brownout phase, per-class shed counts, and the
        # padding classes compiled programs are reused across
        st = self._controller.model_state(name)
        entry["controller"] = {
            "cap": self._batch_cap(name, self._safe_info(name)),
            "max_wait_ms": round(self._max_wait_s(name) * 1e3, 3),
            "brownout_phase": st["brownout_phase"],
            "shed": shed,
            "padding_classes": st["padding_classes"],
        }
        return entry

    def report(self) -> Dict[str, Any]:
        """Per-model serving report: request/batch counts, mean batch
        rows, and exact p50/p99 latency over the last `_REPORT_SAMPLES`
        requests — the operator-facing SLO view (docs/serving.md)."""
        out: Dict[str, Any] = {}
        for name in self.registry.names():
            out[name] = self._model_entry(name)
        with self._lock:
            n_slow = len(self._slow)
            shed_total = {
                cls: sum(
                    by_cls.get(cls, 0)
                    for by_cls in self._shed_counts.values()
                )
                for cls in PRIORITY_CLASSES
            }
        ctl = self._controller
        share = ctl.batch_share()
        with self._cv:
            pipeline = {
                "depth": self._pipeline_depth(),
                "inflight": len(self._inflight)
                + (1 if self._collecting else 0),
                "interleave": bool(
                    get_config("serving_pipeline_interleave")
                ),
            }
        out["_totals"] = {
            "batches": self._batches,
            "queued": self._queued,
            "pinned_bytes": self.registry.pinned_bytes(),
            "slow_traces": n_slow,
            "pipeline": pipeline,
            "controller": {
                "enabled": ctl.enabled(),
                # contested dispatch rounds split credit-weighted:
                # interactive always holds a full share, batch accrues
                # `serving_batch_share` credit per interactive win
                "priority_shares": {"interactive": 1.0, "batch": share},
                "shed": shed_total,
                "brownout": ctl.brownout_summary(),
            },
        }
        # the serving utilization view (telemetry/utilization.py): how
        # busy the device was over the recent window and what the idle
        # gaps are attributable to (lock waits, host-side dispatch)
        from ..telemetry import utilization

        util = utilization.summarize(
            window_s=_UTILIZATION_WINDOW_S, scope="serving",
            domain="serving",
        )
        if util:
            out["_totals"]["utilization"] = util
        # the pod-observatory view (telemetry/fleet.py): last pod pass
        # report + live peer clock-offset table — empty single-process
        try:
            from ..telemetry import fleet

            pod = fleet.fleet_summary()
            if pod:
                out["_totals"]["pod"] = pod
        except Exception:
            pass
        return out

    def pipeline_info(self) -> Dict[str, Any]:
        """The staged pipeline's operator view (`GET /v1/pipeline`):
        resolved depth (explicit conf or auto), the conf value it came
        from, live slot occupancy, the interleave flag, and the serving
        utilization window — busy fraction plus the idle-gap table the
        depth-tuning guidance in docs/serving.md keys off."""
        with self._cv:
            out: Dict[str, Any] = {
                "depth": self._pipeline_depth(),
                "depth_conf": int(
                    get_config("serving_pipeline_depth") or 0
                ),
                "inflight": len(self._inflight)
                + (1 if self._collecting else 0),
                "interleave": bool(
                    get_config("serving_pipeline_interleave")
                ),
                "batches": self._batches,
            }
        from ..telemetry import utilization

        util = utilization.summarize(
            window_s=_UTILIZATION_WINDOW_S, domain="serving"
        )
        if util:
            out["utilization"] = util
        return out

    def model_detail(self, name: str) -> Dict[str, Any]:
        """Everything about ONE served model — pin status and accounted
        bytes, the latency/SLO report entry, and the drift summary (the
        `GET /v1/models/<name>` payload) — built for THIS model only
        (a dashboard polling every model must not pay a full all-model
        report per request).  KeyError for unknown names."""
        info = self.registry.pin_info(name)  # KeyError gate
        entry = self._model_entry(name)
        return {"model": name, **info, **entry}

    # -- sizing --------------------------------------------------------------

    def _max_queue(self) -> int:
        return max(1, int(get_config("serving_max_queue")))

    def _max_wait_s(self, name: Optional[str] = None) -> float:
        """Coalescing max-wait in seconds; with `name`, scaled by the
        controller's AIMD wait actuator (burn shrinks it so batches
        dispatch earlier and smaller)."""
        wait = max(0.0, float(get_config("serving_max_wait_ms"))) / 1e3
        if name is not None:
            wait *= self._controller.wait_scale(name)
        return wait

    def _depth_locked(self, name: str) -> int:
        """Queued requests for `name` across both priority classes
        (called under the cv; feeds `serving_queue_depth`)."""
        return sum(len(q) for q in self._queues.get(name, {}).values())

    def _safe_info(self, name: str) -> Optional[Dict[str, Any]]:
        """Registration facts, or None for a model unregistered while
        requests were still queued — the dispatcher must keep running
        and FAIL those requests (via the dispatch-time KeyError), never
        die on the lookup."""
        try:
            return self.registry.info(name)
        except KeyError:
            return None

    def _base_cap(self, info: Optional[Dict[str, Any]]) -> int:
        """Rows one coalesced dispatch may carry BEFORE SLO control:
        the configured cap, bounded by the byte model every staged
        transfer is sized by (`host_batch_bytes` / row bytes), then by
        the OOM-degraded shrink cap.  The OOM shrink stays here — it is
        the emergency memory actuator the AIMD scale layers on top of,
        never replaces."""
        from ..streaming import chunk_rows_for

        cap = max(1, int(get_config("serving_max_batch_rows")))
        d = info.get("n_features") if info else None
        if d:
            cap = min(
                cap,
                int(chunk_rows_for(int(d), np.dtype(info["dtype"]).itemsize)),
            )
        if self._shrunk_cap is not None:
            cap = min(cap, self._shrunk_cap)
        return max(1, cap)

    def _batch_cap(
        self, name: str, info: Optional[Dict[str, Any]]
    ) -> int:
        """The effective coalescing cap: the base cap scaled by the
        controller's AIMD cap actuator for this model."""
        cap = self._base_cap(info)
        scale = self._controller.cap_scale(name)
        if scale < 1.0:
            cap = max(1, int(cap * scale))
        return cap

    def _cap_wait(
        self, name: str, info: Optional[Dict[str, Any]]
    ) -> tuple:
        """Effective (cap, max_wait_s) for one model in ONE controller
        lock acquisition (`controller.scales`).  The coalesce scan reads
        both per queued model per round — at hundreds of pinned models
        the separate `cap_scale`/`wait_scale` reads would double the
        hot-path lock traffic, and a controller tick landing between
        them could pair an old cap with a new wait.  Scale changes
        therefore apply at the NEXT coalesce, atomically, never to a
        batch mid-flight."""
        cap_scale, wait_scale = self._controller.scales(name)
        cap = self._base_cap(info)
        if cap_scale < 1.0:
            cap = max(1, int(cap * cap_scale))
        wait = max(0.0, float(get_config("serving_max_wait_ms"))) / 1e3
        return cap, wait * wait_scale

    def _oom_floor(self) -> int:
        """Smallest useful coalescing cap: one row per active device
        (the same floor the transform chunk loop shrinks to)."""
        from ..parallel.mesh import active_devices

        return max(1, len(active_devices()))

    # -- pipeline depth ------------------------------------------------------

    def _pipeline_depth(self) -> int:
        """How many dispatched batches may occupy pipeline slots at
        once.  Explicit `serving_pipeline_depth` values clamp to
        [1, _MAX_PIPELINE_DEPTH] (1 = fully serialized, the byte-parity
        baseline); 0 resolves automatically from the serving idle-gap
        profile.  Called under the cv (the memo/gauge state rides the
        dispatcher)."""
        raw = int(get_config("serving_pipeline_depth") or 0)
        if raw >= 1:
            depth = min(raw, _MAX_PIPELINE_DEPTH)
        else:
            depth = self._auto_depth()
        if depth != self._depth_last:
            self._depth_last = depth
            PIPELINE_DEPTH.set(depth)
        return depth

    def _auto_depth(self) -> int:
        """Auto depth from the utilization timeline: start at 2 (the
        classic collect-N-while-dispatching-N+1 overlap) and deepen
        while the gap table says host-side serving phases are stealing
        device-idle seconds — >10% of the observed wall buys one extra
        slot, >25% a second, bounded by `serving_pipeline_max_depth`.
        Rate-limited by `_DEPTH_REFRESH_S`; never raises (the profile
        is advice, not a dependency)."""
        now = time.monotonic()
        ts, memo = self._auto_memo
        if now - ts < _DEPTH_REFRESH_S:
            return memo
        depth = 2
        try:
            from ..telemetry import utilization

            util = utilization.summarize(
                window_s=_UTILIZATION_WINDOW_S, domain="serving"
            )
            wall = float(util.get("wall_s", 0.0)) if util else 0.0
            if wall > 0:
                host_stolen = sum(
                    float(row.get("stolen_s", 0.0))
                    for row in util.get("gap_attribution", ())
                    if row.get("kind") in (
                        "dispatch", "stage", "compute", "collect",
                        "scatter", "host_prep",
                    )
                )
                frac = host_stolen / wall
                if frac > 0.10:
                    depth += 1
                if frac > 0.25:
                    depth += 1
            cap = max(2, int(get_config("serving_pipeline_max_depth")))
            depth = min(depth, cap)
        except Exception:
            depth = 2
        self._auto_memo = (now, depth)
        return depth

    # -- dispatcher ----------------------------------------------------------

    def _ready_name_locked(self, now: float, draining: bool) -> Optional[str]:
        """The queued model whose head request is due: past the (AIMD-
        scaled, per-model) max-wait SLO, a full batch already queued, or
        the server draining.  When BOTH classes hold a due head the
        controller's weighted credit picks the class — batch gets
        `serving_batch_share` credit per interactive win, so neither
        class starves the other.  Within the chosen class, the
        `serving_pipeline_interleave` round-robin rotates across ALL
        due models (no model starves behind a hot one AND no hot model
        monopolizes consecutive pipeline slots); with the interleave
        off, the oldest due head wins outright."""
        due: Dict[str, List[tuple]] = {}  # class -> [(t_enqueue, name)]
        for name, by_cls in self._queues.items():
            if not any(by_cls.values()):
                continue
            info = self._safe_info(name)
            cap, wait = self._cap_wait(name, info)
            rows = 0
            full = False
            for cls in PRIORITY_CLASSES:
                for r in by_cls[cls]:
                    rows += r.rows
                    if rows >= cap:
                        full = True
                        break
                if full:
                    break
            for cls in PRIORITY_CLASSES:
                q = by_cls[cls]
                if not q:
                    continue
                head = q[0]
                ready = (
                    draining
                    or info is None  # unregistered: dispatch fails it NOW
                    or (now - head.t_enqueue) >= wait
                    or full
                )
                if ready:
                    due.setdefault(cls, []).append((head.t_enqueue, name))
        if not due:
            return None
        if len(due) == 1:
            cls = next(iter(due))
        elif not self._controller.enabled():
            # plain oldest-head-first across classes
            cls = min((min(v), c) for c, v in due.items())[1]
        else:
            cls = self._controller.pick_class()
        entries = due[cls]
        if len(entries) == 1 or not bool(
            get_config("serving_pipeline_interleave")
        ):
            return min(entries)[1]
        # cyclic pick: the first due name (sorted order) strictly after
        # the last model this class dispatched, wrapping to the start —
        # per-model FIFO is untouched (each model's class deque still
        # drains front-first), only the CROSS-model order rotates
        names = sorted({n for _, n in entries})
        last = self._rr_last.get(cls, "")
        choice = next((n for n in names if n > last), names[0])
        self._rr_last[cls] = choice
        return choice

    def _take_batch_locked(self, name: str) -> List[_Request]:
        by_cls = self._queues[name]
        cap = self._batch_cap(name, self._safe_info(name))
        reqs: List[_Request] = []
        rows = 0
        # interactive heads coalesce first; batch-class rows fill the
        # remaining cap, so a shared dispatch never displaces the
        # latency-sensitive work that triggered it
        for cls in PRIORITY_CLASSES:
            q = by_cls[cls]
            while q and (not reqs or rows + q[0].rows <= cap):
                r = q.popleft()
                self._queued -= 1
                self._queued_cls[cls] -= 1
                if r.future.cancelled():
                    continue  # the caller gave up while it queued
                reqs.append(r)
                rows += r.rows
        QUEUE_DEPTH.set(self._depth_locked(name), model=name)
        return reqs

    def _requeue_front(self, reqs: List[_Request]) -> None:
        with self._cv:
            for r in reversed(reqs):
                by_cls = self._queues.setdefault(
                    r.model,
                    {c: collections.deque() for c in PRIORITY_CLASSES},
                )
                by_cls[r.priority].appendleft(r)
                self._queued += 1
                self._queued_cls[r.priority] += 1
            for name in {r.model for r in reqs}:
                QUEUE_DEPTH.set(self._depth_locked(name), model=name)
            self._cv.notify_all()

    def _next_deadline_locked(self, now: float) -> float:
        if self._paused and self._running:
            return 0.5  # resume() notifies; no deadline to honor
        deadline = None
        for name, by_cls in self._queues.items():
            wait = self._max_wait_s(name)
            for q in by_cls.values():
                if q:
                    due = q[0].t_enqueue + wait
                    deadline = (
                        due if deadline is None else min(deadline, due)
                    )
        if deadline is None:
            return 0.5
        return max(1e-4, min(deadline - now, 0.5))

    def _lag_locked(self, name: str, now: float) -> float:
        """How far past its intended dispatch deadline the loop is for
        `name`'s oldest head — published on EVERY dispatch round, so the
        gauge stays live under a saturated queue instead of freezing at
        the last idle wake's overshoot."""
        heads = [
            q[0].t_enqueue
            for q in self._queues.get(name, {}).values() if q
        ]
        if not heads:
            return 0.0
        return round(
            max(0.0, now - (min(heads) + self._max_wait_s(name))), 6
        )

    def _loop(self) -> None:
        # the staged pipeline's two threads: THIS thread coalesces,
        # stages and launches device programs; the collect worker drains
        # finished flights (fetch + scatter).  The worker adopts the
        # dispatcher's (already-adopted) trace buffer, so one batch's
        # dispatch->collect span tree stays one tree no matter which
        # thread recorded which half.
        with self._cv:
            self._collect_stop = False
        adopt = adopt_trace_context()

        def _collector() -> None:
            adopt()
            self._collect_loop()

        collector = threading.Thread(
            target=_collector, name="serving-collect", daemon=True
        )
        collector.start()
        while True:
            batch: Optional[List[_Request]] = None
            fault: Optional[tuple] = None
            with self._cv:
                while True:
                    now = time.perf_counter()
                    # a collect-side failure outranks new work: consume
                    # the handback (plus any flight that raced in after
                    # the worker filled it — its requests are LATER in
                    # FIFO order than the failed ones, so letting it
                    # complete would reorder a model's class queue) and
                    # recover outside the cv
                    if self._pipe_fault is not None:
                        e, reqs = self._pipe_fault
                        self._pipe_fault = None
                        reqs = list(reqs)
                        for fl in self._inflight:
                            reqs.extend(fl.reqs)
                        self._inflight.clear()
                        PIPELINE_INFLIGHT.set(
                            1 if self._collecting else 0
                        )
                        self._cv.notify_all()
                        fault = (e, reqs)
                        break
                    draining = not self._running
                    depth = self._pipeline_depth()
                    slots = len(self._inflight) + (
                        1 if self._collecting else 0
                    )
                    blocked = slots >= depth
                    name = (
                        None
                        if blocked or (self._paused and self._running)
                        else self._ready_name_locked(now, draining)
                    )
                    if name is not None:
                        # loop-lag publishes on EVERY dispatch round
                        # (not only the timed-out idle wake below): a
                        # saturated dispatcher never idles, and a gauge
                        # frozen at the last idle overshoot would hide
                        # exactly the lag the controller acts on
                        DISPATCH_LAG.set(self._lag_locked(name, now))
                        batch = self._take_batch_locked(name) or None
                        if batch is None:
                            # nothing but cancelled requests: re-scan
                            continue
                        break
                    if (
                        draining and self._queued == 0
                        and not self._inflight and not self._collecting
                    ):
                        break
                    # with the pipeline full the head deadline is moot
                    # (no slot to dispatch into); wait for the worker's
                    # slot-free notify instead of spinning on it
                    t_wait = (
                        0.5 if blocked
                        else self._next_deadline_locked(now)
                    )
                    if not self._cv.wait(timeout=t_wait):
                        # timed-out idle tick: break to the outer loop so
                        # _refresh_slo_all runs (burn gauges must decay
                        # when traffic STOPS; with work ready the very
                        # next inner pass picks it up).  The overshoot
                        # past the intended deadline is the loop-lag
                        # sensor: a dispatcher that cannot wake on time
                        # is saturated before p99 shows it.
                        DISPATCH_LAG.set(
                            round(
                                max(
                                    0.0,
                                    time.perf_counter() - now - t_wait,
                                ),
                                6,
                            )
                        )
                        break
            if fault is not None:
                self._recover_guarded(fault[0], list(fault[1]))
                self._controller_tick()
                continue
            if batch is None:
                with self._cv:
                    if (
                        not self._running and self._queued == 0
                        and not self._inflight and not self._collecting
                        and self._pipe_fault is None
                    ):
                        # final exit decision under the cv: start() reads
                        # _loop_done under the same lock, so revive and
                        # exit cannot interleave into a dead server
                        self._collect_stop = True
                        self._loop_done = True
                        self._cv.notify_all()
                        collector_done = True
                    else:
                        collector_done = False
                if collector_done:
                    collector.join(timeout=10.0)
                    return
                self._refresh_slo_all()
                self._controller_tick()
                continue
            # a dispatch error belongs to THIS batch only — earlier
            # flights are already computing and stay in the pipeline for
            # the worker to collect, so a fatal error for one model can
            # never fail another model's healthy in-flight work
            try:
                flight = self._dispatch(batch)
            except Exception as e:
                self._recover_guarded(e, list(batch))
            else:
                with self._cv:
                    self._inflight.append(flight)
                    PIPELINE_INFLIGHT.set(
                        len(self._inflight)
                        + (1 if self._collecting else 0)
                    )
                    self._cv.notify_all()
            # feedback step AFTER the round's dispatch: the busy path
            # must tick too — an overloaded dispatcher never reaches
            # the idle branch, and that is exactly when control matters
            # (rate-limited inside, so the hot loop pays ~0)
            self._controller_tick()

    def _collect_loop(self) -> None:
        """The collect worker: pop the oldest in-flight batch, fetch +
        scatter it, repeat.  Runs until the dispatcher's exit path sets
        `_collect_stop` with the pipeline drained.  A collect failure
        drains EVERY in-flight flight into one `_pipe_fault` handback
        (requests in dispatch order — oldest first, so the dispatcher's
        front-requeue preserves per-model/per-class FIFO) and parks
        until the dispatcher consumes it; the worker itself never
        recovers (recovery requeues and repins — dispatcher-side state
        transitions)."""
        while True:
            with self._cv:
                while not self._inflight or self._pipe_fault is not None:
                    if (
                        self._collect_stop
                        and not self._inflight
                        and self._pipe_fault is None
                    ):
                        return
                    self._cv.wait(timeout=0.5)
                flight = self._inflight.popleft()
                # the popped flight still occupies a pipeline slot until
                # its scatter finishes — without this, depth 1 would let
                # the dispatcher stage batch N+1 while N scatters, and
                # "fully serialized" would be a lie
                self._collecting = True
                PIPELINE_INFLIGHT.set(len(self._inflight) + 1)
                self._cv.notify_all()
            try:
                self._collect(flight)
            except Exception as e:
                with self._cv:
                    reqs = list(flight.reqs)
                    for fl in self._inflight:
                        reqs.extend(fl.reqs)
                    self._inflight.clear()
                    self._collecting = False
                    PIPELINE_INFLIGHT.set(0)
                    self._pipe_fault = (e, reqs)
                    self._cv.notify_all()
            else:
                with self._cv:
                    self._collecting = False
                    self._batches += 1
                    PIPELINE_INFLIGHT.set(len(self._inflight))
                    self._cv.notify_all()
                self._note_clean_batch()

    # -- dispatch / collect --------------------------------------------------

    @staticmethod
    def _req_id_detail(reqs: List[_Request]) -> str:
        """Bounded request-id list for span details (the ids are the
        exemplar join keys; a 4096-row batch must not serialize 4096 of
        them into one detail string)."""
        ids = [r.req_id for r in reqs[:8]]
        more = len(reqs) - len(ids)
        return ",".join(ids) + (f",+{more}" if more > 0 else "")

    def _dispatch(self, reqs: List[_Request]) -> _InFlight:
        """Stage one coalesced batch and launch its device program (jax
        dispatch is async — the transfer/compute are in flight when this
        returns).  Host-path models (no `_transform_device`) compute
        synchronously here instead.

        The whole batch runs under a minted `batch-<hex>` run id: the
        dispatch span and its coalesce/stage/compute children (and the
        collect/scatter spans next round) all carry it, so one request's
        path through the server reconstructs as one tree — the
        slow-request capture and the flight recorder both key off it."""
        from ..telemetry import utilization

        name = reqs[0].model
        pinned: PinnedModel = self.registry.resolve(name)
        rows = sum(r.rows for r in reqs)
        t0 = time.perf_counter()
        try:
            return self._dispatch_timed(reqs, name, pinned, rows, t0)
        finally:
            # the host-side dispatch window (coalesce + stage + the
            # async compute launch) feeds the serving utilization
            # timeline; the device window lands at collect
            utilization.note_interval(
                "dispatch", t0, time.perf_counter(), cause=name,
                domain="serving",
            )

    def _dispatch_timed(
        self, reqs: List[_Request], name: str, pinned: PinnedModel,
        rows: int, t0: float,
    ) -> _InFlight:
        from ..parallel.mesh import RowStager
        from ..resilience import maybe_inject
        from ..telemetry import utilization

        with run_context(prefix="batch") as batch_id:
            with trace(f"serving_dispatch[{name}]", logger):
                event(
                    f"serving_batch[{name}]",
                    detail=(
                        f"rows={rows} reqs={len(reqs)} "
                        f"ids={self._req_id_detail(reqs)}"
                    ),
                )
                maybe_inject("serving_dispatch")
                with trace("serving_coalesce", logger):
                    X = (
                        reqs[0].X
                        if len(reqs) == 1
                        else np.concatenate([r.X for r in reqs], axis=0)
                    )
                BATCH_ROWS.observe(rows, model=name)
                if not pinned.device:
                    t_c = time.perf_counter()
                    with trace("serving_compute", logger):
                        X = np.ascontiguousarray(X, dtype=pinned.dtype)
                        outs = pinned.transform_fn(X)
                    utilization.note_interval(
                        "compute", t_c, time.perf_counter(), cause=name,
                        domain="serving",
                    )
                    return _InFlight(
                        name, pinned.model, reqs, rows, None, None, outs,
                        t0, batch_id,
                    )
                # telemetry=False: the per-staging instrumentation (device
                # census, dataset_stagings bump, byte prediction) is fit-
                # scale bookkeeping a request-rate micro-batch must not pay
                t_s = time.perf_counter()
                with trace("serving_stage", logger):
                    # padding classes: force the {1,1.5}x2^k bucket grid
                    # (regardless of the global shape_bucketing conf) so
                    # churning coalesced sizes reuse ONE compiled
                    # transform program per bucket — the jit-audit
                    # zero-recompile guarantee extended to serving
                    bucketing = None
                    if self._controller.padding_enabled():
                        self._controller.note_bucket(name, rows)
                        bucketing = True
                    st = RowStager.for_replicated(
                        rows, pinned.mesh, bucketing=bucketing,
                        telemetry=False,
                    )
                    Xs = st.stage(np.ascontiguousarray(X), pinned.dtype)
                t_c = time.perf_counter()
                utilization.note_interval(
                    "stage", t_s, t_c, cause=name, domain="serving"
                )
                with trace("serving_compute", logger):
                    dev = pinned.model._transform_device(Xs)
                # the compute window here is only the async LAUNCH; the
                # device series (noted at collect) carries the real
                # compute span.  It still matters for depth tuning: a
                # launch stealing idle seconds means dispatch-side
                # Python is the bottleneck, not the chips
                utilization.note_interval(
                    "compute", t_c, time.perf_counter(), cause=name,
                    domain="serving",
                )
        return _InFlight(
            name, pinned.model, reqs, rows, st, dev, None, t0, batch_id
        )

    def _collect(self, flight: _InFlight) -> None:
        """Fetch one in-flight batch (the sync point) and scatter each
        request's row slice to its future.  Futures resolve only after
        EVERY column fetched, so a mid-fetch failure retries the whole
        batch without partial results escaping.  Runs under the batch's
        run id, so the collect/scatter spans join the dispatch tree."""
        with run_context(flight.batch_id or None):
            self._collect_traced(flight)

    def _collect_traced(self, flight: _InFlight) -> None:
        from ..resilience import maybe_inject
        from ..telemetry import utilization

        # deterministic fault hook for the collect/scatter phase
        # (docs/resilience.md `serving_collect`): fires on the collect
        # worker while LATER batches may still be in flight behind this
        # one — the mid-pipeline failure drill.  Every in-flight batch's
        # requests ride the fault handback to the dispatcher's requeue.
        maybe_inject("serving_collect")
        if flight.host_outs is not None:
            outs = flight.host_outs
        else:
            t_fetch = time.perf_counter()
            with trace(f"serving_collect[{flight.name}]", logger):
                outs = flight.model._fetch_transform_outputs(
                    flight.stager, flight.dev
                )
            t_fetched = time.perf_counter()
            # the fetch wait + device->host transfer window: the collect
            # worker's share of the gap table (a "collect" series
            # stealing idle seconds = the worker, not depth, limits)
            utilization.note_interval(
                "collect", t_fetch, t_fetched, cause=flight.name,
                domain="serving",
            )
            # the window from the batch's dispatch to the fetch
            # completing is device-or-transfer activity: the serving
            # timeline's "device" series (host prep rode in at dispatch)
            utilization.note_interval(
                "device",
                min(flight.t_dispatch, t_fetch),
                t_fetched,
                cause=flight.name,
                domain="serving",
            )
        t_done = time.perf_counter()
        slow_s = (
            max(0.0, float(get_config("serving_slow_trace_ms"))) / 1e3
        )
        slow_hits: List[tuple] = []
        lo = 0
        with self._lock:
            lat = self._lat.setdefault(
                flight.name, collections.deque(maxlen=_REPORT_SAMPLES)
            )
            lat_ts = self._lat_ts.setdefault(
                flight.name, collections.deque(maxlen=_REPORT_SAMPLES)
            )
        with trace("serving_scatter", logger):
            now_mono = time.monotonic()
            for r in flight.reqs:
                sl = {c: v[lo : lo + r.rows] for c, v in outs.items()}
                lo += r.rows
                if r.future.done():
                    # cancelled by the caller while queued/in flight, or
                    # resolved by an earlier partially-scattered attempt a
                    # failure requeued — either way, publishing would raise
                    # InvalidStateError and poison the co-batched requests
                    continue
                q_s = max(flight.t_dispatch - r.t_enqueue, 0.0)
                d_s = max(t_done - flight.t_dispatch, 0.0)
                tot = max(t_done - r.t_enqueue, 0.0)
                LATENCY.observe(
                    q_s, exemplar=r.req_id, model=flight.name, phase="queue"
                )
                LATENCY.observe(
                    d_s, exemplar=r.req_id,
                    model=flight.name, phase="dispatch",
                )
                LATENCY.observe(
                    tot, exemplar=r.req_id, model=flight.name, phase="total"
                )
                with self._lock:
                    lat.append(tot)
                    lat_ts.append((now_mono, tot))
                if slow_s > 0 and tot >= slow_s:
                    slow_hits.append((r.req_id, tot))
                try:
                    r.future.set_result(sl)
                except Exception:
                    pass  # cancelled in the race window; result dropped
        # the slice-and-resolve window ("scatter" series): stolen idle
        # seconds here mean the futures' consumers are the gap, which
        # more depth cannot buy back
        utilization.note_interval(
            "scatter", t_done, time.perf_counter(), cause=flight.name,
            domain="serving",
        )
        if slow_hits:
            self._capture_slow(flight, slow_hits)
        # drift monitor fold (monitor/): the batch's already-decoded
        # host rows + its output columns fold into the model's sliding
        # window sketches HERE — on the dispatcher's collect phase,
        # after the next batch's device work is already in flight, so
        # the device hot path pays nothing (host-tier only, bounded
        # memory; the fold itself is buffered-amortized — bench `drift`
        # section measures us/row)
        self._observe_drift(flight, outs)
        # refresh EVERY served model, not just this flight's: a model
        # whose traffic stopped must decay even while the dispatcher
        # stays busy with other models' batches (the per-model rate
        # limit inside _update_slo bounds the cost to ~1 scan/s/model)
        self._refresh_slo_all()

    def _observe_drift(
        self, flight: _InFlight, outs: Dict[str, np.ndarray]
    ) -> None:
        """Fold one served batch into the drift monitor: the decoded
        request rows (feature side) and the batch's output columns
        (prediction side).  No-op for models without a registered
        baseline; never fails the scatter."""
        from ..monitor import MONITOR

        if not MONITOR.tracks(flight.name):
            return
        try:
            for r in flight.reqs:
                MONITOR.observe(flight.name, r.X)
            MONITOR.observe_output(flight.name, outs)
        except Exception as e:  # monitoring must never fail serving
            logger.warning(f"drift fold failed ({e})")

    def _capture_slow(
        self, flight: _InFlight, hits: List[tuple]
    ) -> None:
        """A request breached the `serving_slow_trace_ms` threshold:
        keep the batch's FULL span tree (queue wait is implicit in the
        phase observations; dispatch -> coalesce/stage/compute ->
        collect/scatter are the recorded spans, filtered by the batch's
        run id from this dispatcher thread's bounded buffer) plus the
        breaching request ids — the operator's "what did THAT request
        hit" view, without pre-arming anything."""
        from ..telemetry.report import span_tree

        try:
            events = [
                e for e in get_trace_events()
                if e.run_id == flight.batch_id
            ]
            entry = {
                "model": flight.name,
                "batch_id": flight.batch_id,
                "batch_rows": flight.rows,
                "requests": [
                    {"request_id": rid, "total_ms": round(tot * 1e3, 3)}
                    for rid, tot in hits
                ],
                "spans": span_tree(events),
            }
            with self._lock:
                self._slow.append(entry)
            event(
                f"serving_slow[{flight.name}]",
                detail=self._req_id_detail(
                    [r for r in flight.reqs
                     if r.req_id in {rid for rid, _ in hits}]
                ),
                log=logger,
            )
        except Exception as e:  # capture must never fail the scatter
            logger.warning(f"slow-request capture failed ({e})")

    def slow_traces(self) -> List[Dict[str, Any]]:
        """Captured span trees of requests that breached
        `serving_slow_trace_ms` (newest last, bounded)."""
        with self._lock:
            return list(self._slow)

    # -- SLO sensing ---------------------------------------------------------

    def _slo_target_s(self, name: str) -> float:
        """The model's declared p99 target in seconds (0 = no SLO):
        `serving_slo_targets` ("model=ms,...") overrides the
        `serving_slo_p99_ms` default."""
        spec = str(get_config("serving_slo_targets") or "")
        with self._lock:
            memo_spec, table = self._slo_targets_memo
            if spec != memo_spec:
                table = {}
                for entry in spec.split(","):
                    entry = entry.strip()
                    if not entry:
                        continue
                    model, _, ms = entry.partition("=")
                    try:
                        table[model.strip()] = float(ms)
                    except ValueError:
                        logger.warning(
                            f"serving_slo_targets entry {entry!r} is not "
                            "'model=ms'; ignored"
                        )
                self._slo_targets_memo = (spec, table)
        ms = table.get(name)
        if ms is None:
            ms = float(get_config("serving_slo_p99_ms") or 0.0)
        return max(0.0, ms) / 1e3

    def _update_slo(self, name: str) -> None:
        """Refresh `slo_burn_rate{model,window}` from the recent
        latency samples: (fraction of window requests over the p99
        target) / the 1% budget.  1.0 = exactly on budget; 2.0 = the
        error budget burns twice as fast as it accrues — the signal the
        planned coalescing-cap controller will consume (ROADMAP item
        2).  Rate-limited per model; no-op when no target is declared."""
        target_s = self._slo_target_s(name)
        if target_s <= 0:
            return
        now = time.monotonic()
        with self._lock:
            if now - self._slo_last.get(name, 0.0) < _SLO_REFRESH_S:
                return
            self._slo_last[name] = now
            samples = list(self._lat_ts.get(name, ()))
        for window, span_s in _SLO_WINDOWS:
            recent = [tot for t, tot in samples if now - t <= span_s]
            if not recent:
                # an empty window is ZERO burn, not "whatever the last
                # burst left behind": without this a 100x spike would
                # read as live forever once traffic stops (the same
                # stale-gauge class Heartbeat.close fixes for solvers)
                frac_over = 0.0
            else:
                frac_over = sum(
                    1 for tot in recent if tot > target_s
                ) / len(recent)
            SLO_BURN.set(
                round(frac_over / _SLO_BUDGET, 4),
                model=name, window=window,
            )

    def _refresh_slo_all(self) -> None:
        """Dispatcher idle tick: burn-rate gauges keep decaying toward
        the truth even when no batch collects (a model whose traffic
        STOPPED must not scrape as burning; `_update_slo`'s own
        per-model rate limit bounds the cost).  Only models that have
        SERVED are refreshed — decay maintains existing series, it must
        not mint a 0.0 series for a model no request ever touched."""
        try:
            for name in self.registry.names():
                with self._lock:
                    served = bool(self._lat_ts.get(name))
                if served:
                    self._update_slo(name)
        except Exception:  # gauge upkeep must never wedge the loop
            pass

    def _controller_tick(self) -> None:
        """One feedback pass from the dispatcher loop: per served model
        feed the 1m burn gauge and the live p99 into the controller's
        AIMD/brownout step.  Server-side rate limit keeps the hot loop
        from even walking the model list every round; the per-model
        interval inside `tick` does the real pacing.  Control must
        never wedge the dispatcher — any failure is logged and the loop
        moves on."""
        ctl = self._controller
        if not ctl.enabled():
            return
        now = time.monotonic()
        if now - self._ctl_last < min(0.25, ctl.interval_s()):
            return
        self._ctl_last = now
        try:
            base_wait_ms = max(
                0.0, float(get_config("serving_max_wait_ms"))
            )
            for name in self.registry.names():
                with self._lock:
                    lat = list(self._lat.get(name, ()))
                if not lat:
                    continue  # never served: nothing to control yet
                srt = sorted(lat)
                p99_ms = round(
                    srt[min(len(srt) - 1, int(round(0.99 * (len(srt) - 1))))]
                    * 1e3,
                    3,
                )
                burn = SLO_BURN.value(
                    default=None, model=name, window="1m"
                )
                ctl.tick(
                    name, burn, p99_ms,
                    self._base_cap(self._safe_info(name)),
                    base_wait_ms, now=now,
                )
        except Exception as e:
            logger.warning(f"serving controller tick failed ({e})")

    # -- degradation ---------------------------------------------------------

    def _recover_guarded(self, e: Exception, reqs: List[_Request]) -> None:
        """The last line of defense: a recovery that ITSELF blows up
        must fail the recovered requests and keep the dispatcher alive —
        a dead dispatcher turns every queued future into a permanent
        hang (and every HTTP handler thread into a 504)."""
        try:
            self._recover(e, reqs)
        except Exception as e2:
            logger.error(
                f"serving recovery failed ({type(e2).__name__}: {e2}); "
                f"failing {len(reqs)} request(s)"
            )
            for r in reqs:
                if not r.future.done():
                    r.future.set_exception(e2)

    def _note_clean_batch(self) -> None:
        """Success-driven cap recovery: after enough clean batches the
        OOM-shrunk coalescing cap doubles back toward the configured
        value — one transient OOM must not cap QPS for the rest of the
        process (the memory pressure that caused it is long gone).
        Runs on the collect worker; the cv guards the shrink state
        against the dispatcher's `_recover` halving it concurrently
        (callers never hold the cv — it is non-reentrant)."""
        restored = False
        with self._cv:
            if self._shrunk_cap is None:
                return
            self._clean_batches += 1
            if self._clean_batches < _CAP_REGROW_BATCHES:
                return
            self._clean_batches = 0
            grown = self._shrunk_cap * 2
            if grown >= int(get_config("serving_max_batch_rows")):
                self._shrunk_cap = None
                restored = True
            else:
                self._shrunk_cap = grown
        if restored:
            logger.info("serving coalescing cap fully restored")

    def _recover(self, e: Exception, reqs: List[_Request]) -> None:
        """Policy-driven degradation for a failed dispatch/collect: the
        in-flight requests are requeued at the FRONT (order preserved,
        nothing lost) and the failure class picks the repair — mirroring
        core.py's transform chunk loop, with the batch cap playing the
        chunk-size role.  The attempt budget is PER REQUEST: one model's
        poisoned batch can neither exhaust another model's attempts nor
        ride interleaved successes to retry forever."""
        from ..resilience import RetryPolicy
        from ..resilience.retry import RETRIES

        policy = RetryPolicy.from_config()
        action = policy.classify(e)
        limit = max(policy.max_attempts, 2)
        floor_hit = (
            action == "oom"
            and (self._shrunk_cap or 1 << 30) <= self._oom_floor()
        )
        doomed: List[_Request] = []
        alive: List[_Request] = []
        for r in reqs:
            r.attempts += 1
            if action == "fatal" or floor_hit or r.attempts >= limit:
                doomed.append(r)
            else:
                alive.append(r)
        if doomed:
            logger.error(
                f"serving dispatch failed permanently "
                f"({type(e).__name__}: {e}); failing {len(doomed)} "
                "request(s)"
            )
            if action != "fatal":
                # a recoverable class exhausted its per-request budget:
                # same black-box contract as retry_call's exhaustion path
                from ..telemetry.flight_recorder import note_failure

                note_failure(
                    "retry_exhausted",
                    detail=(
                        f"label=serving_dispatch action={action} "
                        f"doomed={len(doomed)} "
                        f"error={type(e).__name__}: {e}"
                    ),
                    log=logger,
                )
            for r in doomed:
                if not r.future.done():
                    r.future.set_exception(e)
        if not alive:
            return
        RETRIES.inc(label="serving_dispatch", action=action)
        event(
            "retry[serving_dispatch]",
            detail=f"action={action} requeued={len(alive)}",
            log=logger,
        )
        self._requeue_front(alive)
        # a repair that fails (a re-pin that no longer fits the degraded
        # mesh, a probe error) must not unwind past the requeue: the
        # requests are back in the queue, the next dispatch surfaces the
        # same failure, and the attempt budget converges to give_up
        try:
            if action == "oom":
                # resident datasets are re-creatable pressure; the pinned
                # models are the serving working set and stay
                from ..parallel.device_cache import clear_device_cache

                clear_device_cache()
                # cv-guarded against the collect worker's clean-batch
                # regrowth racing this halving (called cv-free here)
                with self._cv:
                    cap = self._shrunk_cap or max(
                        1, int(get_config("serving_max_batch_rows"))
                    )
                    self._shrunk_cap = max(self._oom_floor(), cap // 2)
                    self._clean_batches = 0
                    shrunk = self._shrunk_cap
                logger.warning(
                    "serving dispatch exhausted device memory; coalescing "
                    f"cap shrunk to {shrunk} rows"
                )
            elif action == "device_loss":
                from ..resilience.elastic import recover_from_device_loss

                if recover_from_device_loss(logger):
                    # the shrunken mesh is live: every resident model
                    # re-replicates onto the survivors and the queue
                    # drains there — no request is lost to the dead chip
                    self.registry.repin_all("device_loss")
                logger.warning(
                    "serving dispatch lost a device; queue drains on the "
                    "current mesh"
                )
            elif action == "preemption":
                from ..resilience.retry import _default_preemption_hook

                _default_preemption_hook()
            else:  # transient
                attempt = max((r.attempts for r in alive), default=1)
                time.sleep(policy.backoff(attempt))
        except Exception as re_err:
            logger.error(
                f"serving {action} repair failed ({type(re_err).__name__}: "
                f"{re_err}); requests stay queued for the next attempt"
            )


class ServingClient:
    """The in-process client surface: `transform` blocks, `submit`
    returns a Future.  Exists so call sites talk to a stable client API
    whether the server is in-process or fronted by the HTTP endpoint
    (serving/http.py speaks the same request shape)."""

    def __init__(self, server: ServingServer) -> None:
        self._server = server

    def submit(self, model: str, X: Any,
               request_id: Optional[str] = None,
               priority: Optional[str] = None) -> Future:
        """Enqueue; the returned Future carries `.request_id` (minted
        here unless the caller supplies one) — the id the latency
        exemplars and dispatch spans carry.  `priority` picks the
        admission class (`interactive` | `batch`; default: the model's
        registered class, then `serving_priority_default`)."""
        return self._server.submit(
            model, X, request_id=request_id, priority=priority
        )

    def transform(self, model: str, X: Any,
                  timeout: Optional[float] = None,
                  request_id: Optional[str] = None,
                  priority: Optional[str] = None) -> Any:
        """Transform rows; a single-output model returns the bare array
        (matching `Model.transform`'s array-input contract), multi-output
        models return `{col: array}`."""
        outs = self._server.transform(
            model, X, timeout=timeout, request_id=request_id,
            priority=priority,
        )
        if len(outs) == 1:
            return next(iter(outs.values()))
        return outs

    def models(self) -> List[str]:
        return self._server.registry.names()


__all__ = ["ServingClient", "ServingOverload", "ServingServer"]
