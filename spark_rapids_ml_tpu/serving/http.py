#
# HTTP JSON front end for the serving server — the opt-in network
# surface (`serving_port` conf, 0 = off).  A stdlib ThreadingHTTPServer
# speaks a minimal TF-Serving-shaped protocol:
#
#   POST /v1/models/<name>:transform   {"instances": [[f, ...], ...]}
#       -> 200 {"model": name, "rows": n, "outputs": {col: [...]}}
#       -> 404 unknown model, 400 malformed input, 429 ServingOverload
#          (admission control — the caller sheds load or retries).
#          An `X-Priority: interactive|batch` header picks the request's
#          admission class (default: the model's registered class, then
#          `serving_priority_default`); batch-class requests are bounded
#          to a queue share and shed first under brownout
#   GET  /v1/models                    registered + pinned model names
#   GET  /v1/models/<name>             per-model detail: pin status and
#                                      accounted bytes, p50/p99, SLO
#                                      burn, and the drift summary
#                                      (404 for unknown names)
#   GET  /v1/report                    the per-model latency report
#                                      (p50/p99 ms, request counts)
#   GET  /v1/pipeline                  staged-pipeline state: resolved
#                                      depth, live slot occupancy,
#                                      interleave flag, and the serving
#                                      utilization window (busy
#                                      fraction + idle-gap table) — the
#                                      operator's depth-tuning view
#
# Binds LOOPBACK by default, the same posture as the `telemetry_port`
# /metrics endpoint: model names and latency shapes must not leak to
# every network peer of a multi-tenant host — pass host="0.0.0.0"
# deliberately for a fronted deployment.  Handler threads only enqueue
# and block on futures; all device work stays on the dispatcher thread.
#
from __future__ import annotations

import json
import threading
from concurrent.futures import TimeoutError as FuturesTimeoutError
from typing import Any, Dict

import numpy as np

from ..tracing import mint_run_id
from ..utils import get_logger

logger = get_logger("spark_rapids_ml_tpu.serving")


def _jsonable(outs: Dict[str, Any]) -> Dict[str, Any]:
    return {
        col: (v.tolist() if isinstance(v, np.ndarray) else v)
        for col, v in outs.items()
    }


def _reject_constant(name: str):
    """json.loads accepts bare NaN/Infinity by default; request bodies
    carrying them must 400, not smuggle non-finite rows into a batch."""
    raise ValueError(f"non-finite JSON constant {name!r} in request")


# hard bound on one HTTP request's wait for its future: a wedged
# dispatcher (device hang past the watchdog, repair loop stuck) must
# surface as 504s instead of permanently parking every handler thread —
# ThreadingHTTPServer spawns one per request, and threads that never
# return accumulate without bound
REQUEST_TIMEOUT_S = 120.0


def start_serving_http(server, port: int, host: str = "127.0.0.1"):
    """Serve `server` over HTTP on `port` (0 = ephemeral; read
    `.server_port` off the returned instance).  Returns the
    ThreadingHTTPServer; the caller owns shutdown (ServingServer.stop
    closes one it started itself)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from .server import ServingOverload

    class _Handler(BaseHTTPRequestHandler):
        def _reply(self, code: int, payload: Dict[str, Any]) -> None:
            try:
                # allow_nan=False: bare NaN/Infinity tokens are not valid
                # JSON and strict clients reject the whole body — a model
                # emitting a NaN must surface as a typed 500, not as a
                # 200 the caller cannot parse
                body = json.dumps(payload, allow_nan=False).encode()
            except ValueError:
                code = 500
                body = json.dumps(
                    {"error": "model output contains non-finite values"}
                ).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 - stdlib handler contract
            path = self.path.split("?", 1)[0]
            if path == "/v1/models":
                self._reply(200, {
                    "models": server.registry.names(),
                    "pinned": server.registry.pinned_names(),
                })
            elif path == "/v1/report":
                self._reply(200, server.report())
            elif path == "/v1/pipeline":
                self._reply(200, server.pipeline_info())
            elif (
                path.startswith("/v1/models/")
                and not path.endswith(":transform")
            ):
                # per-model detail: pin status + accounted bytes,
                # p50/p99 and SLO burn, and the drift summary
                name = path[len("/v1/models/"):]
                try:
                    self._reply(200, server.model_detail(name))
                except KeyError as e:
                    self._reply(404, {"error": str(e)})
            else:
                self._reply(404, {"error": f"no route {path!r}"})

        def do_POST(self):  # noqa: N802 - stdlib handler contract
            path = self.path.split("?", 1)[0]
            if not (path.startswith("/v1/models/")
                    and path.endswith(":transform")):
                self._reply(404, {"error": f"no route {path!r}"})
                return
            name = path[len("/v1/models/"):-len(":transform")]
            try:
                length = int(self.headers.get("Content-Length") or 0)
                req = json.loads(
                    self.rfile.read(length) or b"{}",
                    parse_constant=_reject_constant,
                )
                X = np.asarray(req["instances"], dtype=np.float64)
                if not np.isfinite(X).all():
                    raise ValueError("instances contain non-finite values")
            except (ValueError, KeyError, TypeError) as e:
                self._reply(400, {"error": f"malformed request: {e}"})
                return
            # request-scoped tracing crosses the HTTP boundary: a caller-
            # supplied X-Request-Id becomes the request's trace identity
            # (exemplars, dispatch-span details, slow captures); absent,
            # one is minted HERE at ingress — either way the response
            # names it (429 rejections included: those are exactly the
            # requests an operator wants to correlate), so a client log
            # line joins the server's latency exemplars
            req_id = (
                (self.headers.get("X-Request-Id") or "").strip()
                or mint_run_id("req")
            )
            # priority class crosses the boundary the same way: the
            # header names one, else the model/conf defaults apply in
            # submit (an unknown class 400s via its ValueError)
            priority = (
                (self.headers.get("X-Priority") or "").strip().lower()
                or None
            )
            try:
                outs = server.submit(
                    name, X, request_id=req_id, priority=priority
                ).result(timeout=REQUEST_TIMEOUT_S)
            except ServingOverload as e:
                # the rejected requests are the ones an operator most
                # wants to correlate: the reply names the id too
                self._reply(429, {
                    "error": str(e), "reason": e.reason,
                    "request_id": req_id,
                })
            except KeyError as e:
                self._reply(404, {"error": str(e)})
            except ValueError as e:
                self._reply(400, {"error": str(e)})
            except FuturesTimeoutError:
                self._reply(504, {
                    "error": f"no result within {REQUEST_TIMEOUT_S:.0f}s "
                    "(serving dispatcher stalled?)",
                    "request_id": req_id,
                })
            except Exception as e:  # a failed dispatch, not a bad request
                self._reply(500, {
                    "error": f"{type(e).__name__}: {e}",
                    "request_id": req_id,
                })
            else:
                self._reply(200, {
                    "model": name,
                    "rows": int(X.shape[0]) if X.ndim == 2 else 1,
                    "request_id": req_id,
                    "outputs": _jsonable(outs),
                })

        def log_message(self, *args):  # request rate must not spam stderr
            pass

    srv = ThreadingHTTPServer((host, int(port)), _Handler)
    srv.daemon_threads = True
    t = threading.Thread(
        target=srv.serve_forever, name="serving-http", daemon=True
    )
    t.start()
    logger.info(
        f"serving endpoint: http://{host}:{srv.server_port}/v1/models"
    )
    return srv


__all__ = ["start_serving_http"]
