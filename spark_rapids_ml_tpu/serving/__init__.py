#
# serving/ — the online inference subsystem: transform/predict traffic
# from device-resident models (ROADMAP item 1, "millions of users" means
# serving, not just fits).  Four pieces:
#
#   control.py    the closed-loop control plane (ROADMAP item 2's
#                 actuator half): per-model AIMD feedback scales the
#                 coalescing cap and max-wait against the measured
#                 `slo_burn_rate`, priority classes (`interactive` |
#                 `batch`) gate admission and weight dispatch, sustained
#                 burn walks a brownout phase machine (shed batch ->
#                 tighten interactive -> recover), and shape-bucketed
#                 padding classes keep compiled transform programs
#                 reused across churning request sizes.
#   registry.py   model residency: a registered model's weight arrays
#                 replicate onto the serving mesh ONCE (budget-accounted
#                 through parallel/device_cache.py's external-reservation
#                 ledger, LRU-evicted under pressure, transparently
#                 re-pinned on the next request), so no request pays a
#                 weight re-upload.
#   server.py     the micro-batch coalescer + async dispatcher:
#                 concurrent small requests per model concatenate into
#                 one padded device batch under the `serving_max_wait_ms`
#                 SLO, with admission control (`serving_max_queue` ->
#                 typed ServingOverload) and policy-driven degradation
#                 (OOM shrinks the batch cap, device loss re-pins on the
#                 elastic-shrunken mesh, transients back off — queued
#                 requests survive).
#   http.py       the opt-in stdlib HTTP JSON endpoint (`serving_port`
#                 conf; loopback by default, like `telemetry_port`).
#
# Metrics land in the telemetry registry (`serving_request_latency_
# seconds{model,phase}`, `serving_batch_rows`, `serving_rejections_
# total`, pin lifecycle counters) and export through the existing
# /metrics endpoint; `ServingServer.report()` renders per-model p50/p99.
# See docs/serving.md for architecture, SLO tuning, and the degradation
# table.
#
#   from spark_rapids_ml_tpu.serving import ServingServer, ServingClient
#   server = ServingServer()
#   server.register("pca", pca_model)
#   server.start()
#   client = ServingClient(server)
#   projected = client.transform("pca", rows)
#
from .control import ServingController  # noqa: F401
from .registry import ModelRegistry, PinnedModel  # noqa: F401
from .server import (  # noqa: F401
    ServingClient,
    ServingOverload,
    ServingServer,
)

__all__ = [
    "ModelRegistry",
    "PinnedModel",
    "ServingClient",
    "ServingController",
    "ServingOverload",
    "ServingServer",
]
