#
# Serving model registry — device residency for the inference side.  A
# fitted model's transform path normally re-uploads its weight arrays on
# every call (`jnp.asarray(self.components_...)` inside
# `_transform_device`) and nothing accounts for the HBM those weights
# occupy.  Here a model is PINNED once: its ndarray attributes move onto
# the serving mesh as replicated device arrays (a shallow copy of the
# model carries them, the caller's object is never mutated), so every
# subsequent micro-batch dispatch reuses the resident weights and the
# compiled `_transform_device` program for its shape bucket — zero
# weight re-staging across requests (asserted by tests/test_serving.py).
#
# Residency is budget-accounted: a pin books `sum(weight bytes) x n_dev`
# (replication puts one copy in every device's HBM) through
# `parallel/device_cache.py`'s external-reservation ledger, so fit-side
# staging decisions see serving residency and vice versa; under pressure
# the registry LRU-evicts its own pins (the dataset cache LRU-evicts its
# entries) and an evicted model transparently RE-PINS on its next
# request.  After an elastic mesh shrink (resilience/elastic.py) the
# dispatcher calls `repin_all`: every resident model re-replicates onto
# the surviving device set.
#
# Models that manage their own staging (kNN, DBSCAN, UMAP — no
# `_transform_device`) register as HOST-path models: their requests
# still coalesce into micro-batches, but dispatch goes through the
# model's own `_transform_array` and no residency is claimed.
#
from __future__ import annotations

import copy

from ..telemetry.locks import named_lock
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..telemetry.registry import counter, gauge
from ..utils import get_logger

logger = get_logger("spark_rapids_ml_tpu.serving")

PINS = counter(
    "serving_pins_total",
    "Serving model-pin lifecycle events (pin/repin/evict/unpin) by model",
)
PINNED_MODELS = gauge(
    "serving_pinned_models", "Models currently pinned on the serving mesh"
)
PINNED_BYTES = gauge(
    "serving_pinned_bytes",
    "Budget-accounted bytes of pinned serving-model residency",
)


def _external_tag(name: str) -> str:
    return f"serving:{name}"


# arrays below this stay host-resident when a model pins: reading an
# element of a pinned (device) array builds a python scalar through a
# blocking device fetch, which binomial logreg does per dispatch
# (`self.intercept_[0]`) — scalars and tiny vectors (intercepts,
# variance ratios) are exactly the attrs transforms read elementwise,
# and their per-call upload is noise next to one weight matrix
_PIN_MIN_BYTES = 64


class PinnedModel:
    """One registered model, ready to dispatch: the pinned shallow copy
    (device-resident weight arrays when `device` is True), the mesh it
    is replicated on, and its accounting size."""

    __slots__ = (
        "name", "model", "device", "mesh", "dtype", "n_features",
        "nbytes", "last_used", "transform_fn",
    )

    def __init__(self, name: str, model: Any, device: bool, mesh,
                 dtype: np.dtype, n_features: Optional[int],
                 nbytes: int, transform_fn=None) -> None:
        self.name = name
        self.model = model
        self.device = device
        self.mesh = mesh
        self.dtype = np.dtype(dtype)
        self.n_features = n_features
        self.nbytes = int(nbytes)
        self.last_used = time.monotonic()
        # host-path dispatch callable (X) -> {col: array}; None for
        # device-pinned models (they dispatch via _transform_device)
        self.transform_fn = transform_fn


class ModelRegistry:
    """Name-keyed registry of serveable models.  `register` keeps the
    caller's HOST model (the re-pin source) and pins it; `resolve`
    returns the pinned entry, transparently re-pinning one that was
    LRU-evicted under budget pressure.  All mutations hold the instance
    lock; pinning itself (device transfers) runs outside it so a slow
    replication cannot stall concurrent resolves of other models."""

    def __init__(self) -> None:
        self._mu = named_lock("serving_registry", kind="rlock")
        self._host: Dict[str, Dict[str, Any]] = {}  # name -> registration
        self._pinned: Dict[str, PinnedModel] = {}
        # incremental sum of pinned nbytes, maintained by _publish_locked
        # and _drop: pinned_bytes()/_sync_gauges() are polled per report
        # and per pin/drop, and a full-table scan there is O(pins) work
        # under the registry lock every time — at hundreds of pinned
        # models that scan IS the report path's cost
        self._pinned_total_bytes = 0

    # -- registration --------------------------------------------------------

    def register(
        self,
        name: str,
        model: Any,
        dtype: Any = np.float32,
        n_features: Optional[int] = None,
        transform: Any = None,
        priority: Optional[str] = None,
    ) -> PinnedModel:
        """Register `model` under `name` and pin it.  Models with a
        device transform (`_transform_device`) pin device-resident;
        models without one (kNN and friends manage their own staging)
        register as host-path — coalesced micro-batching still applies,
        residency accounting does not.  `transform` overrides the
        host-path dispatch callable (`(X) -> {col: array}`; default
        `model._transform_array`) — the kNN hook, whose query surface is
        `kneighbors`, not transform.  `priority` sets the model's
        DEFAULT admission class (`interactive` | `batch`) for requests
        that do not name one — a background scoring model registers as
        `batch` once instead of tagging every request."""
        from ..core import _TpuModel
        from .control import PRIORITY_CLASSES

        if priority is not None and priority not in PRIORITY_CLASSES:
            raise ValueError(
                f"unknown priority class {priority!r}; expected one of "
                f"{'|'.join(PRIORITY_CLASSES)}"
            )

        if not isinstance(model, _TpuModel):
            raise TypeError(
                f"serving requires a fitted _TpuModel, got {type(model)!r}"
            )
        has_device = (
            type(model)._transform_device is not _TpuModel._transform_device
        )
        if not has_device and transform is None and (
            type(model)._transform_array is _TpuModel._transform_array
        ):
            raise ValueError(
                f"model {name!r} implements neither _transform_device nor "
                "_transform_array; pass transform=<callable> to serve it"
            )
        if n_features is None:
            nc = model._get_model_attributes().get("n_cols")
            if nc is not None:
                n_features = int(nc)
        with self._mu:
            self._host[name] = {
                "model": model,
                "dtype": np.dtype(dtype),
                "n_features": n_features,
                "transform": transform,
                "priority": priority,
            }
        # drift monitor (monitor/): a model carrying a fit-time baseline
        # fingerprint registers it WITH the pin — serving traffic for
        # this name folds into the monitor's sliding windows from the
        # first request (re-registering under the same name restarts
        # the windows against the new model's baseline: hot swap)
        fp = getattr(model, "_drift_baseline", None)
        from ..monitor import MONITOR

        if fp is not None:
            MONITOR.register(name, fp)
        else:
            MONITOR.drop(name)
        return self._pin(name, event="pin")

    def unregister(self, name: str) -> None:
        from ..monitor import MONITOR

        with self._mu:
            self._host.pop(name, None)
        MONITOR.drop(name)
        self._drop(name, event="unpin")

    def names(self) -> List[str]:
        with self._mu:
            return sorted(self._host)

    def info(self, name: str) -> Dict[str, Any]:
        """Registration facts for the admission check — never pins."""
        with self._mu:
            reg = self._host.get(name)
            if reg is None:
                raise KeyError(f"no serving model registered as {name!r}")
            return dict(reg)

    def pin_feature_width(self, name: str, d: int) -> int:
        """Adopt the first observed request width for a model registered
        WITHOUT `n_features`, atomically; returns the canonical width.
        Without this, two concurrent first requests of different widths
        would coalesce into one batch and the np.concatenate failure
        would poison the valid request alongside the bad one — admission
        must reject the mismatch instead."""
        with self._mu:
            reg = self._host.get(name)
            if reg is None:
                raise KeyError(f"no serving model registered as {name!r}")
            if reg.get("n_features") is None:
                reg["n_features"] = int(d)
            return int(reg["n_features"])

    def pinned_names(self) -> List[str]:
        with self._mu:
            return sorted(self._pinned)

    def is_pinned(self, name: str) -> bool:
        """O(1) pin probe for the per-model report paths: building the
        sorted `pinned_names()` list just to test membership is an
        O(n log n) sort per poll, paid once per model row at hundreds
        of pinned models."""
        with self._mu:
            return name in self._pinned

    # -- resolution ----------------------------------------------------------

    def resolve(self, name: str) -> PinnedModel:
        """The pinned entry for `name`, re-pinning an evicted model (a
        cache-miss-shaped event: the host model is the re-pin source)."""
        with self._mu:
            if name not in self._host:
                raise KeyError(f"no serving model registered as {name!r}")
            entry = self._pinned.get(name)
            if entry is not None:
                entry.last_used = time.monotonic()
                return entry
        return self._pin(name, event="repin")

    # -- pinning -------------------------------------------------------------

    def _pin(self, name: str, event: str) -> PinnedModel:
        from ..core import _TpuModel
        from ..parallel.device_cache import reserve_external
        from ..parallel.mesh import get_mesh

        with self._mu:
            reg = dict(self._host[name])
        model = reg["model"]
        has_device = (
            type(model)._transform_device is not _TpuModel._transform_device
        )
        if not has_device:
            entry = PinnedModel(
                name, model, device=False, mesh=None,
                dtype=reg["dtype"], n_features=reg["n_features"], nbytes=0,
                transform_fn=reg.get("transform") or model._transform_array,
            )
            with self._mu:
                self._publish_locked(name, entry)
            PINS.inc(model=name, event=event)
            self._sync_gauges()
            return entry
        mesh = get_mesh()
        pinned_model, nbytes = self._replicate_arrays(model, mesh)
        # book the residency BEFORE publishing: under pressure, evict our
        # own LRU pins (never the one being pinned) until it fits — the
        # dataset-cache side of the ledger LRU-evicts its entries first.
        # Eviction is BATCHED: one shortfall read sizes a single sorted
        # LRU pass and one ledger round-trip frees every victim, instead
        # of a reserve/evict probe per victim (each a ledger lock
        # acquisition shared with staging).  The per-victim loop stays
        # as a fallback for the race where another pinner claims the
        # freed headroom between our release and retry.
        if not reserve_external(_external_tag(name), nbytes):
            self._evict_batch(exclude=name, shortfall=self._shortfall(
                name, nbytes))
            while not reserve_external(_external_tag(name), nbytes):
                if not self._evict_lru(exclude=name):
                    raise RuntimeError(
                        f"serving model {name!r} (~{nbytes/2**20:.1f} MiB "
                        "replicated) does not fit the device budget even "
                        "with every other pin evicted"
                    )
        entry = PinnedModel(
            name, pinned_model, device=True, mesh=mesh,
            dtype=reg["dtype"], n_features=reg["n_features"], nbytes=nbytes,
        )
        with self._mu:
            self._publish_locked(name, entry)
        PINS.inc(model=name, event=event)
        from ..tracing import event as trace_event

        trace_event(
            f"serving_pin[{name}]",
            detail=f"{event} bytes={nbytes} n_dev={mesh.devices.size}",
            log=logger,
        )
        self._sync_gauges()
        return entry

    def _replicate_arrays(self, model: Any, mesh) -> tuple:
        """A shallow copy of `model` whose ndarray attributes are
        replicated jax arrays on `mesh`.  Returns (pinned model, bytes):
        bytes = one replica per device, the cluster-wide honest cost the
        external reservation books.  Dtypes go through jnp.asarray's
        canonicalization so the pinned weights match what the unpinned
        transform's per-call `jnp.asarray` would have produced."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec

        pinned = copy.copy(model)
        attrs = dict(model._get_model_attributes())
        sharding = NamedSharding(mesh, PartitionSpec())
        replica_bytes = 0
        for key, val in attrs.items():
            if not isinstance(val, np.ndarray) or val.dtype == object:
                continue
            if val.nbytes < _PIN_MIN_BYTES:
                # stays host numpy (see _PIN_MIN_BYTES): elementwise
                # reads of a pinned array would pay a BLOCKING device
                # round-trip per dispatch on the latency-critical path
                continue
            dev = jax.device_put(jnp.asarray(val), sharding)
            replica_bytes += int(dev.nbytes)
            attrs[key] = dev
            if hasattr(pinned, key):
                setattr(pinned, key, dev)
        pinned._model_attributes = attrs
        return pinned, replica_bytes * int(mesh.devices.size)

    def _publish_locked(self, name: str, entry: PinnedModel) -> None:
        """Install `entry` in the pin table keeping the incremental byte
        counter exact — a re-register overwrites an existing pin, whose
        bytes must leave the sum (its ledger claim was already replaced
        by the same-tag `reserve_external`)."""
        old = self._pinned.get(name)
        if old is not None:
            self._pinned_total_bytes -= old.nbytes
        self._pinned[name] = entry
        self._pinned_total_bytes += entry.nbytes

    def _shortfall(self, name: str, nbytes: int) -> int:
        from ..parallel.device_cache import external_shortfall

        return external_shortfall(_external_tag(name), nbytes)

    # -- eviction ------------------------------------------------------------

    def _evict_batch(self, exclude: Optional[str], shortfall: int) -> int:
        """Evict LRU pins covering `shortfall` bytes in ONE sorted pass,
        releasing their ledger claims through ONE batched round-trip
        (`release_external_many`).  Returns the number of victims; 0
        when nothing is evictable (the caller's per-victim fallback
        then raises the does-not-fit error)."""
        from ..parallel.device_cache import release_external_many

        if shortfall <= 0:
            return 0
        with self._mu:
            candidates = sorted(
                (e for e in self._pinned.values()
                 if e.device and e.name != exclude),
                key=lambda e: e.last_used,
            )
            victims: List[PinnedModel] = []
            freed = 0
            for e in candidates:
                if freed >= shortfall:
                    break
                victims.append(e)
                freed += e.nbytes
            for e in victims:
                self._pinned.pop(e.name, None)
                self._pinned_total_bytes -= e.nbytes
        if not victims:
            return 0
        release_external_many([_external_tag(e.name) for e in victims])
        for e in victims:
            PINS.inc(model=e.name, event="evict")
        self._sync_gauges()
        logger.info(
            f"serving: batch-evicted {len(victims)} pin(s) "
            f"({freed/2**20:.1f} MiB) to fit a new pin"
        )
        return len(victims)

    def _evict_lru(self, exclude: Optional[str] = None) -> bool:
        with self._mu:
            candidates = [
                e for e in self._pinned.values()
                if e.device and e.name != exclude
            ]
            if not candidates:
                return False
            victim = min(candidates, key=lambda e: e.last_used)
        self._drop(victim.name, event="evict")
        return True

    def _drop(self, name: str, event: str) -> None:
        from ..parallel.device_cache import release_external

        with self._mu:
            entry = self._pinned.pop(name, None)
            if entry is not None:
                self._pinned_total_bytes -= entry.nbytes
        if entry is None:
            return
        if entry.device:
            release_external(_external_tag(name))
        PINS.inc(model=name, event=event)
        self._sync_gauges()

    def repin_all(self, reason: str = "elastic") -> None:
        """Drop every device-resident pin and re-pin on the CURRENT
        active mesh — the dispatcher's device-loss hook: arrays
        replicated over a lost chip are unreadable, and the re-pin lands
        every model on the survivors (resilience/elastic.py shrank the
        mesh before this runs).  The drop phase is BATCHED: one pin-
        table pass plus one ledger round-trip frees every claim at
        once, so the mesh-shrink stall does not scale with pin count
        before the first re-pin can even start."""
        from ..parallel.device_cache import release_external_many

        with self._mu:
            dropped = [e for e in self._pinned.values() if e.device]
            for e in dropped:
                self._pinned.pop(e.name, None)
                self._pinned_total_bytes -= e.nbytes
        names = [e.name for e in dropped]
        logger.warning(
            f"serving: re-pinning {len(names)} model(s) on the current "
            f"mesh ({reason})"
        )
        if not names:
            return
        release_external_many([_external_tag(n) for n in names])
        for name in names:
            PINS.inc(model=name, event="evict")
        self._sync_gauges()
        for name in names:
            self._pin(name, event="repin")

    def pin_info(self, name: str) -> Dict[str, Any]:
        """Pin status + accounting for ONE model (the per-model HTTP
        detail endpoint): KeyError for unregistered names."""
        with self._mu:
            reg = self._host.get(name)
            if reg is None:
                raise KeyError(f"no serving model registered as {name!r}")
            e = self._pinned.get(name)
            return {
                "pinned": e is not None,
                "device": bool(e.device) if e is not None else False,
                "pinned_bytes": int(e.nbytes) if e is not None else 0,
                "n_features": reg.get("n_features"),
                "dtype": str(np.dtype(reg["dtype"])),
            }

    def clear(self) -> None:
        from ..monitor import MONITOR

        with self._mu:
            names = list(self._pinned)
            hosted = list(self._host)
        for name in names:
            self._drop(name, event="unpin")
        for name in hosted:
            MONITOR.drop(name)
        with self._mu:
            self._host.clear()

    def pinned_bytes(self) -> int:
        # incremental counter, NOT a table scan: this is polled per
        # report/admission check and must stay O(1) at hundreds of pins
        with self._mu:
            return self._pinned_total_bytes

    def _sync_gauges(self) -> None:
        with self._mu:
            PINNED_MODELS.set(len(self._pinned))
            PINNED_BYTES.set(self._pinned_total_bytes)


__all__ = ["ModelRegistry", "PinnedModel"]
