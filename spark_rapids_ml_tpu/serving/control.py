#
# Closed-loop serving control plane — the ACTUATOR half of ROADMAP item
# 2.  PRs 12/14/15 gave the serving layer its sensors (`slo_burn_rate
# {model,window}`, `serving_queue_depth`, dispatcher loop lag, drift);
# this module is what ACTS on them.  Three cooperating mechanisms, all
# consumed by serving/server.py:
#
#   AIMD feedback   per model the controller scales the coalescing cap
#                   and the max-wait knob against the measured burn
#                   rate (the p99-target breach fraction over the 1%
#                   budget — the controller's error signal): burn at or
#                   above `serving_controller_burn_high` HALVES both
#                   (multiplicative decrease — smaller batches and
#                   earlier dispatch cut tail latency), burn at or
#                   below `serving_controller_burn_low` regrows both
#                   additively toward the configured values, and the
#                   band between the thresholds HOLDS (hysteresis, so
#                   the actuators cannot oscillate at one boundary).
#                   This generalizes the dispatcher's OOM halving /
#                   clean-batch regrow machinery: the OOM path stays
#                   the emergency memory actuator, this is the SLO
#                   actuator layered on top of it.
#   priority        two admission classes (`interactive` | `batch`,
#                   per request via client/HTTP header or per-model
#                   default): batch-class load is admitted only into a
#                   `serving_batch_share` fraction of the queue and
#                   wins only a credit-weighted share of contested
#                   dispatch rounds, so background scoring can never
#                   starve the latency-sensitive path (and interactive
#                   pressure can never fully starve batch either).
#   brownout        burn held at or above `serving_brownout_burn` for
#                   `serving_brownout_sustain_s` escalates a per-model
#                   phase machine normal -> shed_batch ->
#                   shed_interactive: batch-class load sheds first,
#                   then interactive admission tightens to a fraction
#                   of the queue; burn back at or below the low water
#                   for `serving_brownout_recover_s` de-escalates one
#                   phase at a time and re-admits.  Every transition is
#                   a trace instant; escalations leave a
#                   cooldown-guarded reason="brownout" flight-recorder
#                   bundle (the recorder's per-reason cooldown absorbs
#                   the storm — one black box per episode).
#
# Plus shape-bucketed padding classes: coalesced batches stage into the
# same {1, 1.5} x 2^k bucket grid fits use (parallel/mesh.py
# `bucket_rows`), pinned on for serving by `serving_padding_buckets`
# regardless of the global `shape_bucketing` conf, so churning request
# sizes reuse ONE compiled transform program per bucket — the jit-audit
# zero-recompile guarantee extended to the serving path (asserted via
# `compiles_total` deltas in tests/test_serving_control.py).  Each
# dispatch records its decision in `LAST_BUCKET_DECISION` (the
# `solver_decision` stamp idiom telemetry/report.py copies) and the
# per-model bucket set surfaces in the serving report.
#
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from ..config import get_config
from ..telemetry.locks import named_lock
from ..telemetry.registry import counter, gauge
from ..tracing import event
from ..utils import get_logger

logger = get_logger("spark_rapids_ml_tpu.serving")

# admission/dispatch priority classes, ordered by dispatch preference
# (the batch take drains `interactive` heads first)
PRIORITY_CLASSES = ("interactive", "batch")

# brownout phases, ordered by severity; the phase index is what the
# `serving_controller_brownout_phase` gauge exports
BROWNOUT_PHASES = ("normal", "shed_batch", "shed_interactive")

CTRL_CAP = gauge(
    "serving_controller_cap",
    "Controller-effective coalescing cap (rows) per served model",
)
CTRL_WAIT = gauge(
    "serving_controller_max_wait_ms",
    "Controller-effective coalescing max-wait (ms) per served model",
)
CTRL_ADJ = counter(
    "serving_controller_adjustments_total",
    "AIMD actuator adjustments by model and direction "
    "(increase|decrease)",
)
BROWNOUT_PHASE = gauge(
    "serving_controller_brownout_phase",
    "Brownout phase index per model (0 normal, 1 shed_batch, "
    "2 shed_interactive)",
)
SHED = counter(
    "serving_shed_total",
    "Requests shed by the brownout controller, by model and priority "
    "class",
)

# AIMD shape: halve on breach, regrow an eighth of full scale per clean
# tick — the same halving the OOM cap degradation uses, with the regrow
# made additive (classic AIMD converges; multiplicative regrow
# oscillates at the boundary)
_MD_FACTOR = 0.5
_AI_STEP = 0.125
# actuator floor: a cap/wait scaled below this stops coalescing from
# working at all — the brownout machine is the next escalation, not
# ever-smaller batches
_MIN_SCALE = 1.0 / 64.0

# shed_interactive: the queue fraction interactive admission tightens
# to (1/this of `serving_max_queue`); batch is already fully shed
_INTERACTIVE_TIGHTEN = 8

# padding-class bookkeeping bound: distinct buckets retained per model
# for the report (the grid is coarse; real traffic sees a handful)
_MAX_BUCKETS_TRACKED = 32

# the last serving padding-class decision — the `solver_decision` stamp
# idiom (ops/pca.py LAST_SOLVER_DECISION): telemetry/report.py copies
# it into a fit report whose window covers the stamp, and the serving
# report exposes it live
LAST_BUCKET_DECISION: Dict[str, Any] = {}


def resolve_priority(
    requested: Optional[str], model_default: Optional[str]
) -> str:
    """One request's admission class: the caller's explicit class, else
    the model's registered default, else `serving_priority_default`.
    ValueError for names outside PRIORITY_CLASSES (the HTTP front end
    maps it to a 400)."""
    cls = (
        requested
        or model_default
        or str(get_config("serving_priority_default") or "interactive")
    )
    cls = str(cls).strip().lower()
    if cls not in PRIORITY_CLASSES:
        raise ValueError(
            f"unknown priority class {cls!r}; expected one of "
            f"{'|'.join(PRIORITY_CLASSES)}"
        )
    return cls


class _ModelState:
    __slots__ = (
        "cap_scale", "wait_scale", "phase", "hi_since", "lo_since",
        "last_tick", "p99_ms", "buckets",
    )

    def __init__(self) -> None:
        self.cap_scale = 1.0
        self.wait_scale = 1.0
        self.phase = 0
        # monotonic time burn first crossed the brownout / recovery
        # water marks (None = not currently across); sustain windows
        # are measured from these
        self.hi_since: Optional[float] = None
        self.lo_since: Optional[float] = None
        self.last_tick = 0.0
        self.p99_ms: Optional[float] = None
        self.buckets: List[int] = []


class ServingController:
    """Per-server feedback controller: AIMD actuator scales, the
    brownout phase machine, weighted-credit class dispatch, and the
    padding-class record.  One instance per ServingServer; all state
    behind the `serving_control` named lock.  Lock ordering: the
    dispatcher condition (`serving_dispatch`) may be held when calling
    in here; this lock never wraps an acquire of the condition."""

    def __init__(self) -> None:
        self._mu = named_lock("serving_control")
        self._models: Dict[str, _ModelState] = {}
        # weighted round-robin credit for contested dispatch rounds
        # (both classes have a due head): batch accrues
        # `serving_batch_share` credit per interactive win and
        # dispatches when a full credit accumulates
        self._credit = 0.0

    # -- conf accessors ------------------------------------------------------

    def enabled(self) -> bool:
        return str(get_config("serving_controller")).lower() == "on"

    def interval_s(self) -> float:
        return max(
            0.0, float(get_config("serving_controller_interval_s"))
        )

    def burn_high(self) -> float:
        return float(get_config("serving_controller_burn_high"))

    def burn_low(self) -> float:
        return float(get_config("serving_controller_burn_low"))

    def batch_share(self) -> float:
        share = float(get_config("serving_batch_share"))
        return min(1.0, max(0.0, share))

    def padding_enabled(self) -> bool:
        return bool(get_config("serving_padding_buckets"))

    # -- actuator reads (dispatcher + admission) -----------------------------

    def cap_scale(self, name: str) -> float:
        if not self.enabled():
            return 1.0
        with self._mu:
            st = self._models.get(name)
            return st.cap_scale if st is not None else 1.0

    def wait_scale(self, name: str) -> float:
        if not self.enabled():
            return 1.0
        with self._mu:
            st = self._models.get(name)
            return st.wait_scale if st is not None else 1.0

    def scales(self, name: str) -> Tuple[float, float]:
        """(cap_scale, wait_scale) under ONE lock acquisition — the
        dispatcher's coalesce path reads both per queued model per
        round, and at hundreds of pinned models the two separate locked
        reads above double the hot-path lock traffic.  Also gives the
        caller one CONSISTENT snapshot: a controller tick between
        separate reads could pair an old cap with a new wait."""
        if not self.enabled():
            return 1.0, 1.0
        with self._mu:
            st = self._models.get(name)
            if st is None:
                return 1.0, 1.0
            return st.cap_scale, st.wait_scale

    def phase(self, name: str) -> int:
        if not self.enabled():
            return 0
        with self._mu:
            st = self._models.get(name)
            return st.phase if st is not None else 0

    def admit(
        self, name: str, cls: str, queued_total: int, queued_cls: int,
        max_queue: int,
    ) -> Tuple[bool, str, str]:
        """Admission verdict for one `cls` request: (admitted, reason,
        detail).  Reasons: `queue_full` (capacity — the global bound or
        the batch class-share bound) and `shed` (brownout policy).
        With the controller off only the global bound applies."""
        if queued_total >= max_queue:
            return False, "queue_full", (
                f"{queued_total} requests queued "
                f"(serving_max_queue={max_queue})"
            )
        if not self.enabled():
            return True, "", ""
        phase = self.phase(name)
        if cls == "batch":
            if phase >= 1:
                return False, "shed", (
                    f"brownout {BROWNOUT_PHASES[phase]} sheds "
                    "batch-class load"
                )
            limit = max(1, int(max_queue * self.batch_share()))
            reason = "queue_full"
        elif phase >= 2:
            limit = max(1, max_queue // _INTERACTIVE_TIGHTEN)
            reason = "shed"
        else:
            return True, "", ""
        if queued_cls >= limit:
            return False, reason, (
                f"{queued_cls} {cls}-class requests queued "
                f"(class limit {limit} of serving_max_queue={max_queue})"
            )
        return True, "", ""

    def note_shed(self, name: str, cls: str) -> None:
        SHED.inc(model=name, **{"class": cls})

    def pick_class(self) -> str:
        """Contested dispatch round (both classes hold a due head
        somewhere): weighted round-robin credit.  Batch accrues
        `serving_batch_share` credit per interactive win and dispatches
        once a full credit accumulates — one batch round per
        ceil(1/share) contested rounds, so neither class starves."""
        share = self.batch_share()
        with self._mu:
            if self._credit >= 1.0:
                self._credit -= 1.0
                return "batch"
            self._credit += share
            return "interactive"

    # -- feedback ------------------------------------------------------------

    def tick(
        self,
        name: str,
        burn: Optional[float],
        p99_ms: Optional[float],
        base_cap: int,
        base_wait_ms: float,
        now: Optional[float] = None,
    ) -> None:
        """One feedback step for `name`, rate-limited to
        `serving_controller_interval_s` per model.  `burn` is the 1m
        `slo_burn_rate` gauge (None when no SLO target is declared —
        the actuators then only regrow); `p99_ms` rides into the state
        for the report.  Burn >= the high water multiplicatively
        shrinks both actuators, burn <= the low water additively
        regrows them, in between HOLDS (hysteresis).  The brownout
        machine escalates/recovers on its own sustained thresholds."""
        if not self.enabled():
            return
        now = time.monotonic() if now is None else now
        transition = None
        b = 0.0 if burn is None else float(burn)
        with self._mu:
            st = self._models.setdefault(name, _ModelState())
            if now - st.last_tick < self.interval_s():
                return
            st.last_tick = now
            st.p99_ms = p99_ms
            hi, lo = self.burn_high(), self.burn_low()
            if burn is not None and b >= hi:
                if st.cap_scale > _MIN_SCALE or st.wait_scale > _MIN_SCALE:
                    st.cap_scale = max(_MIN_SCALE, st.cap_scale * _MD_FACTOR)
                    st.wait_scale = max(
                        _MIN_SCALE, st.wait_scale * _MD_FACTOR
                    )
                    CTRL_ADJ.inc(model=name, direction="decrease")
            elif b <= lo and (st.cap_scale < 1.0 or st.wait_scale < 1.0):
                st.cap_scale = min(1.0, st.cap_scale + _AI_STEP)
                st.wait_scale = min(1.0, st.wait_scale + _AI_STEP)
                CTRL_ADJ.inc(model=name, direction="increase")
            # brownout phase machine: sustained burn across the high
            # water escalates one phase per sustain window; sustained
            # recovery below the AIMD low water de-escalates one phase
            # per recovery window (each step restarts its timer, so a
            # flapping burn cannot ratchet straight to the worst phase)
            if burn is not None and b >= float(
                get_config("serving_brownout_burn")
            ):
                st.lo_since = None
                if st.hi_since is None:
                    st.hi_since = now
                elif (
                    now - st.hi_since
                    >= float(get_config("serving_brownout_sustain_s"))
                    and st.phase < len(BROWNOUT_PHASES) - 1
                ):
                    transition = (st.phase, st.phase + 1)
                    st.phase += 1
                    st.hi_since = now
            elif b <= lo:
                st.hi_since = None
                if st.lo_since is None:
                    st.lo_since = now
                elif (
                    now - st.lo_since
                    >= float(get_config("serving_brownout_recover_s"))
                    and st.phase > 0
                ):
                    transition = (st.phase, st.phase - 1)
                    st.phase -= 1
                    st.lo_since = now
            else:
                st.hi_since = None
                st.lo_since = None
            CTRL_CAP.set(
                max(1, int(base_cap * st.cap_scale)), model=name
            )
            CTRL_WAIT.set(
                round(base_wait_ms * st.wait_scale, 3), model=name
            )
            BROWNOUT_PHASE.set(st.phase, model=name)
        if transition is not None:
            self._note_transition(name, transition, b)

    def _note_transition(
        self, name: str, transition: Tuple[int, int], burn: float
    ) -> None:
        """A brownout phase change: always a trace instant; escalations
        additionally leave a reason="brownout" flight-recorder bundle
        (outside the controller lock — the dump writes files; the
        recorder's per-reason cooldown bounds an episode to ONE
        bundle)."""
        old, new = transition
        detail = (
            f"model={name} {BROWNOUT_PHASES[old]}->{BROWNOUT_PHASES[new]} "
            f"burn={burn:.2f}"
        )
        event(f"serving_brownout[{name}]", detail=detail, log=logger)
        if new > old:
            from ..telemetry.flight_recorder import note_failure

            note_failure("brownout", detail=detail, log=logger)

    # -- padding classes -----------------------------------------------------

    def note_bucket(self, name: str, rows: int) -> int:
        """Record one dispatch's padding-class decision and return the
        bucket the stager will pad to (`parallel/mesh.bucket_rows` —
        the same grid fit kernels compile against)."""
        from ..parallel.mesh import bucket_rows

        bucket = int(bucket_rows(int(rows)))
        decision = {
            "model": name,
            "rows": int(rows),
            "bucket": bucket,
            "pad_rows": bucket - int(rows),
            "stamp": round(time.time(), 3),
        }
        with self._mu:
            LAST_BUCKET_DECISION.clear()
            LAST_BUCKET_DECISION.update(decision)
            st = self._models.setdefault(name, _ModelState())
            if (
                bucket not in st.buckets
                and len(st.buckets) < _MAX_BUCKETS_TRACKED
            ):
                st.buckets.append(bucket)
        return bucket

    # -- report --------------------------------------------------------------

    def model_state(self, name: str) -> Dict[str, Any]:
        """One model's controller state for the serving report."""
        with self._mu:
            st = self._models.get(name)
            if st is None:
                return {
                    "cap_scale": 1.0,
                    "wait_scale": 1.0,
                    "brownout_phase": BROWNOUT_PHASES[0],
                    "padding_classes": [],
                }
            return {
                "cap_scale": round(st.cap_scale, 4),
                "wait_scale": round(st.wait_scale, 4),
                "brownout_phase": BROWNOUT_PHASES[st.phase],
                "padding_classes": sorted(st.buckets),
                **(
                    {"p99_ms": round(st.p99_ms, 3)}
                    if st.p99_ms is not None
                    else {}
                ),
            }

    def brownout_summary(self) -> Dict[str, str]:
        """Models currently in any brownout phase -> phase name."""
        with self._mu:
            return {
                name: BROWNOUT_PHASES[st.phase]
                for name, st in sorted(self._models.items())
                if st.phase > 0
            }


__all__ = [
    "BROWNOUT_PHASES",
    "LAST_BUCKET_DECISION",
    "PRIORITY_CLASSES",
    "ServingController",
    "resolve_priority",
]
