#
# Pipeline — the analog of reference pipeline.py (159 LoC): a pyspark.ml-
# style Pipeline whose fit detects the [VectorAssembler, accelerated
# estimator] pattern and bypasses the assembler by feeding the scalar
# columns directly as featuresCols (reference pipeline.py:85-119 replaces
# the assembler with a NoOpTransformer) — array-column materialization is
# pure overhead for a columnar data plane.
#
from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from .core import Estimator, Model, Transformer, _TpuEstimator
from .data import DatasetLike
from .params import Param, TypeConverters
from .utils import get_logger


class VectorAssembler(Transformer):
    """pyspark.ml.feature.VectorAssembler parity for the pandas data plane:
    packs scalar input columns into one array-valued column."""

    inputCols = Param("_", "inputCols", "input column names.",
                      TypeConverters.toListString)
    outputCol = Param("_", "outputCol", "output column name.",
                      TypeConverters.toString)

    def __init__(
        self,
        inputCols: Optional[List[str]] = None,
        outputCol: Optional[str] = None,
    ) -> None:
        super().__init__()
        if inputCols is not None:
            self._set(inputCols=inputCols)
        if outputCol is not None:
            self._set(outputCol=outputCol)

    def setInputCols(self, value: List[str]) -> "VectorAssembler":
        self._set(inputCols=value)
        return self

    def setOutputCol(self, value: str) -> "VectorAssembler":
        self._set(outputCol=value)
        return self

    def getInputCols(self) -> List[str]:
        return self.getOrDefault("inputCols")

    def getOutputCol(self) -> str:
        return self.getOrDefault("outputCol")

    def _transform(self, dataset: DatasetLike):
        import pandas as pd

        if not isinstance(dataset, pd.DataFrame):
            raise TypeError("VectorAssembler requires a pandas DataFrame")
        cols = self.getOrDefault("inputCols")
        out = dataset.copy()
        out[self.getOrDefault("outputCol")] = list(
            np.ascontiguousarray(dataset[cols].to_numpy(np.float64))
        )
        return out


class NoOpTransformer(Transformer):
    """Identity stage standing in for a bypassed assembler (reference
    pipeline.py:52-62)."""

    def _transform(self, dataset: DatasetLike):
        return dataset


class Pipeline(Estimator):
    """pyspark.ml.Pipeline parity with the reference's assembler bypass
    (reference pipeline.py:52-159).

    Examples
    --------
    >>> import numpy as np, pandas as pd
    >>> from spark_rapids_ml_tpu.pipeline import Pipeline, VectorAssembler
    >>> from spark_rapids_ml_tpu.classification import LogisticRegression
    >>> rng = np.random.default_rng(0)
    >>> df = pd.DataFrame({"a": rng.normal(size=100), "b": rng.normal(size=100)})
    >>> df["label"] = (df["a"] > 0).astype(float)
    >>> pipe = Pipeline(stages=[
    ...     VectorAssembler(inputCols=["a", "b"], outputCol="features"),
    ...     LogisticRegression(maxIter=50),
    ... ])
    >>> model = pipe.fit(df)
    >>> float((model.transform(df)["prediction"] == df["label"]).mean()) > 0.9
    True
    """

    def __init__(self, stages: Optional[List[Any]] = None) -> None:
        super().__init__()
        self._stages: List[Any] = stages or []
        self.logger = get_logger(type(self))

    def setStages(self, value: List[Any]) -> "Pipeline":
        self._stages = value
        return self

    def getStages(self) -> List[Any]:
        return self._stages

    def _maybe_bypass_assembler(self, stages: List[Any]) -> List[Any]:
        """Replace [VectorAssembler -> accelerated estimator] with
        [NoOp -> estimator(featuresCols=input scalars)] (reference
        pipeline.py:85-119)."""
        out = list(stages)
        for i in range(len(out) - 1):
            st, nxt = out[i], out[i + 1]
            if (
                isinstance(st, VectorAssembler)
                and isinstance(nxt, _TpuEstimator)
                and nxt.hasParam("featuresCols")
                and st.isSet("inputCols")
                and st.isSet("outputCol")
            ):
                features_col = (
                    nxt.getOrDefault("featuresCol")
                    if nxt.hasParam("featuresCol") and nxt.isDefined("featuresCol")
                    else None
                )
                if features_col == st.getOrDefault("outputCol"):
                    est = nxt.copy()
                    est.setFeaturesCol(st.getOrDefault("inputCols"))
                    out[i] = NoOpTransformer()
                    out[i + 1] = est
                    self.logger.info(
                        "Bypassing VectorAssembler: feeding scalar columns "
                        f"{st.getOrDefault('inputCols')} directly"
                    )
        return out

    def _fit(self, dataset: DatasetLike) -> "PipelineModel":
        stages = self._maybe_bypass_assembler(self._stages)
        fitted: List[Any] = []
        df = dataset
        for i, stage in enumerate(stages):
            if isinstance(stage, Transformer):
                fitted.append(stage)
                df = stage.transform(df)
            elif isinstance(stage, Estimator):
                model = stage.fit(df)
                fitted.append(model)
                if i < len(stages) - 1:
                    df = model.transform(df)
            else:
                raise TypeError(f"Pipeline stage {stage} is neither "
                                "Estimator nor Transformer")
        return PipelineModel(fitted)


class PipelineModel(Model):
    """Fitted pipeline (pyspark PipelineModel parity)."""

    def __init__(self, stages: List[Any]) -> None:
        super().__init__()
        self.stages = stages

    def _transform(self, dataset: DatasetLike):
        df = dataset
        for stage in self.stages:
            df = stage.transform(df)
        return df


__all__ = [
    "Pipeline",
    "PipelineModel",
    "VectorAssembler",
    "NoOpTransformer",
]
