#
# Compile observability — where the non-execute time goes.  XLA
# compilation is this repo's second currency after HBM: a cold fit pays
# tens of seconds of lowering+compile (the 87.8 s round-1 finding that
# motivated shape bucketing), an elastic mesh shrink re-lowers every
# donated staging program for the surviving device set, and a precision
# flip drops every compiled kernel — none of which was measurable
# before this module.  Two mechanisms, used together:
#
#   jax.monitoring   where available (jax >= 0.4.x ships
#                    `register_event_duration_secs_listener`), a
#                    process-global listener turns jax's own compile
#                    events (`/jax/core/compile/jaxpr_trace_duration`,
#                    `.../jaxpr_to_mlir_module_duration`,
#                    `.../backend_compile_duration`) into the
#                    `compile_seconds{fn=,phase=}` histogram and the
#                    `compiles_total{fn=}` counter.  The `fn` label is
#                    the innermost `compile_label(...)` scope active on
#                    the compiling thread (FitTelemetry labels the whole
#                    fit with its estimator name; the staging engine
#                    labels its program builds), so compile time
#                    attributes to the work that paid it.
#   explicit spans   `compile_span(fn)` wraps our OWN lowering seams
#                    (the staging-program builders in parallel/mesh.py)
#                    in a timed trace span + the same histogram — the
#                    fallback that keeps the numbers flowing on jax
#                    builds without the monitoring hooks.
#
# Recompiles are always EXPLICIT: `note_recompile(fn, reason)` bumps
# `recompiles_total{fn=,reason=}` and drops a `recompile[fn]` instant
# marker into the active run's trace buffer — so an elastic recovery's
# re-lowering storm (`mesh.drop_staging_programs`) is visible inside the
# span tree of the fit it interrupted, next to the retry and recovery
# markers.
#
# No jax import at module scope (telemetry/ rule); the listener installs
# lazily on the first fit, by which point jax is loaded anyway.
#
from __future__ import annotations

import contextlib
import threading

from .locks import named_lock
from typing import Iterator

from .registry import counter, histogram

# compile durations cluster far below the fit-duration buckets: a
# recompiled staging program is ~10 ms, a cold solver lowering ~1-100 s
_COMPILE_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0,
    60.0, 120.0, 300.0,
)

compile_seconds = histogram(
    "compile_seconds",
    "Seconds spent in jax tracing/lowering/XLA compilation, by label "
    "and phase",
    buckets=_COMPILE_BUCKETS,
)
compiles_total = counter(
    "compiles_total", "XLA backend compilations observed, by label"
)
recompiles_total = counter(
    "recompiles_total",
    "Compiled programs dropped and re-lowered, by label and reason",
)

# jax.monitoring event key -> phase label; events outside this map are
# not compile-related and stay unrecorded
_PHASE_BY_KEY = {
    "/jax/core/compile/jaxpr_trace_duration": "trace",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "lower",
    "/jax/core/compile/backend_compile_duration": "backend_compile",
}

_tls = threading.local()
_install_lock = named_lock("compile_install")
_installed = False


def current_label() -> str:
    """The innermost compile-label scope on this thread ("unlabeled"
    outside any scope)."""
    stack = getattr(_tls, "labels", None)
    return stack[-1] if stack else "unlabeled"


def snapshot_labels() -> tuple:
    """This thread's label stack, for adoption by a worker thread
    (tracing.adopt_trace_context carries it together with the trace
    buffer/run id, so compiles inside a watchdog-guarded dispatch
    attribute to the fit that issued it)."""
    return tuple(getattr(_tls, "labels", ()) or ())


def adopt_labels(stack) -> None:
    """Install a snapshot taken by `snapshot_labels` on this thread."""
    _tls.labels = list(stack)


@contextlib.contextmanager
def compile_label(name: str) -> Iterator[None]:
    """Attribute every compile event recorded on this thread inside the
    scope to `name` (nests; innermost wins).  FitTelemetry scopes the
    whole fit with the estimator name, so `compile_seconds{fn="KMeans"}`
    answers "what did KMeans fits spend compiling"."""
    stack = getattr(_tls, "labels", None)
    if stack is None:
        stack = _tls.labels = []
    stack.append(str(name))
    try:
        yield
    finally:
        stack.pop()


def _on_duration(key: str, duration_s: float, **_kw) -> None:
    phase = _PHASE_BY_KEY.get(key)
    if phase is None:
        return
    label = current_label()
    compile_seconds.observe(float(duration_s), fn=label, phase=phase)
    if phase == "backend_compile":
        compiles_total.inc(fn=label)


def install_jax_listener() -> bool:
    """Register the jax.monitoring duration listener (idempotent; jax
    offers no per-listener removal, so it installs once per process).
    Returns whether the listener is active — False on jax builds
    without the monitoring API, where only the explicit `compile_span`
    seams record."""
    global _installed
    with _install_lock:
        if _installed:
            return True
        try:
            from jax import monitoring

            monitoring.register_event_duration_secs_listener(_on_duration)
        except Exception:
            return False
        _installed = True
        return True


def listener_installed() -> bool:
    return _installed


@contextlib.contextmanager
def compile_span(fn: str) -> Iterator[None]:
    """Time one of OUR lowering seams (a staging-program build, an
    explicit re-lower) as a trace span + a `compile_seconds{fn=,
    phase="explicit"}` observation — the jax-version-independent path.
    The monitoring listener (when active) also records the inner jax
    phases under the same `fn` via the label scope."""
    import time

    from ..tracing import trace

    t0 = time.perf_counter()
    with compile_label(fn):
        with trace(f"compile[{fn}]"):
            yield
    compile_seconds.observe(
        time.perf_counter() - t0, fn=fn, phase="explicit"
    )


def note_recompile(fn: str, reason: str, count: int = 1) -> None:
    """Record that compiled program(s) under `fn` were dropped and must
    re-lower (`reason`: elastic_shrink, precision_change, ...).  Bumps
    `recompiles_total{fn=,reason=}` and drops a `recompile[fn]` instant
    marker stamped with the active run id — the elastic-recovery caller
    runs on the interrupted fit's (adopted) thread, so the marker lands
    inside that fit's span tree."""
    recompiles_total.inc(int(count), fn=fn, reason=reason)
    try:
        from ..tracing import event

        event(f"recompile[{fn}]", detail=f"reason={reason} n={int(count)}")
    except Exception:
        pass


__all__ = [
    "adopt_labels",
    "compile_label",
    "compile_seconds",
    "compile_span",
    "compiles_total",
    "current_label",
    "install_jax_listener",
    "listener_installed",
    "note_recompile",
    "recompiles_total",
    "snapshot_labels",
]
