#
# Named instrumented locks — the contention half of the progress
# observatory.  ~20 modules guard shared state behind anonymous
# `threading.Lock()`s; when PR 14's two-thread `describe()` wedged the
# whole suite at zero CPU, the only way to learn WHO held WHAT was
# faulthandler plus an afternoon.  `named_lock(name)` wraps the stdlib
# primitives with per-lock accounting the rest of telemetry can read:
#
#   lock_acquisitions_total{lock}   every successful acquire
#   lock_contended_total{lock}      acquires that had to block
#   lock_wait_seconds_total{lock}   blocked-acquire seconds
#   lock_hold_seconds_total{lock}   held seconds (outermost for RLocks)
#
# plus a LIVE holder/waiter table (`lock_table()`) the hang doctor
# (telemetry/hang_doctor.py) turns into a wait-for graph, and slow-wait
# instant markers (`lock_slow_wait[<name>]`, threshold
# `lock_slow_wait_ms`) dropped into the active run's span tree so a
# stalled fit's trace SHOWS the lock it starved on.
#
# Every lock name must be declared in LOCK_CATALOG (mirroring
# METRIC_CATALOG) — the graft-lint `named-lock` rule cross-checks every
# module-level lock in the package against it, so an anonymous lock can
# no longer join the tree unprofiled.
#
# Design constraints (why this module looks the way it does):
#   - stdlib-only at module scope, config/tracing/registry imported
#     LAZILY: the metrics registry's own internal lock is a named lock,
#     so locks.py must be importable while registry.py is mid-import.
#   - the hot path (uncontended acquire/release) updates PLAIN
#     attributes — they are serialized by the lock itself, the one
#     mutex that is always held when they change.  Registry counters
#     are published by `publish_lock_metrics()` (exporters, fit
#     reports, hang-doctor ticks), never inline: an acquire of the
#     registry lock must not recurse into the registry.
#   - holder/waiter bookkeeping uses single GIL-atomic dict/attribute
#     writes, readable lock-free by the doctor.
#
from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Dict, List, Optional

# ---------------------------------------------------------------------------
# Canonical lock catalog.  Every `named_lock("<name>")` literal in the
# package must resolve here and every entry must be minted somewhere
# (staleness flagged) — the graft-lint `named-lock` rule
# (analysis/rules_concurrency.py) parses this table from disk exactly
# like METRIC_CATALOG.  `module` names the declaring file (repo-
# relative); `kind` is lock / rlock / condition.  Tests may mint ad-hoc
# names freely (the rule only audits package modules).
# ---------------------------------------------------------------------------
LOCK_CATALOG: Dict[str, Dict[str, Any]] = {
    # parallel/: dataset + chunk caches, staging writers, codecs
    "device_cache": {
        "kind": "lock", "module": "spark_rapids_ml_tpu/parallel/device_cache.py",
    },
    "dataset_cache": {
        "kind": "rlock", "module": "spark_rapids_ml_tpu/parallel/device_cache.py",
    },
    "chunk_cache": {
        "kind": "rlock", "module": "spark_rapids_ml_tpu/parallel/device_cache.py",
    },
    "staging_writer": {
        "kind": "lock", "module": "spark_rapids_ml_tpu/parallel/mesh.py",
    },
    "chunk_codec": {
        "kind": "lock", "module": "spark_rapids_ml_tpu/parallel/chunk_codec.py",
    },
    # cross-process reduce seam: KV sequence counters + cached psum jits
    "multiproc_kv": {
        "kind": "lock", "module": "spark_rapids_ml_tpu/parallel/context.py",
    },
    # serving/: the dispatcher condition + report state + model registry
    "serving_dispatch": {
        "kind": "condition", "module": "spark_rapids_ml_tpu/serving/server.py",
    },
    "serving_report": {
        "kind": "lock", "module": "spark_rapids_ml_tpu/serving/server.py",
    },
    "serving_registry": {
        "kind": "rlock", "module": "spark_rapids_ml_tpu/serving/registry.py",
    },
    # the feedback controller's actuator/phase state; leaf lock — the
    # dispatcher condition may be held when entering it, never the
    # reverse
    "serving_control": {
        "kind": "lock", "module": "spark_rapids_ml_tpu/serving/control.py",
    },
    # stats/: the shared one-pass statistics locks — `device_step` is
    # the serializer the PR-14 deadlock taught us to hold across
    # dispatch-to-sync of every mesh-sharded accumulator step
    "stat_metrics": {
        "kind": "lock", "module": "spark_rapids_ml_tpu/stats/engine.py",
    },
    "device_step": {
        "kind": "lock", "module": "spark_rapids_ml_tpu/stats/engine.py",
    },
    # monitor/
    "drift_monitor": {
        "kind": "rlock", "module": "spark_rapids_ml_tpu/monitor/monitor.py",
    },
    # resilience/
    "faults": {
        "kind": "lock", "module": "spark_rapids_ml_tpu/resilience/faults.py",
    },
    "elastic": {
        "kind": "lock", "module": "spark_rapids_ml_tpu/resilience/elastic.py",
    },
    # pod rank-loss recovery: generation/plan state, liveness tables,
    # and the in-flight cross-process wait registry
    "pod_state": {
        "kind": "lock", "module": "spark_rapids_ml_tpu/resilience/pod.py",
    },
    # telemetry/: the registry's own internal lock is named too (it is
    # one of the hottest in the process), plus the install/http/owner
    # guards
    "metrics_registry": {
        "kind": "rlock", "module": "spark_rapids_ml_tpu/telemetry/registry.py",
    },
    "memory_telemetry": {
        "kind": "lock", "module": "spark_rapids_ml_tpu/telemetry/memory.py",
    },
    "telemetry_http": {
        "kind": "lock", "module": "spark_rapids_ml_tpu/telemetry/exporters.py",
    },
    "heartbeat_owners": {
        "kind": "lock", "module": "spark_rapids_ml_tpu/telemetry/heartbeat.py",
    },
    "compile_install": {
        "kind": "lock", "module": "spark_rapids_ml_tpu/telemetry/compile.py",
    },
    "flight_recorder": {
        "kind": "rlock",
        "module": "spark_rapids_ml_tpu/telemetry/flight_recorder.py",
    },
    "flight_recorder_install": {
        "kind": "lock",
        "module": "spark_rapids_ml_tpu/telemetry/flight_recorder.py",
    },
    "fit_telemetry_active": {
        "kind": "lock", "module": "spark_rapids_ml_tpu/telemetry/report.py",
    },
    "hang_doctor": {
        "kind": "rlock",
        "module": "spark_rapids_ml_tpu/telemetry/hang_doctor.py",
    },
    # core.py: fitMultiple's thread-safe model iterator
    "fit_multiple": {
        "kind": "lock", "module": "spark_rapids_ml_tpu/core.py",
    },
    # native.py: the one-shot native library build/load guard
    "native_build": {
        "kind": "lock", "module": "spark_rapids_ml_tpu/native.py",
    },
    # fleet.py: pod-observatory state — peer clock samples, current
    # pass bookkeeping, drift-window publish/fetch caches.  Never held
    # across a KV wait
    "fleet_state": {
        "kind": "lock", "module": "spark_rapids_ml_tpu/telemetry/fleet.py",
    },
}

# waits shorter than this never record a lock_wait utilization interval
# (micro-contention is normal; the attribution table wants stalls)
_MIN_WAIT_INTERVAL_S = 0.001

# bootstrap lock guarding the live-instance table and publish state —
# deliberately a BARE threading.Lock: the instrumentation cannot
# instrument itself (the named-lock rule exempts this module)
_table_mu = threading.Lock()
_instances: List = []  # (name, kind, weakref-to-core)

# slow-wait conf cache: re-read at most every few seconds so the
# contended path never pays a per-acquire config-lock round trip
_slow_conf: Dict[str, float] = {"t": 0.0, "ms": 50.0}
_SLOW_CONF_REFRESH_S = 5.0

_tls = threading.local()


def _register(core: "_LockCore", kind: str) -> None:
    with _table_mu:
        # prune dead instances lazily (staging writers churn per fit)
        _instances[:] = [e for e in _instances if e[2]() is not None]
        _instances.append((core.name, kind, weakref.ref(core)))


def _slow_wait_ms() -> float:
    now = time.monotonic()
    if now - _slow_conf["t"] >= _SLOW_CONF_REFRESH_S:
        ms = _slow_conf["ms"]
        try:
            from ..config import get_config

            ms = float(get_config("lock_slow_wait_ms"))
        except Exception:
            pass
        with _table_mu:
            _slow_conf["ms"] = ms
            _slow_conf["t"] = now
    return _slow_conf["ms"]


def _note_wait(name: str, waited_s: float) -> None:
    """A contended acquire finished: record the utilization interval and
    (past the threshold) drop a slow-wait instant into the active run's
    span tree.  Re-entrancy guarded — recording the event itself takes
    locks (the flight-recorder tap), and a slow wait THERE must not
    recurse."""
    if getattr(_tls, "in_note", False):
        return
    _tls.in_note = True
    try:
        t1 = time.perf_counter()
        if waited_s >= _MIN_WAIT_INTERVAL_S:
            from .utilization import note_interval

            note_interval("lock_wait", t1 - waited_s, t1, cause=name,
                          domain="any")
        ms = _slow_wait_ms()
        if ms > 0 and waited_s * 1e3 >= ms:
            from ..tracing import event

            event(
                f"lock_slow_wait[{name}]",
                detail=f"waited_ms={waited_s * 1e3:.1f}",
            )
    except Exception:
        pass  # instrumentation must never fail the acquire it observed
    finally:
        _tls.in_note = False


class _LockCore:
    """Instrumentation shared by every named-lock flavor: an inner
    stdlib lock plus wait/hold accounting and a live holder/waiter
    table.  The plain counter attributes are mutated only while the
    inner lock is HELD (the lock serializes its own bookkeeping);
    holder/waiter entries are single GIL-atomic writes, read lock-free
    by `lock_table()` and the hang doctor."""

    reentrant = False

    __slots__ = (
        "name", "_inner", "_waiters", "_holder",
        "acquisitions", "contended", "wait_s", "hold_s", "_pub",
        "__weakref__",
    )

    def __init__(self, name: str, inner: Any) -> None:
        self.name = name
        self._inner = inner
        # tid -> (thread name, wall t0, perf t0); set before a blocking
        # acquire, popped after — the doctor's waiter view
        self._waiters: Dict[int, tuple] = {}
        # (tid, thread name, wall t, perf t, depth) or None
        self._holder: Optional[tuple] = None
        self.acquisitions = 0
        self.contended = 0
        self.wait_s = 0.0
        self.hold_s = 0.0
        # last totals published to the registry (per-core, so a dying
        # instance can never make the process counters run backwards)
        self._pub = {"acq": 0, "cont": 0, "wait": 0.0, "hold": 0.0}
        _register(self, "rlock" if self.reentrant else "lock")

    # -- acquire/release ----------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # fast path first: a nonblocking inner acquire succeeds both for
        # an uncontended lock AND for the reentrant owner, so the common
        # case pays one C acquire, one get_ident and one clock read.
        # Thread NAMES are resolved lazily by lock_table() — the hot
        # path must not pay threading.current_thread() per acquire.
        if self._inner.acquire(False):
            me = threading.get_ident()
            h = self._holder
            self.acquisitions += 1
            if self.reentrant and h is not None and h[0] == me:
                self._holder = (me, h[1], h[2], h[3], h[4] + 1)
            else:
                # wall "since" (slot 2) derives lazily in lock_table()
                # from the perf stamp — one clock read on the hot path
                self._holder = (me, None, None, time.perf_counter(), 1)
            return True
        if not blocking:
            return False
        me = threading.get_ident()
        t0 = time.perf_counter()
        self._waiters[me] = (
            threading.current_thread().name, time.time(), t0,
        )
        try:
            ok = self._inner.acquire(True, timeout)
        finally:
            self._waiters.pop(me, None)
        if ok:
            self._note_acquired(me, time.perf_counter() - t0)
        return ok

    def _note_acquired(self, me: int, waited_s: float) -> None:
        # runs while HOLDING the inner lock: plain attribute updates are
        # serialized by the lock itself
        self.acquisitions += 1
        self._holder = (
            me, threading.current_thread().name,
            time.time(), time.perf_counter(), 1,
        )
        if waited_s > 0.0:
            self.contended += 1
            self.wait_s += waited_s
            _note_wait(self.name, waited_s)

    def release(self) -> None:
        h = self._holder
        me = threading.get_ident()
        if h is not None and (h[0] == me or not self.reentrant):
            # plain Locks may legally be released from another thread;
            # account the hold to whoever acquired it
            if self.reentrant and h[4] > 1:
                self._holder = (h[0], h[1], h[2], h[3], h[4] - 1)
            else:
                self.hold_s += time.perf_counter() - h[3]
                self._holder = None
        self._inner.release()

    def __enter__(self) -> "_LockCore":
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.release()

    def locked(self) -> bool:
        inner_locked = getattr(self._inner, "locked", None)
        if callable(inner_locked):
            return bool(inner_locked())
        return self._holder is not None

    # -- Condition protocol (threading.Condition delegates to these) --------

    def _is_owned(self) -> bool:
        h = self._holder
        return h is not None and h[0] == threading.get_ident()

    def _release_save(self):
        """Full release for Condition.wait: close out the hold window
        (whatever the reentrant depth) and hand back the state
        `_acquire_restore` needs to rebuild it."""
        h = self._holder
        me = threading.get_ident()
        depth = 1
        if h is not None and h[0] == me:
            self.hold_s += time.perf_counter() - h[3]
            depth = h[4]
            self._holder = None
        inner_save = getattr(self._inner, "_release_save", None)
        if callable(inner_save):
            return (inner_save(), depth)
        self._inner.release()
        return (None, depth)

    def _acquire_restore(self, saved) -> None:
        """Reacquire after Condition.wait.  The idle notify wait happened
        on the condition's internal waiter lock, NOT here — this measures
        only the genuine reacquire contention."""
        state, depth = saved
        me = threading.get_ident()
        t0 = time.perf_counter()
        self._waiters[me] = (
            threading.current_thread().name, time.time(), t0,
        )
        try:
            inner_restore = getattr(self._inner, "_acquire_restore", None)
            if state is not None and callable(inner_restore):
                inner_restore(state)
            else:
                self._inner.acquire()
        finally:
            self._waiters.pop(me, None)
        waited = time.perf_counter() - t0
        self.acquisitions += 1
        self._holder = (
            me, threading.current_thread().name,
            time.time(), time.perf_counter(), depth,
        )
        if waited > _MIN_WAIT_INTERVAL_S:
            self.contended += 1
            self.wait_s += waited
            _note_wait(self.name, waited)

    def __repr__(self) -> str:
        h = self._holder
        state = f"held by {h[1]} (depth {h[4]})" if h else "unlocked"
        return f"<NamedLock {self.name!r} {state}>"


class NamedLock(_LockCore):
    """Instrumented `threading.Lock`."""


class NamedRLock(_LockCore):
    """Instrumented `threading.RLock`."""

    reentrant = True


def named_lock(name: str, kind: str = "lock"):
    """Mint one instrumented lock registered under `name`.

    `kind`: "lock" (default), "rlock", or "condition" (a
    `threading.Condition` built over an instrumented RLock, so the
    condition's own acquire/release traffic is profiled and its holder
    shows in the wait-for table).  Package modules must use names
    declared in `LOCK_CATALOG` (graft-lint `named-lock` rule); tests may
    mint ad-hoc names freely."""
    if kind == "lock":
        return NamedLock(name, threading.Lock())
    if kind == "rlock":
        return NamedRLock(name, threading.RLock())
    if kind == "condition":
        return threading.Condition(NamedRLock(name, threading.RLock()))
    raise ValueError(f"unknown named_lock kind: {kind!r}")


# ---------------------------------------------------------------------------
# Live table + registry publication
# ---------------------------------------------------------------------------


def _live_cores() -> List[tuple]:
    with _table_mu:
        entries = [(n, k, ref()) for n, k, ref in _instances]
    return [(n, k, c) for n, k, c in entries if c is not None]


def lock_table() -> List[Dict[str, Any]]:
    """The live holder/waiter table: one row per lock INSTANCE (several
    instances may share a catalog name — e.g. two serving servers), with
    cumulative wait/hold accounting and, when held or waited on, who by
    and for how long.  Lock-free snapshot; values are observational."""
    now_wall = time.time()
    now_perf = time.perf_counter()
    # thread names resolve here, not on the acquire hot path
    tnames = {t.ident: t.name for t in threading.enumerate()}
    out: List[Dict[str, Any]] = []
    for name, kind, core in _live_cores():
        row: Dict[str, Any] = {
            "name": name,
            "kind": kind,
            "acquisitions": core.acquisitions,
            "contended": core.contended,
            "wait_s": round(core.wait_s, 6),
            "hold_s": round(core.hold_s, 6),
        }
        h = core._holder
        if h is not None:
            since = h[2] if h[2] is not None else (
                now_wall - (now_perf - h[3])
            )
            row["holder"] = {
                "thread_id": h[0],
                "thread": h[1] or tnames.get(h[0], "?"),
                "since": round(since, 3),
                "held_s": round(max(now_wall - since, 0.0), 3),
                "depth": h[4],
            }
        waiters = [
            {
                "thread_id": tid,
                "thread": w[0],
                "since": round(w[1], 3),
                "waited_s": round(max(now_wall - w[1], 0.0), 3),
            }
            for tid, w in list(core._waiters.items())
        ]
        if waiters:
            row["waiters"] = waiters
        out.append(row)
    return out


_metrics: Dict[str, Any] = {}

# serializes publish_lock_metrics: concurrent publishers (the hang
# doctor's tick, a Prometheus scrape, a fit report) would read the same
# per-core ledger, double-inc the registry counters AND overshoot the
# ledger past the core's actual totals (silently swallowing the next
# real deltas).  Deliberately NOT _table_mu: the slow-wait path takes
# _table_mu while holding an arbitrary named lock, and a publisher
# holds this mutex while acquiring the registry lock — sharing one
# mutex across those two orders could deadlock.  Nothing ever waits on
# _publish_mu while holding another lock, so this order is safe.
_publish_mu = threading.Lock()


def _ensure_metrics() -> Dict[str, Any]:
    if not _metrics:
        from .registry import counter

        acq = counter(
            "lock_acquisitions_total", "Named-lock acquisitions by lock"
        )
        cont = counter(
            "lock_contended_total",
            "Named-lock acquisitions that had to block, by lock",
        )
        wait = counter(
            "lock_wait_seconds_total",
            "Seconds spent blocked acquiring named locks, by lock",
        )
        hold = counter(
            "lock_hold_seconds_total",
            "Seconds named locks were held, by lock",
        )
        with _table_mu:
            _metrics.update(acq=acq, cont=cont, wait=wait, hold=hold)
    return _metrics


def publish_lock_metrics() -> None:
    """Fold every live lock's accounting into the registry counter
    families (per-core monotone deltas, so counters never run
    backwards).  Called by `dump_prometheus`, fit-report builds and the
    hang doctor's tick — never inline on the acquire path, which must
    not recurse into the registry."""
    m = _ensure_metrics()
    with _publish_mu:
        for name, _kind, core in _live_cores():
            pub = core._pub
            d_acq = core.acquisitions - pub["acq"]
            d_cont = core.contended - pub["cont"]
            d_wait = core.wait_s - pub["wait"]
            d_hold = core.hold_s - pub["hold"]
            if d_acq > 0:
                m["acq"].inc(d_acq, lock=name)
                pub["acq"] += d_acq
            if d_cont > 0:
                m["cont"].inc(d_cont, lock=name)
                pub["cont"] += d_cont
            if d_wait > 0:
                m["wait"].inc(d_wait, lock=name)
                pub["wait"] += d_wait
            if d_hold > 0:
                m["hold"].inc(d_hold, lock=name)
                pub["hold"] += d_hold


__all__ = [
    "LOCK_CATALOG",
    "NamedLock",
    "NamedRLock",
    "lock_table",
    "named_lock",
    "publish_lock_metrics",
]
