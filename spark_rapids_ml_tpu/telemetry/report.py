#
# Per-fit telemetry reports — one JSON artifact per fit answering "what
# did this fit actually do": the stage timing tree (from the run's
# spans), bytes staged and staging throughput, cache hits/evictions,
# retries and recoveries (with iterations salvaged), and the solver's
# iteration count / loss curve.  `core.Estimator.fit` opens a
# `FitTelemetry` around every fit: it mints the run id (tracing.py
# `run_context`), snapshots the registry before/after, and — when the
# `telemetry_dir` conf is set — writes `<dir>/fit_<Est>_<run_id>.json`.
# The same dict is reachable in-process as `model.fit_report()`.
#
from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Any, Dict, List, Optional

from .locks import named_lock
from .registry import REGISTRY, delta, histogram

_fit_seconds = histogram(
    "fit_duration_seconds", "Wall-clock seconds per estimator fit"
)

# model attribute names the solver summary scans, in preference order
_N_ITER_KEYS = ("n_iter_", "num_iters", "n_iter")
_LOSS_CURVE_KEYS = ("objective_history", "loss_curve", "hist")
_FINAL_LOSS_KEYS = ("objective", "inertia_", "cost", "loss")


def span_tree(events: List[Any]) -> List[Dict[str, Any]]:
    """Nest the run's events into a start-ordered tree keyed off each
    span's recorded depth (instant markers attach as zero-duration
    leaves).  Events arrive start-sorted from
    `tracing.get_all_trace_events`."""
    root: List[Dict[str, Any]] = []
    stack: List[tuple] = []  # (depth, node)
    for e in sorted(events, key=lambda e: (e.t0, -e.t1)):
        node: Dict[str, Any] = {
            "name": e.name,
            "t0": round(e.t0, 6),
            "seconds": round(e.seconds, 6),
        }
        if e.detail:
            node["detail"] = e.detail
        if getattr(e, "kind", "span") == "instant":
            node["instant"] = True
        node["children"] = []
        while stack and stack[-1][0] >= e.depth:
            stack.pop()
        (stack[-1][1]["children"] if stack else root).append(node)
        stack.append((e.depth, node))
    # drop empty children arrays for a compact artifact
    def _prune(nodes: List[Dict[str, Any]]) -> None:
        for n in nodes:
            if n["children"]:
                _prune(n["children"])
            else:
                del n["children"]

    _prune(root)
    return root


def solver_summary(model: Any) -> Dict[str, Any]:
    """Iteration count / loss curve from a fitted model's attributes —
    generic over the solver families (KMeans `n_iter_`, LogReg
    `num_iters` + `objective_history`, LinReg diag `n_iter`)."""
    attrs: Dict[str, Any] = {}
    getter = getattr(model, "_get_model_attributes", None)
    if callable(getter):
        try:
            attrs = dict(getter() or {})
        except Exception:
            attrs = {}
    out: Dict[str, Any] = {}
    for k in _N_ITER_KEYS:
        v = attrs.get(k, getattr(model, k, None))
        if v is not None:
            try:
                out["n_iter"] = int(v)
                break
            except (TypeError, ValueError):
                continue
    for k in _LOSS_CURVE_KEYS:
        v = attrs.get(k)
        if v is not None:
            try:
                out["loss_curve"] = [float(x) for x in list(v)]
                break
            except (TypeError, ValueError):
                continue
    for k in _FINAL_LOSS_KEYS:
        v = attrs.get(k, getattr(model, k, None))
        if isinstance(v, (int, float)):
            out["final_loss"] = float(v)
            break
    return out


def _view_delta(d: Dict[str, Dict[str, Any]], family: str) -> Dict[str, Any]:
    """One dict-view family's changed keys from a registry `delta`:
    {'key=hits': 3} -> {'hits': 3}."""
    out = {}
    for ls, v in d.get(family, {}).items():
        k = ls.split("=", 1)[1] if ls.startswith("key=") else ls
        out[k] = v
    return out


class FitTelemetry:
    """The per-fit observability scope `core.Estimator.fit` wraps every
    fit in: mints the run id, opens the root `fit[<Est>]` span, and after
    the fit builds the report dict from the run's spans plus registry
    deltas.

    The registry deltas are process-global: when fits OVERLAP (a caller
    pulling `fitMultiple` from several threads), each report's
    staging/cache/recovery sections include the concurrent fits'
    activity too — the report then carries `"concurrent_fits": true` so
    the numbers are read as process-level, not per-fit.  The span tree
    and resilience marker counts stay exact (run-id filtered)."""

    # fits currently inside span(); >1 means the registry deltas span
    # more than this fit
    _active = 0
    _active_lock = named_lock("fit_telemetry_active")

    def __init__(self, estimator_name: str) -> None:
        self.estimator = estimator_name
        self.run_id: str = ""
        self.report: Optional[Dict[str, Any]] = None
        self._before: Dict[str, Dict[str, Any]] = {}
        self._t0 = 0.0
        self._t1 = 0.0
        self._overlapped = False
        self._watermark = None

    @contextlib.contextmanager
    def span(self):
        from ..tracing import mint_run_id, run_context, trace
        from .compile import compile_label, install_jax_listener
        from .exporters import maybe_start_http_server
        from .memory import FitMemoryWatermark

        maybe_start_http_server()
        install_jax_listener()
        self.run_id = mint_run_id("fit")
        # fold the named locks' pending accounting in BEFORE the
        # baseline snapshot, so this fit's registry delta reflects only
        # the lock traffic of its own window
        from .locks import publish_lock_metrics

        publish_lock_metrics()
        self._before = REGISTRY.snapshot()
        self._t0 = time.time()
        cls = FitTelemetry
        with cls._active_lock:
            cls._active += 1
            self._overlapped = cls._active > 1
        self._watermark = FitMemoryWatermark(self.run_id, self.estimator)
        self._watermark.open()
        try:
            with run_context(self.run_id):
                # compile events on this thread (and adopted workers)
                # attribute to this estimator
                with compile_label(self.estimator):
                    with trace(f"fit[{self.estimator}]"):
                        yield self
        finally:
            with cls._active_lock:
                self._overlapped = self._overlapped or cls._active > 1
                cls._active -= 1
            self._watermark.close()
        self._t1 = time.time()

    def _resilience_section(
        self, events: List[Any], deltas: Dict[str, Dict[str, Any]]
    ) -> Dict[str, Any]:
        instants = [e for e in events if getattr(e, "kind", "") == "instant"]
        sec = {
            "retries": sum(
                1 for e in instants if e.name.startswith("retry[")
            ),
            "faults_injected": sum(
                1 for e in instants if e.name.startswith("fault_injected[")
            ),
            "dispatch_timeouts": sum(
                1 for e in instants if e.name.startswith("dispatch_timeout[")
            ),
            "checkpoint_resumes": sum(
                1 for e in instants
                if e.name.endswith("_resume") or e.name == "elastic_recovery[resumed]"
            ),
        }
        rec = _view_delta(deltas, "recovery")
        if rec:
            sec["recoveries"] = rec
            if "iterations_salvaged" in rec:
                sec["iterations_salvaged"] = rec["iterations_salvaged"]
        return sec

    def _compile_section(
        self, events: List[Any], deltas: Dict[str, Dict[str, Any]]
    ) -> Dict[str, Any]:
        """Compile time + recompile count for this fit.  The recompile
        count is RUN-EXACT (the `recompile[...]` instant markers carry
        this run's id); the seconds come from the registry delta of
        `compile_seconds`, filtered to this estimator's label where the
        jax.monitoring listener attributed them (process-global samples
        under other labels are excluded, so a concurrent fit's compiles
        don't leak in)."""
        sec: Dict[str, Any] = {}
        recompiles = [
            e
            for e in events
            if getattr(e, "kind", "") == "instant"
            and e.name.startswith("recompile[")
        ]
        if recompiles:
            sec["recompiles"] = len(recompiles)
            sec["recompiled"] = sorted(
                {e.name[len("recompile["):-1] for e in recompiles}
            )
        seconds = 0.0
        count = 0
        for ls, v in deltas.get("compile_seconds", {}).items():
            if f"fn={self.estimator}" not in ls.split(","):
                continue
            if isinstance(v, dict):
                seconds += float(v.get("sum", 0.0))
                count += int(v.get("count", 0))
        if count:
            sec["seconds"] = round(seconds, 4)
            sec["events"] = count
        return sec

    def _profile_section(self) -> Dict[str, Any]:
        """Cross-reference the XProf capture (`profile_dir` conf) so the
        device profile and this report's run_id stop being orphaned from
        each other: the report names the profile directory plus any
        artifact entries written during this fit's window."""
        from ..config import get_config

        pdir = str(get_config("profile_dir") or "")
        if not pdir:
            return {}
        sec: Dict[str, Any] = {"dir": pdir}
        try:
            arts = []
            # top level: trace FILES only (the 'plugins' container dir's
            # mtime refreshes on every child write and is not itself an
            # artifact); under plugins/profile the per-capture TIMESTAMP
            # DIRECTORIES are the artifacts XProf consumes
            for root, dirs_ok in (
                (pdir, False),
                (os.path.join(pdir, "plugins", "profile"), True),
            ):
                if not os.path.isdir(root):
                    continue
                upper = (self._t1 if self._t1 > 0 else time.time()) + 1.0
                for name in os.listdir(root):
                    p = os.path.join(root, name)
                    if not dirs_ok and not os.path.isfile(p):
                        continue
                    # written during (± 1 s of) THIS fit's window: a
                    # later fit sharing the profile_dir must not have
                    # its capture attributed here
                    if self._t0 - 1.0 <= os.path.getmtime(p) <= upper:
                        arts.append(os.path.relpath(p, pdir))
            if arts:
                sec["artifacts"] = sorted(arts)
        except OSError:
            pass
        return sec

    def build(self, model: Any = None) -> Dict[str, Any]:
        """Assemble the report from the run's events + registry deltas.
        Called once, after `span()` exits.  Reads only the CALLING
        thread's trace buffer: every event of this run lands there by
        construction (watchdog workers adopt it; concurrent fits on
        other threads carry other run ids), so the per-fit cost stays a
        single bounded-buffer scan, not a cross-thread merge."""
        from ..tracing import get_trace_events

        events = [
            e for e in get_trace_events() if e.run_id == self.run_id
        ]
        from .locks import publish_lock_metrics

        publish_lock_metrics()
        deltas = delta(self._before, REGISTRY.snapshot())
        wall = max(self._t1 - self._t0, 0.0)
        _fit_seconds.observe(wall, estimator=self.estimator)

        staging: Dict[str, Any] = _view_delta(deltas, "staging_counts")
        # the staging engine's throughput numbers are process-wide
        # LAST-RUN state: copy them only when that run completed inside
        # this fit's window (the `stamp` key) and no OTHER fit overlapped
        # it — a cache-served / serial-path / concurrent fit must not
        # inherit someone else's bytes and MB/s
        try:
            from ..parallel.mesh import STAGE_METRICS

            if (
                not self._overlapped
                and STAGE_METRICS.get("stamp", 0) >= self._t0
            ):
                for k in ("bytes", "mb_per_s", "overlap_ratio", "pieces"):
                    v = STAGE_METRICS.get(k)
                    if v is not None:
                        staging[k] = v
        except Exception:
            pass

        # fused stage-and-solve metrics (fused.py FUSED_METRICS): same
        # last-run-state discipline as STAGE_METRICS — copy only when the
        # fused pass completed inside this fit's window and no other fit
        # overlapped; likewise the PCA solver decision (ops/pca.py)
        fused: Dict[str, Any] = {}
        solver_decision: Dict[str, Any] = {}
        try:
            from ..fused import FUSED_METRICS

            if (
                not self._overlapped
                and FUSED_METRICS.get("stamp", 0) >= self._t0
            ):
                fused = {
                    k: FUSED_METRICS.get(k)
                    for k in (
                        "kind", "solver", "passes", "chunks", "bytes",
                        "wall_s", "host_prep_s", "device_acc_s",
                        "overlap_s", "overlap_fraction",
                    )
                    if FUSED_METRICS.get(k) is not None
                }
        except Exception:
            pass
        # statistic-program engine metrics (stats/engine.py
        # STAT_METRICS): same last-run-state discipline — a fused
        # multi-program pass that completed inside this fit's window
        # lands as the report's `stats` section
        stats_section: Dict[str, Any] = {}
        try:
            from ..stats.engine import STAT_METRICS

            if (
                not self._overlapped
                and STAT_METRICS.get("stamp", 0) >= self._t0
            ):
                stats_section = {
                    k: STAT_METRICS.get(k)
                    for k in (
                        "label", "programs", "passes", "chunks", "bytes",
                        "wall_s", "host_prep_s", "device_acc_s",
                        "overlap_s", "overlap_fraction",
                    )
                    if STAT_METRICS.get(k) is not None
                }
        except Exception:
            pass
        try:
            from ..ops.pca import LAST_SOLVER_DECISION

            if (
                not self._overlapped
                and LAST_SOLVER_DECISION.get("stamp", 0) >= self._t0
            ):
                solver_decision = {
                    k: LAST_SOLVER_DECISION.get(k)
                    for k in ("solver", "reason", "d", "k", "l", "power_iters")
                    if LAST_SOLVER_DECISION.get(k) is not None
                }
        except Exception:
            pass
        # parallel parquet-reader decision (fused.resolve_parquet_readers):
        # same last-run-state discipline — "why did this fit decode with
        # N readers" is part of the solver_decision story
        try:
            from ..fused import LAST_READER_DECISION

            if (
                not self._overlapped
                and LAST_READER_DECISION.get("stamp", 0) >= self._t0
            ):
                solver_decision.update({
                    k: LAST_READER_DECISION[k]
                    for k in (
                        "parquet_readers", "parquet_readers_mode",
                        "parquet_readers_reason",
                    )
                    if LAST_READER_DECISION.get(k) is not None
                })
        except Exception:
            pass
        # serving padding-class decision (serving/control.py): which
        # {1,1.5}x2^k bucket the last coalesced micro-batch padded to —
        # same last-run-state discipline, prefixed so the serving keys
        # never collide with the solver/reader keys above
        try:
            from ..serving.control import LAST_BUCKET_DECISION

            if (
                not self._overlapped
                and LAST_BUCKET_DECISION.get("stamp", 0) >= self._t0
            ):
                solver_decision.update({
                    f"serving_{k}": LAST_BUCKET_DECISION[k]
                    for k in ("model", "rows", "bucket")
                    if LAST_BUCKET_DECISION.get(k) is not None
                })
        except Exception:
            pass
        # pod pass report (telemetry/fleet.py LAST_PASS_REPORT): the
        # straggler table of the last pod-correlated pass — same
        # last-run-state discipline, so a report only claims a pass
        # that completed inside its own window
        pass_report: Dict[str, Any] = {}
        try:
            from . import fleet as _fleet

            rep = _fleet.pass_report()
            if (
                not self._overlapped
                and rep.get("stamp", 0) >= self._t0
            ):
                pass_report = rep
        except Exception:
            pass

        report: Dict[str, Any] = {
            "run_id": self.run_id,
            "estimator": self.estimator,
            # set when another fit overlapped this one: the registry
            # deltas below then include the concurrent fits' activity
            # (span tree / marker counts stay run-exact)
            **({"concurrent_fits": True} if self._overlapped else {}),
            "t0": round(self._t0, 6),
            "t1": round(self._t1, 6),
            "wall_s": round(wall, 4),
            "spans": span_tree(events),
            "staging": staging,
            "cache": _view_delta(deltas, "device_cache"),
            "resilience": self._resilience_section(events, deltas),
        }
        # per-fit lock profile: this window's acquisitions / contended
        # acquires / wait seconds per lock (registry counter deltas,
        # process-global like the other delta sections — `concurrent_
        # fits` marks the overlap caveat above)
        lock_sec: Dict[str, Any] = {}
        for fam, short in (
            ("lock_wait_seconds_total", "wait_s"),
            ("lock_contended_total", "contended"),
            ("lock_acquisitions_total", "acquisitions"),
        ):
            for ls, v in deltas.get(fam, {}).items():
                name = ls.split("=", 1)[1] if ls.startswith("lock=") else ls
                lock_sec.setdefault(name, {})[short] = (
                    round(v, 6) if isinstance(v, float) else v
                )
        if any(e.get("wait_s") for e in lock_sec.values()):
            report["locks"] = {
                k: v for k, v in sorted(
                    lock_sec.items(),
                    key=lambda kv: -(kv[1].get("wait_s", 0) or 0),
                )
                if v.get("wait_s")
            }
        # the run's utilization timeline (telemetry/utilization.py):
        # device-busy fraction + ranked idle-gap attribution
        from . import utilization as _utilization

        util = _utilization.summarize(run_id=self.run_id, scope="fit")
        if util:
            report["utilization"] = util
        chunk_cache = _view_delta(deltas, "chunk_cache")
        if any(chunk_cache.values()):
            report["chunk_cache"] = chunk_cache
        if fused:
            report["fused"] = fused
        if stats_section:
            report["stats"] = stats_section
        if pass_report:
            report["pass_report"] = pass_report
        if solver_decision:
            report["solver_decision"] = solver_decision
        if self._watermark is not None:
            memory = self._watermark.section()
            if memory:
                report["memory"] = memory
        comp = self._compile_section(events, deltas)
        if comp:
            report["compile"] = comp
        prof = self._profile_section()
        if prof:
            report["profile"] = prof
        solver = solver_summary(model) if model is not None else {}
        if solver:
            report["solver"] = solver
        # drift baseline (monitor/): a fit that captured a fingerprint
        # records what it holds — the serving-side comparison is live
        # state (server.report()), but "did THIS fit capture a
        # baseline, from how many rows" belongs in the fit artifact
        fp = getattr(model, "_drift_baseline", None)
        if fp is not None:
            report["drift"] = {
                "baseline_rows": int(fp.n),
                "columns": int(fp.d),
            }
        self.report = report
        return report

    def attach(self, model: Any, log: Optional[object] = None) -> None:
        """Build the report, expose it as `model.fit_report()`, and write
        the JSON artifact when `telemetry_dir` is set.  Never raises —
        observability must not fail the fit it observed."""
        try:
            report = self.build(model)
        except Exception as e:  # pragma: no cover - defensive
            _warn(log, f"fit report build failed ({type(e).__name__}: {e})")
            return
        try:
            model._fit_report = report
        except Exception:
            pass  # models without assignable attributes keep the artifact
        from ..config import get_config

        tdir = str(get_config("telemetry_dir") or "")
        if not tdir:
            return
        try:
            os.makedirs(tdir, exist_ok=True)
            path = os.path.join(
                tdir, f"fit_{self.estimator}_{self.run_id}.json"
            )
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                json.dump(report, f, indent=1)
            os.replace(tmp, path)
        except OSError as e:
            _warn(log, f"fit report write to {tdir} failed ({e})")


def _warn(log: Optional[object], msg: str) -> None:
    if log is None:
        from ..utils import get_logger

        log = get_logger("spark_rapids_ml_tpu.telemetry")
    log.warning(msg)


__all__ = ["FitTelemetry", "solver_summary", "span_tree"]
