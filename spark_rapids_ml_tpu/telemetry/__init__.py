#
# telemetry/ — the unified observability layer.  Four PRs of machinery
# (staging engine, device cache, retry, elastic recovery) each grew a
# module-level metric dict and timestamp-less trace events; this package
# gives them one queryable surface:
#
#   registry.py   typed process-global metrics registry
#                 (Counter/Gauge/Histogram with labels, snapshot/reset).
#                 The legacy dicts — `mesh.STAGE_METRICS`/`STAGE_COUNTS`,
#                 `device_cache.CACHE_METRICS`,
#                 `elastic.RECOVERY_METRICS` — are now thin views over it
#                 (`dict_view`), so every old caller keeps working while
#                 the registry exports everything.
#   exporters.py  Chrome trace-event JSON (loads in Perfetto: one track
#                 per thread + an instant-marker track for resilience
#                 events) and Prometheus text format (`dump_prometheus`,
#                 plus the opt-in stdlib HTTP endpoint gated by the
#                 `telemetry_port` conf).
#   report.py     per-fit JSON reports (stage timing tree, bytes staged,
#                 cache hits, retries/recoveries, solver loss curve) —
#                 written under `telemetry_dir` and reachable as
#                 `model.fit_report()`.
#   heartbeat.py  progress heartbeat for long iterative solvers
#                 (iteration/loss/throughput every
#                 `heartbeat_interval_s`).
#
# Span correlation lives in tracing.py: every span/instant carries
# absolute t0/t1, the recording thread id, and the `run_id` core.py
# mints per fit/transform — so retries, device-loss recoveries and
# checkpoint resumes land inside the spans they interrupted.
#
# Like resilience/, this package imports neither jax nor numpy at module
# scope: reading a counter must not pay the accelerator import.
#
from .exporters import (  # noqa: F401
    chrome_trace,
    dump_chrome_trace,
    dump_prometheus,
    maybe_start_http_server,
    parse_prometheus,
    start_http_server,
    stop_http_server,
)
from .heartbeat import Heartbeat  # noqa: F401
from .registry import (  # noqa: F401
    REGISTRY,
    DictView,
    Metric,
    MetricsRegistry,
    counter,
    delta,
    dict_view,
    gauge,
    histogram,
    reset_metrics,
    snapshot,
)
from .report import FitTelemetry, solver_summary, span_tree  # noqa: F401

__all__ = [
    "DictView",
    "FitTelemetry",
    "Heartbeat",
    "Metric",
    "MetricsRegistry",
    "REGISTRY",
    "chrome_trace",
    "counter",
    "delta",
    "dict_view",
    "dump_chrome_trace",
    "dump_prometheus",
    "gauge",
    "histogram",
    "maybe_start_http_server",
    "parse_prometheus",
    "reset_metrics",
    "snapshot",
    "solver_summary",
    "span_tree",
    "start_http_server",
    "stop_http_server",
]
