#
# telemetry/ — the unified observability layer.  Four PRs of machinery
# (staging engine, device cache, retry, elastic recovery) each grew a
# module-level metric dict and timestamp-less trace events; this package
# gives them one queryable surface:
#
#   registry.py   typed process-global metrics registry
#                 (Counter/Gauge/Histogram with labels, snapshot/reset).
#                 The legacy dicts — `mesh.STAGE_METRICS`/`STAGE_COUNTS`,
#                 `device_cache.CACHE_METRICS`,
#                 `elastic.RECOVERY_METRICS` — are now thin views over it
#                 (`dict_view`), so every old caller keeps working while
#                 the registry exports everything.
#   exporters.py  Chrome trace-event JSON (loads in Perfetto: one track
#                 per thread + an instant-marker track for resilience
#                 events) and Prometheus text format (`dump_prometheus`,
#                 plus the opt-in stdlib HTTP endpoint gated by the
#                 `telemetry_port` conf).
#   report.py     per-fit JSON reports (stage timing tree, bytes staged,
#                 cache hits, retries/recoveries, solver loss curve) —
#                 written under `telemetry_dir` and reachable as
#                 `model.fit_report()`.
#   heartbeat.py  progress heartbeat for long iterative solvers
#                 (iteration/loss/throughput every
#                 `heartbeat_interval_s`).
#   memory.py     HBM accounting: per-device live/peak byte gauges
#                 (`device.memory_stats()` where the backend has it, a
#                 deterministic `jax.live_arrays()` census elsewhere),
#                 per-fit peak watermarks, and the
#                 `budget_drift_ratio{est=}` feedback that checks the
#                 byte model's predictions against the chips.
#   compile.py    compile observability: `compile_seconds{fn=,phase=}`
#                 from a jax.monitoring listener (explicit span wrappers
#                 where the hooks are absent) and `recompiles_total` for
#                 every dropped-and-re-lowered program (elastic shrink,
#                 precision flips), with `recompile[...]` markers inside
#                 the interrupted fit's span tree.
#
# Span correlation lives in tracing.py: every span/instant carries
# absolute t0/t1, the recording thread id, and the `run_id` core.py
# mints per fit/transform — so retries, device-loss recoveries and
# checkpoint resumes land inside the spans they interrupted.
#
# Like resilience/, this package imports neither jax nor numpy at module
# scope: reading a counter must not pay the accelerator import.
#
from .aggregate import (  # noqa: F401
    dump_merged,
    merge_prometheus,
    scrape_endpoints,
)
from .compile import (  # noqa: F401
    compile_label,
    compile_span,
    install_jax_listener,
    note_recompile,
)
from .exporters import (  # noqa: F401
    chrome_trace,
    dump_chrome_trace,
    dump_prometheus,
    maybe_start_http_server,
    parse_prometheus,
    parse_prometheus_families,
    render_families,
    start_http_server,
    stop_http_server,
)
from .flight_recorder import (  # noqa: F401
    RECORDER,
    FlightRecorder,
    note_failure,
)

# pod observatory — the cross-rank correlation layer (stdlib-only at
# module scope, like everything else in this package)
from .fleet import (  # noqa: F401
    begin_pod_pass,
    clock_offsets,
    complete_pod_pass,
    merge_chrome_traces,
    mint_incident_id,
)
from .hang_doctor import (  # noqa: F401
    DOCTOR,
    HangDoctor,
    all_thread_stacks,
    build_wait_graph,
    find_cycles,
)
from .heartbeat import Heartbeat  # noqa: F401
from .locks import (  # noqa: F401
    LOCK_CATALOG,
    lock_table,
    named_lock,
    publish_lock_metrics,
)
from .memory import (  # noqa: F401
    FitMemoryWatermark,
    SimulatedMemoryProvider,
    get_provider,
    record_budget_decision,
    record_prediction,
    reset_memory_telemetry,
    sample_devices,
)
from .registry import (  # noqa: F401
    METRIC_CATALOG,
    REGISTRY,
    DictView,
    Metric,
    MetricsRegistry,
    check_cardinality,
    counter,
    delta,
    dict_view,
    gauge,
    histogram,
    reset_metrics,
    snapshot,
)
from .report import FitTelemetry, solver_summary, span_tree  # noqa: F401
from .utilization import (  # noqa: F401
    note_interval,
    summarize_utilization,
)

# the flight recorder is ALWAYS-ON by design: hook it onto the tracing
# tap as soon as the telemetry package loads (every fit/serving path
# imports it), so the black box is recording before the first span.  The
# `flight_recorder` conf gates recording itself, re-read cheaply inside
# record().
from .flight_recorder import install as _install_flight_recorder  # noqa: E402

_install_flight_recorder()

# the hang doctor rides the same tap (always-on, `hang_doctor` conf):
# its watchdog thread spawns lazily on the first recorded event, so
# importing the package starts no threads
from .hang_doctor import install as _install_hang_doctor  # noqa: E402

_install_hang_doctor()

__all__ = [
    "DOCTOR",
    "DictView",
    "FitMemoryWatermark",
    "FitTelemetry",
    "FlightRecorder",
    "HangDoctor",
    "Heartbeat",
    "LOCK_CATALOG",
    "METRIC_CATALOG",
    "Metric",
    "MetricsRegistry",
    "RECORDER",
    "REGISTRY",
    "SimulatedMemoryProvider",
    "begin_pod_pass",
    "check_cardinality",
    "chrome_trace",
    "clock_offsets",
    "compile_label",
    "compile_span",
    "complete_pod_pass",
    "counter",
    "delta",
    "dict_view",
    "dump_chrome_trace",
    "dump_merged",
    "dump_prometheus",
    "gauge",
    "get_provider",
    "histogram",
    "install_jax_listener",
    "all_thread_stacks",
    "build_wait_graph",
    "find_cycles",
    "lock_table",
    "maybe_start_http_server",
    "merge_chrome_traces",
    "merge_prometheus",
    "mint_incident_id",
    "named_lock",
    "note_failure",
    "note_interval",
    "note_recompile",
    "publish_lock_metrics",
    "summarize_utilization",
    "parse_prometheus",
    "parse_prometheus_families",
    "record_budget_decision",
    "record_prediction",
    "render_families",
    "reset_memory_telemetry",
    "reset_metrics",
    "sample_devices",
    "scrape_endpoints",
    "snapshot",
    "solver_summary",
    "span_tree",
    "start_http_server",
    "stop_http_server",
]
