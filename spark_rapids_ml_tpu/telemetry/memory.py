#
# HBM / device-memory telemetry — the measurement half of the byte model.
# Every staging decision in this repo runs on PREDICTED bytes (the
# `_over_device_budget` formula in core.py, the device cache's n_dev+2
# gather reservations, the streaming chunk sizing), and until this module
# nothing ever checked the predictions against the chips: the gather
# factors and reservation math were faith-based.  Snap ML's wins are
# attributed through exact per-phase accounting of accelerator memory and
# the DuHL out-of-core scheme only holds together because HBM occupancy
# is measured, not assumed (PAPERS.md) — this is that layer:
#
#   providers   where the bytes come from.  `RealMemoryProvider` reads
#               `device.memory_stats()` (TPU/GPU runtimes report
#               bytes_in_use / peak_bytes_in_use); backends without it
#               (this CPU container) degrade to the DETERMINISTIC
#               `SimulatedMemoryProvider`, which censuses
#               `jax.live_arrays()` per device — so tests and
#               fault-injection runs exercise the full sampling path
#               with real numbers instead of a stubbed no-op.
#   gauges      `device_bytes_in_use{device=}` / `device_bytes_peak{device=}`
#               in the metrics registry on every sample.
#   watermarks  `FitMemoryWatermark` — opened per fit by
#               `FitTelemetry` (report.py): tracks the per-device PEAK
#               over the fit's samples and collects the byte-model
#               predictions recorded during the fit.
#   drift       `budget_drift_ratio{est=}` = measured GROWTH (peak minus
#               the fit-start baseline — residency predating the fit is
#               subtracted out) / predicted bytes, per prediction label
#               — in the registry and the per-fit report, so a
#               reservation factor that overshoots (ratio << 1) or a
#               byte model that lies (ratio >> 1) is a number on a
#               dashboard, not an OOM postmortem.
#
# Sampling points: watermark open/close, after every `RowStager.stage`,
# each solver heartbeat (rate-limited), and — when the
# `memory_sample_interval_s` conf is > 0 — a background daemon thread
# while at least one fit is active.
#
# Like the rest of telemetry/, no jax import at module scope: reading a
# gauge must not pay the accelerator import.  jax loads lazily on the
# first sample (by which point the caller has imported it anyway).
#
from __future__ import annotations

import threading

from .locks import named_lock
import time
from typing import Any, Dict, Optional

from .registry import counter, gauge

_in_use_g = gauge(
    "device_bytes_in_use", "Last sampled live bytes per device"
)
_peak_g = gauge(
    "device_bytes_peak", "Process-lifetime peak sampled bytes per device"
)
_drift_g = gauge(
    "budget_drift_ratio",
    "Measured peak bytes / predicted bytes per estimate label",
)
_pred_g = gauge(
    "budget_predicted_bytes", "Last predicted bytes per estimate label"
)
_decisions_c = counter(
    "budget_decisions_total",
    "Byte-model budget decisions by label and outcome",
)
_samples_c = counter(
    "memory_samples_total", "Device memory samples taken, by provider"
)

_lock = named_lock("memory_telemetry")
# run_id -> FitMemoryWatermark for every fit currently inside its span
_active: Dict[str, "FitMemoryWatermark"] = {}
# process-lifetime peaks the _peak_g gauge mirrors (provider peaks reset
# with the provider; these survive a provider swap)
_process_peak: Dict[str, int] = {}
_last_sample_t = 0.0

_provider: Optional["MemoryProvider"] = None
_sampler_thread: Optional[threading.Thread] = None


# ---------------------------------------------------------------------------
# Providers
# ---------------------------------------------------------------------------


class MemoryProvider:
    """One way of answering "how many bytes does each device hold".
    `sample()` returns {device_id: {"bytes_in_use": int,
    "peak_bytes_in_use": int}} for every active device it can answer
    for (missing devices simply don't appear)."""

    name = "none"

    def sample(self) -> Dict[int, Dict[str, int]]:  # pragma: no cover
        raise NotImplementedError


class RealMemoryProvider(MemoryProvider):
    """`device.memory_stats()` — the TPU/GPU runtime's own allocator
    counters.  Devices whose backend lacks the call (CPU) are skipped;
    `available()` says whether ANY active device reports stats."""

    name = "real"

    @staticmethod
    def available() -> bool:
        from ..parallel.mesh import active_devices

        for d in active_devices():
            try:
                if d.memory_stats() is not None:
                    return True
            except Exception:
                continue
        return False

    def sample(self) -> Dict[int, Dict[str, int]]:
        from ..parallel.mesh import active_devices

        out: Dict[int, Dict[str, int]] = {}
        for d in active_devices():
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            out[int(d.id)] = {
                "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                "peak_bytes_in_use": int(
                    stats.get("peak_bytes_in_use",
                              stats.get("bytes_in_use", 0))
                ),
            }
        return out


class SimulatedMemoryProvider(MemoryProvider):
    """Deterministic provider for backends without allocator counters
    (the CPU test mesh): live bytes are censused from
    `jax.live_arrays()` — each array's addressable shards attribute
    their exact nbytes to the device holding them — and the peak is the
    running max this provider has observed.  Deterministic given the
    same program, so tests can assert exact byte accounting, and the
    whole sampling/watermark/drift path runs in CPU CI instead of
    no-oping."""

    name = "simulated"

    def __init__(self) -> None:
        self._peaks: Dict[int, int] = {}

    def sample(self) -> Dict[int, Dict[str, int]]:
        import jax

        from ..parallel.mesh import active_devices

        # every active device answers, at 0 when nothing lives on it —
        # otherwise a device whose arrays all freed would keep its stale
        # last gauge value forever
        live: Dict[int, int] = {int(d.id): 0 for d in active_devices()}
        for arr in jax.live_arrays():
            try:
                if getattr(arr, "is_deleted", None) and arr.is_deleted():
                    continue
                for sh in arr.addressable_shards:
                    did = int(sh.device.id)
                    live[did] = live.get(did, 0) + int(sh.data.nbytes)
            except Exception:
                continue  # a mid-donation array can vanish underneath us
        out: Dict[int, Dict[str, int]] = {}
        for did, b in live.items():
            peak = max(self._peaks.get(did, 0), b)
            self._peaks[did] = peak
            out[did] = {"bytes_in_use": b, "peak_bytes_in_use": peak}
        return out


def get_provider() -> Optional[MemoryProvider]:
    """The provider the `memory_provider` conf selects — resolved once
    and cached (`reset_memory_telemetry()` re-resolves):
    "auto" = real where any device reports `memory_stats()`, else
    simulated; "real" / "simulated" force one; "off" disables sampling
    entirely."""
    global _provider
    with _lock:
        if _provider is not None:
            return _provider if _provider.name != "none" else None
    from ..config import get_config

    mode = str(get_config("memory_provider") or "auto").lower()
    if mode == "off":
        prov: MemoryProvider = MemoryProvider()  # name="none" sentinel
    elif mode == "real":
        prov = RealMemoryProvider()
    elif mode == "simulated":
        prov = SimulatedMemoryProvider()
    else:
        prov = (
            RealMemoryProvider()
            if RealMemoryProvider.available()
            else SimulatedMemoryProvider()
        )
    with _lock:
        _provider = prov
    return prov if prov.name != "none" else None


def reset_memory_telemetry() -> None:
    """Drop the cached provider and process peaks (tests; after flipping
    the `memory_provider` conf)."""
    global _provider, _last_sample_t
    with _lock:
        _provider = None
        _process_peak.clear()
        _last_sample_t = 0.0


# ---------------------------------------------------------------------------
# Sampling
# ---------------------------------------------------------------------------


def sample_devices() -> Dict[int, int]:
    """Take one sample: update the registry gauges, feed every active
    fit watermark, and return {device_id: bytes_in_use}.  Returns {} (and
    touches nothing) when the provider is off/unavailable.  Never raises
    — memory observability must not fail the work it observes."""
    global _last_sample_t
    try:
        prov = get_provider()
        if prov is None:
            return {}
        stats = prov.sample()
    except Exception:
        return {}
    now = time.time()
    with _lock:
        _last_sample_t = now
        watermarks = list(_active.values())
    out: Dict[int, int] = {}
    for did, s in stats.items():
        key = str(did)
        out[did] = s["bytes_in_use"]
        _in_use_g.set(s["bytes_in_use"], device=key)
        # read-max-write under the lock: the daemon sampler and explicit
        # sample points race here, and a lost update would let a peak
        # regress.  The gauge set stays inside too — otherwise a stale
        # peak computed before losing the race could overwrite a newer
        # one on the exported family
        with _lock:
            peak = max(
                _process_peak.get(key, 0),
                s["peak_bytes_in_use"],
                s["bytes_in_use"],
            )
            _process_peak[key] = peak
            _peak_g.set(peak, device=key)
    _samples_c.inc(provider=prov.name)
    for wm in watermarks:
        wm._observe(stats)
    return out


def maybe_sample(min_interval_s: float = 1.0) -> None:
    """Rate-limited `sample_devices` for hot callers (solver heartbeats):
    samples only when the last sample is older than `min_interval_s`
    (or the `memory_sample_interval_s` conf when larger)."""
    from ..config import get_config

    try:
        conf = float(get_config("memory_sample_interval_s") or 0.0)
    except Exception:
        conf = 0.0
    spacing = max(min_interval_s, conf)
    with _lock:
        due = (time.time() - _last_sample_t) >= spacing
    if due:
        sample_devices()


def _sampler_loop() -> None:
    """Background sampling while >= 1 fit is active
    (`memory_sample_interval_s` > 0).  Exits when the last watermark
    closes; the next fit starts a fresh thread."""
    from ..config import get_config

    while True:
        try:
            interval = float(get_config("memory_sample_interval_s") or 0.0)
        except Exception:
            interval = 0.0
        with _lock:
            if interval <= 0 or not _active:
                global _sampler_thread
                _sampler_thread = None
                return
        sample_devices()
        time.sleep(interval)


def _maybe_start_sampler() -> None:
    global _sampler_thread
    from ..config import get_config

    try:
        interval = float(get_config("memory_sample_interval_s") or 0.0)
    except Exception:
        interval = 0.0
    if interval <= 0:
        return
    with _lock:
        if _sampler_thread is not None and _sampler_thread.is_alive():
            return
        t = threading.Thread(
            target=_sampler_loop, name="memory-sampler", daemon=True
        )
        _sampler_thread = t
    t.start()


# ---------------------------------------------------------------------------
# Predictions (the byte model's side of the drift ratio)
# ---------------------------------------------------------------------------


def record_prediction(label: str, nbytes: float) -> None:
    """Record one byte-model prediction (a staging's padded-byte
    estimate, a cache reservation, a budget-decision operand).  Lands on
    the `budget_predicted_bytes{est=}` gauge and on every watermark whose
    run is active on this thread (workers adopt the caller's run id), so
    the fit that made the prediction owns its drift ratio."""
    nbytes = float(nbytes)
    if nbytes <= 0:
        return
    _pred_g.set(nbytes, est=label)
    from ..tracing import current_run_id

    rid = current_run_id()
    if not rid:
        # no run on this thread -> no watermark owns the prediction; a
        # broadcast to every active fit would cross-contaminate reports
        return
    with _lock:
        wms = [w for r, w in _active.items() if r == rid]
    for wm in wms:
        wm._predict(label, nbytes)


def record_budget_decision(label: str, need_bytes: float, over: bool) -> None:
    """One `_over_device_budget`-style decision: the predicted bytes it
    ran on plus the outcome, counted per label so the streamed-stats
    routing rate is visible next to the drift its estimates carry."""
    _decisions_c.inc(label=label, over=str(bool(over)).lower())
    record_prediction(label, need_bytes)


def note_measured_drift(
    label: str, predicted_bytes: float, baseline_bytes: float = 0.0
) -> Optional[float]:
    """Immediate point-in-time drift for a prediction that just became
    real (a device-cache insert: reservation vs the bytes the staging
    actually added): samples now, sets `budget_drift_ratio{est=label}`
    to (measured total - `baseline_bytes`) / predicted, and returns the
    ratio (None when the provider is off or the prediction is empty).
    Pass the PRE-action total as `baseline_bytes` so unrelated residency
    (other cache entries, a concurrent fit's arrays) doesn't inflate the
    ratio into measuring occupancy instead of model error."""
    predicted_bytes = float(predicted_bytes)
    if predicted_bytes <= 0:
        return None
    measured = sample_devices()
    if not measured:
        return None
    grew = max(sum(measured.values()) - float(baseline_bytes), 0.0)
    ratio = round(grew / predicted_bytes, 4)
    _drift_g.set(ratio, est=label)
    return ratio


# ---------------------------------------------------------------------------
# Per-fit watermark
# ---------------------------------------------------------------------------


class FitMemoryWatermark:
    """Peak-byte watermark for one fit: opened/closed by `FitTelemetry`
    around the fit span.  Collects the per-device peak over every sample
    taken during the fit plus the byte-model predictions recorded inside
    it, and renders the report's `memory` section — per-device peaks and
    one `budget_drift_ratio` per prediction label (measured peak total /
    predicted bytes), also set on the registry's
    `budget_drift_ratio{est=}` gauge."""

    def __init__(self, run_id: str, estimator: str = "") -> None:
        self.run_id = run_id
        self.estimator = estimator
        self.peaks: Dict[int, int] = {}
        # per-device bytes at this fit's FIRST sample: the drift ratio
        # measures the fit's GROWTH over this baseline, so residency that
        # predates the fit (cache entries, another fit's arrays) doesn't
        # inflate it into an occupancy number
        self.start: Dict[int, int] = {}
        # label -> LARGEST prediction recorded under it during this fit
        # (a re-staging after device loss predicts again; max — not sum —
        # keeps the ratio comparable to a peak)
        self.predictions: Dict[str, float] = {}
        self._samples = 0

    # -- lifecycle (FitTelemetry) -------------------------------------------

    def open(self) -> None:
        with _lock:
            _active[self.run_id] = self
        sample_devices()
        _maybe_start_sampler()

    def close(self) -> None:
        sample_devices()
        with _lock:
            _active.pop(self.run_id, None)

    # -- feed ---------------------------------------------------------------

    def _observe(self, stats: Dict[int, Dict[str, int]]) -> None:
        self._samples += 1
        for did, s in stats.items():
            b = max(s["bytes_in_use"], 0)
            self.start.setdefault(did, b)
            if b > self.peaks.get(did, 0):
                self.peaks[did] = b

    def _predict(self, label: str, nbytes: float) -> None:
        if nbytes > self.predictions.get(label, 0.0):
            self.predictions[label] = nbytes

    # -- output -------------------------------------------------------------

    def grew_bytes(self) -> int:
        """How many bytes this fit ADDED at its peak: peak total minus
        the fit-start baseline (floored at 0 — frees during the fit can
        push the total below where it started)."""
        peak_total = sum(self.peaks.values())
        start_total = sum(self.start.get(d, 0) for d in self.peaks)
        return max(peak_total - start_total, 0)

    def drift_ratios(self) -> Dict[str, float]:
        """Measured growth / predicted bytes, per prediction label — the
        byte-model error, not process occupancy: residency that predates
        the fit is subtracted out via the start baseline."""
        grew = float(self.grew_bytes())
        out: Dict[str, float] = {}
        if self._samples == 0:
            return out
        for label, pred in self.predictions.items():
            if pred > 0:
                out[label] = round(grew / pred, 4)
        return out

    def section(self) -> Dict[str, Any]:
        """The fit report's `memory` section ({} when sampling is off —
        the report then simply omits it)."""
        if not self.peaks and not self.predictions:
            return {}
        prov = None
        with _lock:
            if _provider is not None and _provider.name != "none":
                prov = _provider.name
        sec: Dict[str, Any] = {
            "provider": prov,
            "samples": self._samples,
            "per_device_peak_bytes": {
                str(d): int(b) for d, b in sorted(self.peaks.items())
            },
            "peak_total_bytes": int(sum(self.peaks.values())),
            "start_total_bytes": int(sum(self.start.values())),
            "grew_bytes": int(self.grew_bytes()),
        }
        if self.predictions:
            sec["predicted_bytes"] = {
                k: int(v) for k, v in sorted(self.predictions.items())
            }
        drift = self.drift_ratios()
        if drift:
            sec["budget_drift_ratio"] = drift
            label = self.estimator or "fit"
            for est, r in drift.items():
                _drift_g.set(r, est=f"{label}:{est}")
        return sec


__all__ = [
    "FitMemoryWatermark",
    "MemoryProvider",
    "RealMemoryProvider",
    "SimulatedMemoryProvider",
    "get_provider",
    "maybe_sample",
    "note_measured_drift",
    "record_budget_decision",
    "record_prediction",
    "reset_memory_telemetry",
    "sample_devices",
]
