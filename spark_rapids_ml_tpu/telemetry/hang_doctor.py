#
# Automatic hang doctor — the stall half of the progress observatory.
# PR 14's two-thread `describe()` deadlock wedged three tier-1 runs at
# zero CPU and was root-caused BY HAND with faulthandler; the doctor
# makes that diagnosis automatic and always-on.  A daemon thread
# (spawned on the first trace event; `hang_doctor` conf, default on)
# watches forward progress through signals the telemetry stack already
# emits:
#
#   trace-event flow      every span/instant bumps a tap counter (the
#                         same tap feed the flight recorder rides)
#   heartbeat advance     the `solver_iteration`/`solver_loss` gauges
#   serving collects      completed-request counts on the serving
#                         latency family
#
# A STALL is either (a) a thread stuck waiting on a named lock for
# `hang_doctor_stall_s` (telemetry/locks.py waiter table), or (b) work
# visibly in progress — live solver gauges, queued serving requests,
# held/waited named locks — with NO progress signal advancing for
# `hang_doctor_stall_s`.  On a stall the doctor:
#
#   1. captures ALL thread stacks (`sys._current_frames`),
#   2. builds the lock wait-for graph from the holder/waiter table and
#      detects cycles (naming the deadlocked threads and locks),
#   3. dumps a `reason="stall"` flight-recorder bundle — the stacks,
#      wait-for graph and lock table ride as attachments next to the
#      bundle's usual trace.json of the newest spans — under the
#      recorder's existing per-reason cooldown, counted by
#      `postmortems_total{reason="stall"}`.
#
# One stall EPISODE dumps once: the doctor re-arms only after a progress
# signal moves again, so a wedged run leaves one bundle, not one per
# tick.  Tick cost is microseconds (bench `utilization` section reports
# it); the default 120 s stall threshold keeps long XLA compiles — which
# emit no trace events while they run — from reading as stalls in CI.
#
from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

from . import locks
from .registry import REGISTRY, counter

TICKS = counter(
    "hang_doctor_ticks_total", "Hang-doctor watchdog evaluations"
)
STALLS = counter(
    "hang_doctor_stalls_total",
    "Stall episodes the hang doctor detected, by kind",
)

_DEFAULT_STALL_S = 120.0
# how long _diagnose waits for the flight-recorder dump thread before
# falling back to a stderr diagnosis (the dump path takes locks and
# writes files — in a badly wedged process those can hang too)
_DUMP_JOIN_S = 15.0
# poll cadence: fast enough to catch a stall within ~stall_s * 1.25,
# bounded so tiny test thresholds don't spin
_MIN_POLL_S = 0.05
_MAX_POLL_S = 2.0
_DISABLED_POLL_S = 0.5


def all_thread_stacks() -> str:
    """Every live thread's current stack, faulthandler-style, with
    thread names resolved — the evidence the PR-14 wedge had to be
    root-caused with by hand."""
    names = {t.ident: t.name for t in threading.enumerate()}
    parts: List[str] = []
    for tid, frame in sorted(sys._current_frames().items()):
        parts.append(
            f"--- thread {tid} ({names.get(tid, '?')}) ---\n"
            + "".join(traceback.format_stack(frame))
        )
    return "\n".join(parts)


def build_wait_graph(table: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Edges of the thread wait-for graph: one edge per (waiter, lock,
    holder) triple in the live lock table — thread W waits for lock L
    held by thread H."""
    edges: List[Dict[str, Any]] = []
    for row in table:
        holder = row.get("holder")
        if not holder:
            continue
        for w in row.get("waiters", ()):
            edges.append({
                "waiter_id": w["thread_id"],
                "waiter": w["thread"],
                "lock": row["name"],
                "holder_id": holder["thread_id"],
                "holder": holder["thread"],
                "waited_s": w.get("waited_s", 0.0),
            })
    return edges


def find_cycles(edges: List[Dict[str, Any]]) -> List[List[Dict[str, Any]]]:
    """Cycles in the wait-for graph, each as its edge list — a cycle IS
    a deadlock (every thread on it waits for a lock another one holds).
    A thread waits on at most one lock at a time, so successor-chasing
    with a visited set finds every cycle exactly once."""
    succ: Dict[int, Dict[str, Any]] = {}
    for e in edges:
        succ.setdefault(e["waiter_id"], e)
    cycles: List[List[Dict[str, Any]]] = []
    done: set = set()
    for start in succ:
        if start in done:
            continue
        path: List[int] = []
        seen_at: Dict[int, int] = {}
        node = start
        while node in succ and node not in done:
            if node in seen_at:
                cyc = path[seen_at[node]:]
                cycles.append([succ[t] for t in cyc])
                break
            seen_at[node] = len(path)
            path.append(node)
            node = succ[node]["holder_id"]
        done.update(path)
    return cycles


def describe_cycle(cycle: List[Dict[str, Any]]) -> str:
    """Human line naming the deadlocked threads and locks:
    `A -(lock1)-> B -(lock2)-> A`."""
    if not cycle:
        return ""
    hops = [f"{e['waiter']} -({e['lock']})-> " for e in cycle]
    return "".join(hops) + cycle[0]["waiter"]


class HangDoctor:
    """The process-global stall watchdog.  `install()` hooks it onto the
    tracing tap; the daemon spawns on the first observed event and then
    re-reads the `hang_doctor`/`hang_doctor_stall_s` confs every tick,
    so tests (and operators) retune it live."""

    def __init__(self, force_enabled: bool = False) -> None:
        # reentrant for the same reason as the flight recorder's
        # lock: on_event (a trace tap) takes it on the first event,
        # and the slow-wait instrumentation may emit a trace event
        # while it is held
        self._mu = locks.named_lock("hang_doctor", kind="rlock")
        # tests drive PRIVATE doctors tick-by-tick with the global
        # daemon conf'd off; force_enabled makes such an instance ignore
        # the `hang_doctor` conf (stall_s still reads from conf)
        self._force = force_enabled
        self._started = False
        self._thread: Optional[threading.Thread] = None
        self._events = 0  # tap counter; lone-writer += races lose only a tick
        self._last_fp: Any = None
        self._last_progress = time.monotonic()
        # the last diagnosed stall EPISODE: for a lock stall, the frozen
        # set of (lock, waiter) pairs — stable while other threads keep
        # making progress, so one stuck waiter in an otherwise-active
        # process dumps ONCE, not once per tick; for a no-progress
        # stall, the progress fingerprint (any advance re-arms)
        self._dumped_episode: Any = None

    # -- feed ----------------------------------------------------------------

    def on_event(self, _event: Any) -> None:
        """Tracing-tap entry point: count the event (progress signal)
        and make sure the watchdog thread exists."""
        self._events += 1
        if not self._started:
            self._ensure_thread()

    def _ensure_thread(self) -> None:
        with self._mu:
            if self._started:
                return
            self._started = True
            t = threading.Thread(
                target=self._loop, name="hang-doctor", daemon=True
            )
            self._thread = t
        t.start()

    # -- configuration -------------------------------------------------------

    def _conf(self) -> tuple:
        try:
            from ..config import get_config

            enabled = str(get_config("hang_doctor")).lower() != "off"
            stall_s = float(get_config("hang_doctor_stall_s"))
        except Exception:
            enabled, stall_s = True, _DEFAULT_STALL_S
        return enabled or self._force, max(stall_s, 0.1)

    # -- progress signals ----------------------------------------------------

    def _fingerprint(self) -> tuple:
        """A cheap hash of every forward-progress signal: trace-event
        count, the live solver gauges, completed serving requests.  Any
        change = the process moved."""
        solver: tuple = ()
        m = REGISTRY.get("solver_iteration")
        if m is not None:
            solver = tuple(sorted(m.samples().items()))
        collects = 0
        lat = REGISTRY.get("serving_request_latency_seconds")
        if lat is not None:
            collects = sum(
                h.get("count", 0)
                for h in lat.samples().values()
                if isinstance(h, dict)
            )
        return (self._events, solver, collects)

    def _reduce_waits(self) -> List[Dict[str, Any]]:
        """In-flight cross-process waits (resilience/pod.py kv_wait):
        thread, reduce tag, peer rank, waited seconds — the pod-scale
        analog of the lock waiter table."""
        try:
            from ..resilience.pod import live_reduce_waits

            return live_reduce_waits()
        except Exception:  # pragma: no cover - import-order defensive
            return []

    def _work_pending(self, table: List[Dict[str, Any]]) -> List[str]:
        """Evidence something SHOULD be making progress: live solver
        gauges (a fit mid-loop), queued serving requests, held or
        awaited named locks, in-flight cross-process reduce waits.
        Returns the evidence labels (empty = the process is
        legitimately idle)."""
        evidence: List[str] = []
        m = REGISTRY.get("solver_iteration")
        if m is not None and m.samples():
            evidence.append("live_solver_gauges")
        q = REGISTRY.get("serving_queue_depth")
        if q is not None and any(
            isinstance(v, (int, float)) and v > 0
            for v in q.samples().values()
        ):
            evidence.append("queued_serving_requests")
        if any(r.get("holder") or r.get("waiters") for r in table):
            evidence.append("held_locks")
        if self._reduce_waits():
            evidence.append("reduce_wait")
        return evidence

    # -- the tick ------------------------------------------------------------

    def tick(self) -> Optional[str]:
        """One watchdog evaluation (the daemon calls this every poll;
        tests call it directly).  Returns the bundle directory when a
        stall was diagnosed and dumped, else None."""
        TICKS.inc()
        locks.publish_lock_metrics()
        enabled, stall_s = self._conf()
        if not enabled:
            return None
        now = time.monotonic()
        fp = self._fingerprint()
        if fp != self._last_fp:
            self._last_fp = fp
            self._last_progress = now
        table = locks.lock_table()
        stuck = [
            (row, w)
            for row in table
            for w in row.get("waiters", ())
            if w.get("waited_s", 0.0) >= stall_s
        ]
        reduce_stuck = [
            w
            for w in self._reduce_waits()
            if w.get("waited_s", 0.0) >= stall_s
        ]
        kind = None
        episode: Any = None
        if stuck:
            kind = "lock_wait"
            episode = (
                "lock_wait",
                frozenset(
                    (row["name"], w["thread_id"]) for row, w in stuck
                ),
            )
        elif reduce_stuck:
            # a thread parked in a cross-process wait past the stall
            # window: name the blocked reduce tag and peer rank — the
            # pod-scale analog of the lock_wait diagnosis.  kv_wait
            # itself bounds the wait (ReduceTimeout at the deadline);
            # the doctor's job is ATTRIBUTION while it is still stuck
            kind = "reduce_wait"
            episode = (
                "reduce_wait",
                frozenset(
                    (w["tag"], w["thread_id"]) for w in reduce_stuck
                ),
            )
        else:
            pending = self._work_pending(table)
            if pending and (now - self._last_progress) >= stall_s:
                kind = "no_progress"
                episode = ("no_progress", fp)
        if kind is None:
            self._dumped_episode = None  # healthy tick re-arms
            return None
        if self._dumped_episode == episode:
            return None  # same episode, already diagnosed
        self._dumped_episode = episode
        STALLS.inc(kind=kind)
        return self._diagnose(kind, stall_s, table, stuck, reduce_stuck)

    def _diagnose(
        self,
        kind: str,
        stall_s: float,
        table: List[Dict[str, Any]],
        stuck: List[tuple],
        reduce_stuck: Optional[List[Dict[str, Any]]] = None,
    ) -> Optional[str]:
        from .flight_recorder import note_failure

        reduce_stuck = reduce_stuck or []
        edges = build_wait_graph(table)
        cycles = find_cycles(edges)
        if cycles:
            detail = "deadlock: " + "; ".join(
                describe_cycle(c) for c in cycles
            )
        elif kind == "reduce_wait" and reduce_stuck:
            worst = max(reduce_stuck, key=lambda w: w.get("waited_s", 0.0))
            peer = worst.get("peer")
            detail = (
                f"thread {worst['thread']} has waited "
                f"{worst.get('waited_s', 0.0):.1f}s in cross-process "
                f"reduce {worst['tag']!r}"
                + (f" on rank {peer}" if peer is not None else "")
            )
        elif stuck:
            worst_row, worst_w = max(
                stuck, key=lambda rw: rw[1].get("waited_s", 0.0)
            )
            holder = worst_row.get("holder") or {}
            detail = (
                f"thread {worst_w['thread']} has waited "
                f"{worst_w.get('waited_s', 0.0):.1f}s for lock "
                f"{worst_row['name']!r}"
                + (
                    f" held by {holder.get('thread')} for "
                    f"{holder.get('held_s', 0.0):.1f}s"
                    if holder
                    else ""
                )
            )
        else:
            detail = (
                f"no forward progress for {stall_s:.0f}s with work "
                "in flight"
            )
        waitfor = {
            "kind": kind,
            "stall_s": stall_s,
            "edges": edges,
            "reduce_waits": [
                {k: v for k, v in w.items() if k != "since"}
                for w in reduce_stuck
            ],
            "cycles": [
                {
                    "threads": [e["waiter"] for e in c],
                    "locks": [e["lock"] for e in c],
                    "description": describe_cycle(c),
                }
                for c in cycles
            ],
        }
        stacks = all_thread_stacks()

        # The dump path takes the flight recorder's lock and writes
        # files — in a badly wedged process THOSE can hang too, and the
        # watchdog must never die of its patient.  Dump on a short-lived
        # side thread with a join timeout; if even the dump wedges, the
        # diagnosis still escapes via stderr (the same channel the
        # WEDGE_GUARD faulthandler backstop uses).
        result: Dict[str, Any] = {}

        def _dump() -> None:
            result["bdir"] = note_failure(
                "stall",
                detail=detail,
                attachments={
                    # bytes write verbatim; dicts land as `<key>.json`
                    "stacks.txt": stacks.encode(),
                    "waitfor": waitfor,
                    "locks": table,
                },
            )

        t = threading.Thread(
            target=_dump, name="hang-doctor-dump", daemon=True
        )
        t.start()
        t.join(timeout=_DUMP_JOIN_S)
        if t.is_alive():
            sys.stderr.write(
                f"hang doctor: stall diagnosed ({detail}) but the "
                "flight-recorder dump itself wedged; stacks follow\n"
                + stacks + "\n"
            )
            return None
        return result.get("bdir")

    # -- the daemon ----------------------------------------------------------

    def _loop(self) -> None:
        while True:
            enabled, stall_s = self._conf()
            if not enabled:
                time.sleep(_DISABLED_POLL_S)
                continue
            try:
                self.tick()
            except Exception:  # the watchdog must never die of its patient
                pass
            time.sleep(
                min(_MAX_POLL_S, max(_MIN_POLL_S, stall_s / 4.0))
            )


# the process-global doctor every trace event feeds
DOCTOR = HangDoctor()

_installed = False


def install() -> HangDoctor:
    """Hook the doctor onto the tracing tap (idempotent; called at
    telemetry import, like the flight recorder).  The watchdog thread
    itself spawns lazily on the first recorded event, so merely
    importing the package starts no threads."""
    global _installed
    with DOCTOR._mu:
        if not _installed:
            from ..tracing import add_trace_tap

            add_trace_tap(DOCTOR.on_event)
            _installed = True
    return DOCTOR


__all__ = [
    "DOCTOR",
    "HangDoctor",
    "all_thread_stacks",
    "build_wait_graph",
    "describe_cycle",
    "find_cycles",
    "install",
]
