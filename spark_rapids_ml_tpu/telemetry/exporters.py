#
# Telemetry exporters — the two formats production tooling already
# understands:
#
#   Chrome trace-event JSON   the recorded spans (tracing.py) as complete
#                             events, one track per thread, plus an
#                             instant-event track for the resilience
#                             markers (retries, injected faults, elastic
#                             recoveries, checkpoint resumes).  Loads
#                             directly in Perfetto (ui.perfetto.dev) or
#                             chrome://tracing.
#   Prometheus text format    every registry metric (counters, gauges —
#                             including the legacy dict views — and
#                             histograms) as `spark_rapids_ml_tpu_*`
#                             families.  `dump_prometheus()` renders the
#                             page; `start_http_server` serves it from a
#                             stdlib http endpoint gated by the
#                             `telemetry_port` conf (opt-in: 0 = off).
#
# A minimal text-format parser (`parse_prometheus`) rides along so tests
# and the CI smoke can round-trip the dump without a prometheus client
# dependency.
#
from __future__ import annotations

import json
import os
import re
import threading

from .locks import named_lock
from typing import Any, Dict, List, Optional, Tuple

# one label pair inside a sample's {...} body; values are quoted with
# \\ / \" / \n escapes per the exposition format
_RE_LABEL = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')
_RE_ESCAPE = re.compile(r"\\(.)")
# OpenMetrics-style exemplar suffix (` # {labels} value timestamp`,
# end-anchored so an adversarial LABEL VALUE merely containing the
# shape cannot truncate a sample — inside a label its quotes are
# escaped, so the label-pair body below cannot match and the real
# sample value stays in place).  ANY exemplar labelset is recognized —
# our own dump writes `request_id=`, but foreign pages (federation
# output, other exporters) ship `trace_id=`-style exemplars and those
# must strip cleanly too, never leak into the sample value/labels.
_EXEMPLAR_BODY = r'(?:\w+="(?:[^"\\]|\\.)*"(?:,\w+="(?:[^"\\]|\\.)*")*)?'
_RE_EXEMPLAR = re.compile(
    r' # \{' + _EXEMPLAR_BODY + r'\} \S+ \S+$'
)
# capturing twin: the family-level parser keeps the exemplar (labels,
# value, timestamp) so merged fleet pages preserve request-id forensics
_RE_EXEMPLAR_CAP = re.compile(
    r' # \{(' + _EXEMPLAR_BODY + r')\} (\S+) (\S+)$'
)


def _unescape_one(m: "re.Match") -> str:
    c = m.group(1)
    return "\n" if c == "n" else c


def _parse_value(s: str):
    """Sample value as the exact number the dump wrote: integers stay
    int (counter sums across processes must be exact), everything else
    float."""
    try:
        return int(s)
    except ValueError:
        return float(s)

from .registry import REGISTRY, MetricsRegistry

# every exported family carries the library prefix so a shared scrape
# endpoint can't collide with the host application's metrics
PROM_PREFIX = "spark_rapids_ml_tpu_"

# synthetic Chrome-trace thread id for the instant-marker track: real
# thread ids are pthread handles and never reach this reserved value
MARKER_TID = 2**31 - 1


# ---------------------------------------------------------------------------
# Chrome trace events (Perfetto / chrome://tracing)
# ---------------------------------------------------------------------------


def chrome_trace(
    events: Optional[list] = None, run_id: Optional[str] = None
) -> Dict[str, Any]:
    """The recorded trace spans as a Chrome trace-event JSON object
    (`{"traceEvents": [...]}`).  `events` defaults to every thread's
    buffer (tracing.get_all_trace_events); `run_id` filters to one
    fit/transform run.  Spans become complete ("X") events on their
    recording thread's track; instant events (kind="instant") land on a
    dedicated "resilience markers" track so retries/recoveries stay
    visible at any zoom level.  Timestamps are absolute epoch
    microseconds, so traces from concurrent processes align."""
    from ..tracing import get_all_trace_events

    evs = events if events is not None else get_all_trace_events(run_id)
    if events is not None and run_id is not None:
        evs = [e for e in evs if e.run_id == run_id]
    pid = os.getpid()
    out: List[Dict[str, Any]] = []
    tids = {}
    for e in evs:
        args: Dict[str, Any] = {}
        if e.detail:
            args["detail"] = e.detail
        if e.run_id:
            args["run_id"] = e.run_id
        # the pod-global pass id (telemetry/fleet.py): the join key a
        # merged pod trace correlates cross-rank spans on
        if getattr(e, "pass_id", ""):
            args["pass_id"] = e.pass_id
        if getattr(e, "kind", "span") == "instant":
            out.append(
                {
                    "name": e.name,
                    "ph": "i",
                    "s": "p",  # process-scoped marker line
                    "ts": e.t0 * 1e6,
                    "pid": pid,
                    "tid": MARKER_TID,
                    "args": args,
                }
            )
        else:
            tids.setdefault(e.thread_id, None)
            out.append(
                {
                    "name": e.name,
                    "ph": "X",
                    "ts": e.t0 * 1e6,
                    "dur": max(e.seconds, 0.0) * 1e6,
                    "pid": pid,
                    "tid": e.thread_id,
                    "args": args,
                }
            )
    # track names: one per recording thread + the marker track
    meta = [
        {
            "name": "thread_name",
            "ph": "M",
            "pid": pid,
            "tid": MARKER_TID,
            "args": {"name": "resilience markers"},
        }
    ]
    for i, tid in enumerate(sorted(tids)):
        meta.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": f"thread-{i}" if i else "controller"},
            }
        )
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


def dump_chrome_trace(
    path: Optional[str] = None,
    events: Optional[list] = None,
    run_id: Optional[str] = None,
) -> str:
    """`chrome_trace` as a JSON string; also written to `path` when
    given (atomic tmp + replace, so a concurrent Perfetto load never
    sees a torn file)."""
    payload = json.dumps(chrome_trace(events, run_id))
    if path:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, path)
    return payload


# ---------------------------------------------------------------------------
# Prometheus text format
# ---------------------------------------------------------------------------


def _fmt_value(v: Any) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def _escape_label(v: str) -> str:
    """Prometheus exposition-format label escaping: backslash, quote,
    newline.  Without it a label value carrying a quote/comma breaks
    every consumer of the page (including our own parser)."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_labels(pairs: Tuple[Tuple[str, str], ...], extra: str = "") -> str:
    items = [f'{k}="{_escape_label(v)}"' for k, v in pairs]
    if extra:
        items.append(extra)
    return "{" + ",".join(items) + "}" if items else ""


def dump_prometheus(
    registry: Optional[MetricsRegistry] = None, exemplars: bool = False
) -> str:
    """Every registry metric in the Prometheus exposition text format
    (`# HELP` / `# TYPE` headers, `_bucket`/`_sum`/`_count` histogram
    series).  The legacy dict views (STAGE_COUNTS, CACHE_METRICS,
    RECOVERY_METRICS, ...) export as gauge families labeled by `key`, so
    `spark_rapids_ml_tpu_recovery{key="meshes_rebuilt"}` always equals
    `RECOVERY_METRICS["meshes_rebuilt"]`.

    `exemplars=True` appends each histogram labelset's recorded request
    ids to their `_bucket` lines in the OpenMetrics exemplar shape
    (` # {request_id="..."} value timestamp`) — opt-in because classic
    0.0.4 scrapers reject the syntax; `parse_prometheus` strips it
    either way.  The flight recorder's post-mortem bundles dump with
    exemplars on, so a latency bucket in the black box names the
    requests that landed in it."""
    reg = registry or REGISTRY
    if reg is REGISTRY:
        # fold the named locks' pending accounting into the lock_*
        # counter families first, so every scrape sees current numbers
        # (publication is deferred off the acquire hot path by design)
        from .locks import publish_lock_metrics

        publish_lock_metrics()
    lines: List[str] = []
    for m in reg.metrics():
        name = PROM_PREFIX + m.name
        if m.help:
            lines.append(f"# HELP {name} {m.help}")
        lines.append(f"# TYPE {name} {m.kind}")
        samples = m.samples()
        if m.kind == "histogram":
            for lk, h in samples.items():
                ex_by_bucket: Dict[int, Dict[str, Any]] = {}
                if exemplars:
                    for e in h.get("exemplars", ()):
                        for i, le in enumerate(m.buckets):
                            if e["value"] <= le:
                                ex_by_bucket[i] = e  # newest wins
                                break
                        else:
                            ex_by_bucket[len(m.buckets)] = e
                for i, (le, c) in enumerate(zip(m.buckets, h["buckets"])):
                    extra = 'le="%s"' % le
                    suffix = _fmt_exemplar(ex_by_bucket.get(i))
                    lines.append(
                        f"{name}_bucket{_fmt_labels(lk, extra)} {c}{suffix}"
                    )
                inf = 'le="+Inf"'
                suffix = _fmt_exemplar(ex_by_bucket.get(len(m.buckets)))
                lines.append(
                    f"{name}_bucket{_fmt_labels(lk, inf)} "
                    f"{h['count']}{suffix}"
                )
                lines.append(f"{name}_sum{_fmt_labels(lk)} "
                             f"{_fmt_value(h['sum'])}")
                lines.append(f"{name}_count{_fmt_labels(lk)} {h['count']}")
        else:
            for lk, v in samples.items():
                lines.append(f"{name}{_fmt_labels(lk)} {_fmt_value(v)}")
    return "\n".join(lines) + "\n"


def _fmt_exemplar(e: Optional[Dict[str, Any]]) -> str:
    if not e:
        return ""
    return (
        f' # {{request_id="{_escape_label(e["id"])}"}} '
        f"{_fmt_value(e['value'])} {round(e['t'], 3)}"
    )


def _parse_sample_line(
    line: str,
) -> Tuple[str, Tuple[Tuple[str, str], ...], str]:
    """One sample line -> (name, sorted label pairs, raw value string).
    Strips an OpenMetrics exemplar suffix when present, and tolerates
    the exposition format's OPTIONAL trailing timestamp (foreign pages
    — federation output, other exporters — emit `name{l} value ts`; the
    timestamp is dropped, never mistaken for the value).  Raises
    ValueError on malformed lines so a broken dump fails loudly."""
    line = _RE_EXEMPLAR.sub("", line)
    head, _, value = line.rpartition(" ")
    if not head:
        raise ValueError(f"malformed prometheus sample: {line!r}")
    if " " in head and (
        ("}" in head and not head.endswith("}")) or "{" not in head
    ):
        # the token we took as the value is a trailing timestamp: the
        # real value is the token before it (a head that still has a
        # space after its label block — or a label-less head with a
        # space — cannot be a bare metric name)
        head, _, value = head.rpartition(" ")
    labels: Tuple[Tuple[str, str], ...] = ()
    name = head
    if head.endswith("}"):
        name, _, rest = head.partition("{")
        body = rest[:-1]
        # escape-aware: values may contain \\, \" and \n (and
        # commas, which a naive split would sever)
        pairs = [
            (k, _RE_ESCAPE.sub(_unescape_one, v))
            for k, v in _RE_LABEL.findall(body)
        ]
        if body and not pairs:
            raise ValueError(f"malformed label in: {line!r}")
        labels = tuple(sorted(pairs))
    return name, labels, value


def parse_prometheus(
    text: str,
) -> Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float]:
    """Minimal text-format parser: `{(name, ((label, value), ...)): v}`.
    Enough to round-trip `dump_prometheus` in tests/CI without a
    prometheus client library; raises ValueError on malformed sample
    lines so a broken dump fails loudly."""
    out: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, labels, value = _parse_sample_line(line)
        out[(name, labels)] = float(value)
    return out


def parse_prometheus_families(text: str) -> Dict[str, Dict[str, Any]]:
    """Structured family-level parse — the exact round-trip the
    cross-process aggregator (telemetry/aggregate.py) stands on:

        {family: {"kind": counter|gauge|histogram|untyped,
                  "help": str,
                  "samples": {label_pairs: value}}}

    Histogram families reassemble their `_bucket`/`_sum`/`_count` series
    back into one value per labelset —
    `{"buckets": {le_str: count}, "sum": float, "count": int}` — keyed
    WITHOUT the `le` label, so bucket-wise merging is a dict walk.
    Escaped label values (backslash, quote, newline — and commas/spaces/
    braces, which need no escape but break naive splitters) round-trip
    byte-exactly; integer sample values stay `int` so counter sums
    across processes are exact.  OpenMetrics exemplars on `_bucket`
    lines are KEPT (`{"exemplars": [{"id", "value", "t"}, ...]}` beside
    the histogram sample, oldest first) so a fleet merge
    (telemetry/aggregate.py) preserves the request-id forensics instead
    of silently dropping them.  `render_families` is the inverse."""
    kinds: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    raw: Dict[str, Dict[Tuple[Tuple[str, str], ...], Any]] = {}
    exemplars_raw: Dict[
        Tuple[str, Tuple[Tuple[str, str], ...]], List[Dict[str, Any]]
    ] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ", 3)
            if len(parts) == 4:
                kinds[parts[2]] = parts[3]
            continue
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            if len(parts) >= 3:
                helps[parts[2]] = parts[3] if len(parts) == 4 else ""
            continue
        if line.startswith("#"):
            continue
        ex = _RE_EXEMPLAR_CAP.search(line)
        name, labels, value = _parse_sample_line(line)
        raw.setdefault(name, {})[labels] = _parse_value(value)
        if ex is not None and name.endswith("_bucket"):
            # keep only request_id exemplars (the shape our dump writes
            # and render_families re-emits); foreign exemplar labelsets
            # were stripped from the sample above and are dropped here
            ex_labels = dict(_RE_LABEL.findall(ex.group(1)))
            rid = ex_labels.get("request_id")
            if rid is not None:
                base = tuple(p for p in labels if p[0] != "le")
                exemplars_raw.setdefault(
                    (name[:-len("_bucket")], base), []
                ).append({
                    "id": _RE_ESCAPE.sub(_unescape_one, rid),
                    "value": float(ex.group(2)),
                    "t": float(ex.group(3)),
                })
    out: Dict[str, Dict[str, Any]] = {}
    for fam, kind in kinds.items():
        entry: Dict[str, Any] = {"kind": kind, "help": helps.get(fam, "")}
        if kind == "histogram":
            samples: Dict[Tuple[Tuple[str, str], ...], Any] = {}
            for lk, v in raw.pop(fam + "_bucket", {}).items():
                le = dict(lk).get("le", "")
                base = tuple(p for p in lk if p[0] != "le")
                h = samples.setdefault(
                    base, {"buckets": {}, "sum": 0.0, "count": 0}
                )
                h["buckets"][le] = v
                exs = exemplars_raw.get((fam, base))
                if exs and "exemplars" not in h:
                    h["exemplars"] = sorted(exs, key=lambda e: e["t"])
            for lk, v in raw.pop(fam + "_sum", {}).items():
                samples.setdefault(
                    lk, {"buckets": {}, "sum": 0.0, "count": 0}
                )["sum"] = float(v)
            for lk, v in raw.pop(fam + "_count", {}).items():
                samples.setdefault(
                    lk, {"buckets": {}, "sum": 0.0, "count": 0}
                )["count"] = int(v)
            entry["samples"] = samples
        else:
            entry["samples"] = raw.pop(fam, {})
        out[fam] = entry
    # samples with no TYPE header (foreign pages): keep them, untyped
    for fam, samples in raw.items():
        out[fam] = {"kind": "untyped", "help": "", "samples": samples}
    return out


def render_families(families: Dict[str, Dict[str, Any]]) -> str:
    """`parse_prometheus_families`'s inverse: families back to the text
    exposition format (deterministic ordering: families as given,
    labelsets sorted), so merged pages are themselves scrapeable and
    re-parseable."""
    lines: List[str] = []
    for fam, entry in families.items():
        if entry.get("help"):
            lines.append(f"# HELP {fam} {entry['help']}")
        kind = entry.get("kind", "untyped")
        if kind != "untyped":
            lines.append(f"# TYPE {fam} {kind}")
        samples = entry.get("samples", {})
        if kind == "histogram":
            for lk in sorted(samples):
                h = samples[lk]
                les = sorted(
                    h["buckets"],
                    key=lambda s: float("inf") if s == "+Inf" else float(s),
                )
                # re-attach retained exemplars to their bucket lines
                # (newest per bucket wins, the dump_prometheus shape) so
                # merged pages keep the request-id forensics and still
                # re-parse through this module
                ex_by_le: Dict[str, Dict[str, Any]] = {}
                for e in h.get("exemplars", ()):
                    for le in les:
                        le_f = float("inf") if le == "+Inf" else float(le)
                        if e["value"] <= le_f:
                            ex_by_le[le] = e
                            break
                for le in les:
                    extra = f'le="{le}"'
                    suffix = _fmt_exemplar(ex_by_le.get(le))
                    lines.append(
                        f"{fam}_bucket{_fmt_labels(lk, extra)} "
                        f"{_fmt_value(h['buckets'][le])}{suffix}"
                    )
                lines.append(
                    f"{fam}_sum{_fmt_labels(lk)} {_fmt_value(h['sum'])}"
                )
                lines.append(
                    f"{fam}_count{_fmt_labels(lk)} {_fmt_value(h['count'])}"
                )
        else:
            for lk in sorted(samples):
                lines.append(
                    f"{fam}{_fmt_labels(lk)} {_fmt_value(samples[lk])}"
                )
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Opt-in stdlib HTTP endpoint (`telemetry_port` conf)
# ---------------------------------------------------------------------------

_server_lock = named_lock("telemetry_http")
_server = None


def start_http_server(
    port: int,
    registry: Optional[MetricsRegistry] = None,
    host: str = "127.0.0.1",
):
    """Serve `/metrics` (Prometheus text format) from a daemon-thread
    stdlib HTTP server on `port` (0 = ephemeral; read the bound port off
    the returned server's `.server_port`).  One server per process —
    repeat calls return the running one.  Binds LOOPBACK by default:
    the dump names datasets, staging sizes and failure activity, which
    must not leak to every network peer of a multi-tenant host — pass
    `host="0.0.0.0"` deliberately for a cluster-scraped deployment."""
    global _server
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    with _server_lock:
        if _server is not None:
            return _server
        reg = registry or REGISTRY

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - stdlib handler contract
                if self.path.split("?", 1)[0] not in ("/metrics", "/"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = dump_prometheus(reg).encode()
                self.send_response(200)
                # the full exposition-format content type: scrapers key
                # the parser off version AND charset (a bare text/plain
                # makes strict clients fall back to guessing)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8",
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes must not spam stderr
                pass

        srv = ThreadingHTTPServer((host, int(port)), _Handler)
        srv.daemon_threads = True
        t = threading.Thread(
            target=srv.serve_forever, name="telemetry-http", daemon=True
        )
        t.start()
        _server = srv
        from ..utils import get_logger

        get_logger("spark_rapids_ml_tpu.telemetry").info(
            f"telemetry endpoint: http://{host}:{srv.server_port}/metrics"
        )
        return srv


def stop_http_server() -> None:
    """Shut the endpoint down (tests; operator teardown).  Idempotent."""
    global _server
    with _server_lock:
        if _server is not None:
            _server.shutdown()
            _server.server_close()
            _server = None


def maybe_start_http_server():
    """Start the endpoint iff the `telemetry_port` conf is set (> 0) and
    no server is running yet — the cheap per-fit hook core.py calls.
    Never raises: an occupied port logs a warning instead of failing the
    fit it was meant to observe."""
    from ..config import get_config

    port = int(get_config("telemetry_port") or 0)
    if port <= 0 or _server is not None:
        return _server
    try:
        return start_http_server(port)
    except OSError as e:
        from ..utils import get_logger

        get_logger("spark_rapids_ml_tpu.telemetry").warning(
            f"telemetry endpoint on port {port} failed to start ({e}); "
            "metrics stay available via dump_prometheus()"
        )
        return None


__all__ = [
    "MARKER_TID",
    "PROM_PREFIX",
    "chrome_trace",
    "dump_chrome_trace",
    "dump_prometheus",
    "maybe_start_http_server",
    "parse_prometheus",
    "parse_prometheus_families",
    "render_families",
    "start_http_server",
    "stop_http_server",
]
