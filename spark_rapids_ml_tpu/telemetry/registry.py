#
# Typed process-global metrics registry — the single surface that absorbs
# the metric dicts four PRs grew independently (`mesh.STAGE_METRICS` /
# `STAGE_COUNTS`, `device_cache.CACHE_METRICS`,
# `elastic.RECOVERY_METRICS`).  Three metric kinds with label support:
#
#   Counter    monotonically increasing (retries, faults injected,
#              checkpoint saves) — `inc(amount, **labels)`
#   Gauge      settable point-in-time value (resident bytes, solver
#              iteration) — `set(value, **labels)` / `inc`/`dec`
#   Histogram  bucketed observations (fit wall seconds) —
#              `observe(value, **labels)`
#
# Values are stored as exact Python numbers (int stays int), so the
# legacy dict views (`dict_view`) preserve the arithmetic the old
# module-level dicts had.  `snapshot()` returns a plain nested dict for
# delta computation (per-fit reports, bench sections); `reset()` zeroes
# every sample but keeps registrations (and re-seeds view initials).
# The Prometheus text rendering lives in exporters.py (`dump_prometheus`).
#
# Deliberately dependency-free (no jax/numpy at module scope): bumping a
# counter from the resilience layer must never pay an accelerator import.
#
from __future__ import annotations

import threading
import time
from collections.abc import MutableMapping
from typing import Any, Dict, Iterator, List, Optional, Tuple

LabelKey = Tuple[Tuple[str, str], ...]

# ---------------------------------------------------------------------------
# Canonical metric catalog.  Every metric family the package registers
# MUST be declared here: name -> {kind, labels, cardinality}.  The
# graft-lint `metric-name` rule (spark_rapids_ml_tpu/analysis/)
# cross-checks every registration call and every `.inc/.set/.observe`
# label set against this table, so a counter minted ad hoc in some
# module — or a label set that drifts from the registration — fails CI
# instead of silently forking the Prometheus surface.  `cardinality`
# bounds the DISTINCT labelsets a family may accumulate at runtime
# (`check_cardinality()`, asserted by the jit-audit sanitizer job and
# tests): labels must stay enumerable — site names, estimator names,
# device ordinals — never run ids or timestamps.
#
# Kinds: counter / gauge / histogram, plus "view" — a gauge family
# fronted by a legacy `dict_view` mapping (labeled only by `key`).
# ---------------------------------------------------------------------------
METRIC_CATALOG: Dict[str, Dict[str, Any]] = {
    # resilience
    "retries_total": {
        "kind": "counter", "labels": ("label", "action"), "cardinality": 64,
    },
    "dispatch_timeouts_total": {
        "kind": "counter", "labels": ("label",), "cardinality": 32,
    },
    "faults_injected_total": {
        "kind": "counter", "labels": ("site", "kind"), "cardinality": 64,
    },
    "checkpoint_saves_total": {
        "kind": "counter", "labels": (), "cardinality": 1,
    },
    "checkpoint_resumes_total": {
        "kind": "counter", "labels": (), "cardinality": 1,
    },
    "device_health_probes_total": {
        "kind": "counter", "labels": (), "cardinality": 1,
    },
    "device_probe_failures_total": {
        "kind": "counter", "labels": (), "cardinality": 1,
    },
    # telemetry: memory / budget drift
    "device_bytes_in_use": {
        "kind": "gauge", "labels": ("device",), "cardinality": 256,
    },
    "device_bytes_peak": {
        "kind": "gauge", "labels": ("device",), "cardinality": 256,
    },
    "budget_drift_ratio": {
        "kind": "gauge", "labels": ("est",), "cardinality": 64,
    },
    "budget_predicted_bytes": {
        "kind": "gauge", "labels": ("est",), "cardinality": 64,
    },
    "budget_decisions_total": {
        "kind": "counter", "labels": ("label", "over"), "cardinality": 64,
    },
    "memory_samples_total": {
        "kind": "counter", "labels": ("provider",), "cardinality": 4,
    },
    # telemetry: compile tracking
    "compile_seconds": {
        "kind": "histogram", "labels": ("fn", "phase"), "cardinality": 256,
    },
    "compiles_total": {
        "kind": "counter", "labels": ("fn",), "cardinality": 64,
    },
    "recompiles_total": {
        "kind": "counter", "labels": ("fn", "reason"), "cardinality": 64,
    },
    # telemetry: solver progress / fit accounting
    "solver_iteration": {
        "kind": "gauge", "labels": ("solver",), "cardinality": 16,
    },
    "solver_loss": {
        "kind": "gauge", "labels": ("solver",), "cardinality": 16,
    },
    "fit_duration_seconds": {
        "kind": "histogram", "labels": ("estimator",), "cardinality": 32,
    },
    # serving layer (serving/): request latency split by phase, batch
    # coalescing sizes, admission-control rejections, and model-pin
    # lifecycle.  Labels stay enumerable: model names are
    # operator-chosen registry keys, phases/reasons/events are fixed
    # vocabularies.  `exemplars: True` declares the family carries
    # bounded per-labelset exemplars (request ids) — the ONLY families
    # allowed to pass `exemplar=` to observe() (metric-name rule); the
    # unbounded ids live beside the samples, never as labels.
    "serving_request_latency_seconds": {
        "kind": "histogram", "labels": ("model", "phase"),
        "cardinality": 96, "exemplars": True,
    },
    # SLO sensing (serving/server.py): measured over-p99-target request
    # fraction / the 1% budget a p99 target implies, per declared
    # window — the sensor half of the planned coalescing-cap feedback
    # controller (ROADMAP item 2).
    "slo_burn_rate": {
        "kind": "gauge", "labels": ("model", "window"), "cardinality": 96,
    },
    # failure flight recorder (telemetry/flight_recorder.py): one bump
    # per post-mortem bundle written, labeled by the typed failure path
    # that triggered the dump (retry_exhausted / dispatch_timeout /
    # device_lost / serving_overload / brownout / drift / manual)
    "postmortems_total": {
        "kind": "counter", "labels": ("reason",), "cardinality": 16,
    },
    "serving_batch_rows": {
        "kind": "histogram", "labels": ("model",), "cardinality": 32,
    },
    "serving_requests_total": {
        "kind": "counter", "labels": ("model",), "cardinality": 32,
    },
    "serving_rejections_total": {
        "kind": "counter", "labels": ("model", "reason"), "cardinality": 64,
    },
    "serving_pins_total": {
        "kind": "counter", "labels": ("model", "event"), "cardinality": 96,
    },
    "serving_pinned_models": {
        "kind": "gauge", "labels": (), "cardinality": 1,
    },
    "serving_pinned_bytes": {
        "kind": "gauge", "labels": (), "cardinality": 1,
    },
    # legacy dict-view families (gauges labeled by `key`)
    "staging_last": {"kind": "view", "labels": ("key",), "cardinality": 32},
    "staging_counts": {"kind": "view", "labels": ("key",), "cardinality": 32},
    "device_cache": {"kind": "view", "labels": ("key",), "cardinality": 32},
    # chunk cache (parallel/device_cache.py ChunkCache): hit/miss/spill/
    # restore/evict/invalidate counters + per-tier byte gauges for the
    # out-of-core epoch engine's decoded-chunk tiers
    "chunk_cache": {"kind": "view", "labels": ("key",), "cardinality": 32},
    "recovery": {"kind": "view", "labels": ("key",), "cardinality": 16},
    # pod rank-loss recovery (resilience/pod.py): losses detected,
    # shares reassigned, recoveries, bounded-wait expiries, generation
    "pod_recovery": {"kind": "view", "labels": ("key",), "cardinality": 16},
    "fused_last": {"kind": "view", "labels": ("key",), "cardinality": 32},
    "pca_solver_last": {"kind": "view", "labels": ("key",), "cardinality": 16},
    # statistic-program engine (stats/engine.py): executions per
    # registered program, wall seconds per fused multi-program pass
    # (labeled by the run's caller-facing label — summarize / describe /
    # estimator names, a fixed vocabulary), and the last-run state the
    # fit report's `stats` section and bench.py's `summarize` section
    # copy
    "stat_program_runs_total": {
        "kind": "counter", "labels": ("program",), "cardinality": 64,
    },
    "stat_program_pass_seconds": {
        "kind": "histogram", "labels": ("label",), "cardinality": 32,
    },
    "stat_program_last": {
        "kind": "view", "labels": ("key",), "cardinality": 32,
    },
    # drift monitor (monitor/): per-model divergence gauges, bounded to
    # the `drift_top_k` highest-scoring columns per model (stale column
    # series are REMOVED on every refresh — monitor._export), plus the
    # per-model `_overall` alert series and per-output-column scores;
    # `column` is therefore enumerable by construction, never a raw
    # feature index stream.  512 covers ~8 models x (8 columns x 7
    # stats + outputs + overall).
    "drift_score": {
        "kind": "gauge", "labels": ("model", "column", "stat"),
        "cardinality": 512,
    },
    "drift_rows_observed_total": {
        "kind": "counter", "labels": ("model",), "cardinality": 32,
    },
    # named-lock contention profiling (telemetry/locks.py): per-lock
    # acquire / contended / wait-seconds / hold-seconds counters,
    # published from the per-instance accounting by
    # `publish_lock_metrics` (exporters, fit reports, hang-doctor
    # ticks).  `lock` label values come from LOCK_CATALOG — a fixed
    # vocabulary the graft-lint `named-lock` rule enforces.
    "lock_acquisitions_total": {
        "kind": "counter", "labels": ("lock",), "cardinality": 64,
    },
    "lock_contended_total": {
        "kind": "counter", "labels": ("lock",), "cardinality": 64,
    },
    "lock_wait_seconds_total": {
        "kind": "counter", "labels": ("lock",), "cardinality": 64,
    },
    "lock_hold_seconds_total": {
        "kind": "counter", "labels": ("lock",), "cardinality": 64,
    },
    # utilization timeline (telemetry/utilization.py): fraction of the
    # observed wall the device was busy, per scope (fit | serving)
    "device_busy_fraction": {
        "kind": "gauge", "labels": ("scope",), "cardinality": 8,
    },
    # hang doctor (telemetry/hang_doctor.py): watchdog liveness + stall
    # episodes by kind (lock_wait | no_progress); the dumped bundles
    # themselves count on postmortems_total{reason="stall"}
    "hang_doctor_ticks_total": {
        "kind": "counter", "labels": (), "cardinality": 1,
    },
    "hang_doctor_stalls_total": {
        "kind": "counter", "labels": ("kind",), "cardinality": 8,
    },
    # serving queue sensors (serving/server.py): live queued rows per
    # model and the dispatcher's loop lag (how far past its intended
    # wake deadline the loop ran) — the queueing half of ROADMAP item
    # 2's feedback controller, next to `slo_burn_rate`
    "serving_queue_depth": {
        "kind": "gauge", "labels": ("model",), "cardinality": 32,
    },
    "serving_dispatcher_lag_seconds": {
        "kind": "gauge", "labels": (), "cardinality": 1,
    },
    # staged dispatch pipeline (serving/server.py): the resolved
    # in-flight depth (explicit conf or the auto value derived from the
    # serving idle-gap profile) and the live batch occupancy across the
    # stage/compute/collect/scatter stages — occupancy pinned at depth
    # means the pipeline is full and depth is the throughput limiter
    "serving_pipeline_depth": {
        "kind": "gauge", "labels": (), "cardinality": 1,
    },
    "serving_pipeline_inflight": {
        "kind": "gauge", "labels": (), "cardinality": 1,
    },
    # serving control plane (serving/control.py, ROADMAP item 2's
    # actuator half): the AIMD controller's live actuator values per
    # model (the EFFECTIVE coalescing cap / max-wait after scaling),
    # its adjustment counter by direction (increase | decrease), the
    # brownout phase index (0 normal, 1 shed_batch, 2 shed_interactive),
    # and brownout sheds by priority class (interactive | batch)
    "serving_controller_cap": {
        "kind": "gauge", "labels": ("model",), "cardinality": 32,
    },
    "serving_controller_max_wait_ms": {
        "kind": "gauge", "labels": ("model",), "cardinality": 32,
    },
    "serving_controller_adjustments_total": {
        "kind": "counter", "labels": ("model", "direction"),
        "cardinality": 64,
    },
    "serving_controller_brownout_phase": {
        "kind": "gauge", "labels": ("model",), "cardinality": 32,
    },
    "serving_shed_total": {
        "kind": "counter", "labels": ("model", "class"), "cardinality": 64,
    },
    # multi-host data path (parallel/context.py): wall time of each
    # cross-process reduction step by phase — `agreement` (the content-
    # fingerprint check), `psum` (jitted collective fold), `wire`
    # (coordination-service allgather + rank-order host fold), `sketch`
    # (host-tier sketch wire merges), `fingerprint` (drift-baseline
    # builder merges)
    "multiproc_reduce_seconds": {
        "kind": "histogram", "labels": ("phase",), "cardinality": 8,
    },
    # ...and the reductions that completed, by backend actually used
    # (psum | wire) — the observable for "did auto pick the collective
    # path on this build"
    "multiproc_reductions_total": {
        "kind": "counter", "labels": ("backend",), "cardinality": 4,
    },
    # pod observatory (telemetry/fleet.py): per-rank wall seconds by
    # pass phase (decode | device_accumulate | reduce_wait) from the
    # last pod pass report — every rank publishes the SAME table, so
    # any one scrape names the straggler; pod-scale incidents minted,
    # by reason (rank_loss | drift | ...) — each incident id is shared
    # by every bundle the event produced across the pod
    "pod_straggler_seconds": {
        "kind": "gauge", "labels": ("rank", "phase"), "cardinality": 256,
    },
    "pod_incidents_total": {
        "kind": "counter", "labels": ("reason",), "cardinality": 16,
    },
    # fleet-merged drift (monitor/monitor.py + telemetry/fleet.py):
    # `drift_score` itself reflects pod-wide traffic after the
    # rank-ordered sketch merge; this family keeps each host's LOCAL
    # window score visible next to it, keyed by process rank
    "drift_score_partial": {
        "kind": "gauge", "labels": ("model", "process"),
        "cardinality": 256,
    },
}

_DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0, 60.0, 120.0, 300.0, 600.0,
)


def _label_key(labels: Dict[str, Any]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Metric:
    """One metric family: a name, a kind, and per-labelset samples.
    Thread-safe through the owning registry's lock."""

    def __init__(
        self,
        name: str,
        kind: str,
        help: str = "",
        buckets: Optional[Tuple[float, ...]] = None,
        lock: Optional[threading.RLock] = None,
    ) -> None:
        if kind not in ("counter", "gauge", "histogram"):
            raise ValueError(f"unknown metric kind: {kind!r}")
        self.name = name
        self.kind = kind
        self.help = help
        self.buckets: Tuple[float, ...] = tuple(
            sorted(buckets or _DEFAULT_BUCKETS)
        )
        self._lock = lock or threading.RLock()
        # counter/gauge: labelset -> number; histogram: labelset ->
        # {"buckets": [count per le], "sum": float, "count": int}
        self._samples: Dict[LabelKey, Any] = {}

    # -- counter/gauge -------------------------------------------------------

    def inc(self, amount: Any = 1, **labels: Any) -> None:
        if self.kind == "histogram":
            raise TypeError("histograms take observe(), not inc()")
        if self.kind == "counter" and amount < 0:
            raise ValueError("counters only increase")
        key = _label_key(labels)
        with self._lock:
            self._samples[key] = self._samples.get(key, 0) + amount

    def dec(self, amount: Any = 1, **labels: Any) -> None:
        if self.kind != "gauge":
            raise TypeError("only gauges decrease")
        self.inc(-amount, **labels)

    def set(self, value: Any, **labels: Any) -> None:
        if self.kind == "histogram":
            raise TypeError("histograms take observe(), not set()")
        with self._lock:
            self._samples[_label_key(labels)] = value

    def value(self, default: Any = 0, **labels: Any) -> Any:
        with self._lock:
            return self._samples.get(_label_key(labels), default)

    # -- histogram -----------------------------------------------------------

    # exemplars retained per labelset: enough to answer "which request
    # was that" for the recent observations without growing with traffic
    _MAX_EXEMPLARS = 4

    def observe(
        self, value: float, exemplar: Optional[str] = None, **labels: Any
    ) -> None:
        if self.kind != "histogram":
            raise TypeError(f"{self.kind} metrics take inc()/set()")
        v = float(value)
        key = _label_key(labels)
        with self._lock:
            h = self._samples.get(key)
            if h is None:
                h = self._samples[key] = {
                    "buckets": [0] * len(self.buckets),
                    "sum": 0.0,
                    "count": 0,
                }
            for i, le in enumerate(self.buckets):
                if v <= le:
                    h["buckets"][i] += 1
            h["sum"] += v
            h["count"] += 1
            if exemplar is not None:
                # exemplars (request/run ids) are UNBOUNDED values and
                # must never become labels (cardinality); a short ring
                # beside the sample keeps the trace join-key without
                # growing with traffic
                ex = h.setdefault("exemplars", [])
                ex.append({
                    "id": str(exemplar), "value": v, "t": time.time(),
                })
                del ex[: -self._MAX_EXEMPLARS]

    def exemplars(self, **labels: Any) -> List[Dict[str, Any]]:
        """Recent exemplars recorded for one labelset (histograms whose
        catalog entry declares `exemplars: True`); newest last."""
        with self._lock:
            h = self._samples.get(_label_key(labels))
            if not isinstance(h, dict):
                return []
            return [dict(e) for e in h.get("exemplars", ())]

    # -- shared --------------------------------------------------------------

    def samples(self) -> Dict[LabelKey, Any]:
        with self._lock:
            return {
                k: (
                    dict(
                        v,
                        buckets=list(v["buckets"]),
                        **(
                            {"exemplars": [dict(e) for e in v["exemplars"]]}
                            if "exemplars" in v
                            else {}
                        ),
                    )
                    if isinstance(v, dict)
                    else v
                )
                for k, v in self._samples.items()
            }

    def remove(self, **labels: Any) -> bool:
        """Drop one labelset's sample entirely (True when it existed).
        The end-mark for gauges that would otherwise report a finished
        run as live forever — a scrape after `Heartbeat.close()` shows
        NO `solver_iteration{solver=...}` series instead of the last
        iteration of a fit that ended minutes ago."""
        with self._lock:
            return self._samples.pop(_label_key(labels), None) is not None

    def clear(self) -> None:
        with self._lock:
            self._samples.clear()


class DictView(MutableMapping):
    """Mapping facade over one gauge family labeled by ``key`` — the
    back-compat skin for the legacy module-level metric dicts
    (`mesh.STAGE_COUNTS` et al.).  Every read/write goes straight through
    the registry, so `dump_prometheus()` and `snapshot()` see the same
    numbers the old dict callers do; non-numeric values (the staging
    engine's `label` field) are kept on the view itself, outside the
    metric samples."""

    def __init__(self, metric: Metric, initial: Optional[dict] = None):
        self._metric = metric
        self._initial = dict(initial or {})
        self._strs: Dict[str, Any] = {}
        self.seed()

    def seed(self) -> None:
        """Apply the initial key set WITHOUT clobbering live samples:
        only missing keys are set.  Registry reset clears samples first
        (so the initials land), while a re-import/reload that rebuilds a
        view must not zero counters the process already accumulated."""
        for k, v in self._initial.items():
            if k not in self:
                self[k] = v

    def __getitem__(self, key: str) -> Any:
        if key in self._strs:
            return self._strs[key]
        sentinel = object()
        v = self._metric.value(default=sentinel, key=key)
        if v is sentinel:
            raise KeyError(key)
        return v

    def __setitem__(self, key: str, value: Any) -> None:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            self._strs[key] = value
            with self._metric._lock:
                self._metric._samples.pop(_label_key({"key": key}), None)
        else:
            self._strs.pop(key, None)
            self._metric.set(value, key=key)

    def __delitem__(self, key: str) -> None:
        if key in self._strs:
            del self._strs[key]
            return
        with self._metric._lock:
            lk = _label_key({"key": key})
            if lk not in self._metric._samples:
                raise KeyError(key)
            del self._metric._samples[lk]

    def __iter__(self) -> Iterator[str]:
        # only this view's own samples — exactly one `key` label; a
        # stray differently-labeled sample someone registered onto the
        # same family must not break iteration/len/clear
        keys = [
            lk[0][1]
            for lk in self._metric.samples()
            if len(lk) == 1 and lk[0][0] == "key"
        ]
        keys += [k for k in self._strs if k not in keys]
        return iter(keys)

    def __len__(self) -> int:
        return len(list(iter(self)))

    def bump(self, key: str, amount: Any = 1) -> None:
        """Increment `key`, creating it at 0 first — the drift-proof form
        of ``view[key] += 1`` (never drops a missing mirror key)."""
        self[key] = self.get(key, 0) + amount

    def __repr__(self) -> str:  # debugging/reprs in logs
        return repr(dict(self))


class MetricsRegistry:
    """Process-global metric store: register-once families, snapshot and
    reset.  One RLock guards registration and every sample mutation."""

    def __init__(self) -> None:
        # the registry's internal lock is itself a NAMED lock — it is
        # one of the hottest in the process (every metric op holds it)
        # and the contention profile must cover it.  Imported lazily:
        # locks.py publishes INTO this registry, so the two modules
        # bootstrap in either order (locks.py is stdlib-only at module
        # scope; publication is deferred, never inline in acquire).
        from .locks import named_lock

        self._lock = named_lock("metrics_registry", kind="rlock")
        self._metrics: Dict[str, Metric] = {}
        self._views: Dict[str, DictView] = {}

    def _register(
        self, name: str, kind: str, help: str = "",
        buckets: Optional[Tuple[float, ...]] = None,
    ) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if m.kind != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}"
                    )
                return m
            m = Metric(name, kind, help, buckets, lock=self._lock)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "") -> Metric:
        return self._register(name, "counter", help)

    def gauge(self, name: str, help: str = "") -> Metric:
        return self._register(name, "gauge", help)

    def histogram(
        self, name: str, help: str = "",
        buckets: Optional[Tuple[float, ...]] = None,
    ) -> Metric:
        return self._register(name, "histogram", help, buckets)

    def dict_view(
        self, name: str, help: str = "", initial: Optional[dict] = None
    ) -> DictView:
        """A legacy-dict facade over a gauge family labeled ``key``.
        Idempotent per name: a repeat call (module reload, a test
        re-importing bench.py) returns the SAME view with any new
        initial keys merged non-destructively — live counters are never
        zeroed and the view table stays bounded."""
        metric = self._register(name, "gauge", help)
        with self._lock:
            view = self._views.get(name)
            if view is None:
                view = DictView(metric, initial)
                self._views[name] = view
            elif initial:
                view._initial.update(initial)
                view.seed()
        return view

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> List[Metric]:
        with self._lock:
            return list(self._metrics.values())

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Plain nested dict of every sample: {metric: {labelstr: value}}
        with labelstr ``'k=v,k2=v2'`` (empty string for unlabeled) and
        histogram values flattened to {"sum", "count"}.  Safe to hold
        across a fit and diff with `delta`."""
        out: Dict[str, Dict[str, Any]] = {}
        for m in self.metrics():
            fam: Dict[str, Any] = {}
            for lk, v in m.samples().items():
                ls = ",".join(f"{k}={val}" for k, val in lk)
                if isinstance(v, dict):
                    fam[ls] = {"sum": v["sum"], "count": v["count"]}
                else:
                    fam[ls] = v
            out[m.name] = fam
        return out

    def reset(self) -> None:
        """Zero every sample; registrations (and dict-view initial keys)
        survive."""
        with self._lock:
            for m in self._metrics.values():
                m.clear()
            for v in self._views.values():
                v._strs.clear()
                v.seed()


def delta(
    before: Dict[str, Dict[str, Any]], after: Dict[str, Dict[str, Any]]
) -> Dict[str, Dict[str, Any]]:
    """Numeric per-sample change between two `snapshot()`s, keeping only
    samples that moved (per-fit reports, bench section telemetry).
    Histogram samples diff their {"sum", "count"} pair."""
    out: Dict[str, Dict[str, Any]] = {}
    for name, fam in after.items():
        prev = before.get(name, {})
        changed: Dict[str, Any] = {}
        for ls, v in fam.items():
            p = prev.get(ls)
            if isinstance(v, dict):
                pc = (p or {}).get("count", 0)
                if v.get("count", 0) != pc:
                    changed[ls] = {
                        "count": v.get("count", 0) - pc,
                        "sum": round(
                            v.get("sum", 0.0) - (p or {}).get("sum", 0.0), 6
                        ),
                    }
            elif isinstance(v, (int, float)) and not isinstance(v, bool):
                pv = p if isinstance(p, (int, float)) else 0
                if v != pv:
                    changed[ls] = v - pv
        if changed:
            out[name] = changed
    return out


def check_cardinality(
    registry: Optional["MetricsRegistry"] = None,
) -> List[str]:
    """Live label-cardinality audit against METRIC_CATALOG: returns one
    problem string per family whose DISTINCT labelset count exceeds its
    declared bound (a label fed from an unbounded value — a run id, a
    timestamp — blows past it immediately).  Run by the jit-audit
    sanitizer CI job after exercising the solvers, and by tests."""
    reg = registry or REGISTRY
    problems: List[str] = []
    for m in reg.metrics():
        spec = METRIC_CATALOG.get(m.name)
        if spec is None:
            continue  # private/test registries may carry their own names
        n = len(m.samples())
        bound = int(spec.get("cardinality", 0) or 0)
        if bound and n > bound:
            problems.append(
                f"metric {m.name!r}: {n} distinct labelsets exceed the "
                f"declared cardinality bound {bound}"
            )
    return problems


# the process-global default registry every module-level view and counter
# registers with; tests may build private MetricsRegistry instances
REGISTRY = MetricsRegistry()

counter = REGISTRY.counter
gauge = REGISTRY.gauge
histogram = REGISTRY.histogram
dict_view = REGISTRY.dict_view
snapshot = REGISTRY.snapshot
reset_metrics = REGISTRY.reset


__all__ = [
    "DictView",
    "METRIC_CATALOG",
    "Metric",
    "MetricsRegistry",
    "REGISTRY",
    "check_cardinality",
    "counter",
    "delta",
    "dict_view",
    "gauge",
    "histogram",
    "reset_metrics",
    "snapshot",
]
