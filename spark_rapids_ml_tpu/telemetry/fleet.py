#
# Pod observatory — the cross-rank half of the telemetry stack.  Every
# observability surface below this module (span trees, the flight
# recorder, drift windows, the utilization timeline) is per-process;
# this module correlates them across the pod:
#
#   pass correlation    rank 0 mints one `pass_id` per accumulate pass
#                       and broadcasts it over the coordination-service
#                       seam (`begin_pod_pass`); every rank's spans,
#                       reduce-wait intervals and pod_recovery events
#                       carry it, so N per-rank traces of one pass can
#                       be joined on a single key
#
#   clock alignment     heartbeat KV values carry the sender's wall
#                       clock; `note_clock_sample` collects
#                       (ts_send, t_recv) pairs and `clock_offsets`
#                       estimates per-peer skew as min(t_recv - ts_send)
#                       — an upper bound on (skew + delivery delay), so
#                       the estimate errs by at most the minimum
#                       delivery delay observed, itself bounded by the
#                       heartbeat probe cadence.  `merge_chrome_traces`
#                       folds per-rank trace dumps into ONE
#                       Perfetto-loadable trace, one track group per
#                       rank, peer timestamps shifted by the estimated
#                       offset (uniform per rank — order within a track
#                       is preserved, so merged tracks stay monotone)
#
#   straggler ledger    at pass complete each rank rides a tiny
#                       per-phase wall-clock blob (decode /
#                       device-accumulate / reduce-wait, from the
#                       utilization timeline clipped to the pass
#                       window) on a `reduce_blob_list` exchange; every
#                       rank computes the SAME critical-path table and
#                       publishes `pod_straggler_seconds{rank,phase}`,
#                       plus a `pass_report` naming the slowest rank
#                       per phase for the fit report
#
#   incident bundles    a pod-scale failure (rank loss, reduce timeout)
#                       mints one DETERMINISTIC incident id per event —
#                       a hash of (reason, generation, token), so every
#                       survivor computes it without communicating —
#                       and `exchange_incident_rings` best-effort pulls
#                       peers' recent flight-recorder rings over the
#                       bounded `pod.kv_wait` (a dead rank's ring is
#                       simply absent, and named as such) into one
#                       merged `pod_trace.json` attachment
#
#   fleet drift         serve-time drift windows publish their closed
#                       builder blobs to per-rank monotonic KV keys
#                       (non-collective — serving traffic is
#                       asymmetric, a blind allgather would hang the
#                       busy rank on the idle one); peers drain each
#                       other's keys with tiny bounded probes and merge
#                       rank-ordered, so `drift_score` reflects
#                       pod-wide traffic while per-host partials stay
#                       visible as `drift_score_partial{model,process}`
#
# Everything here is best-effort observability: no call may take down
# the pass or the recovery path it instruments, so cross-process
# failures degrade to the local view, never raise past this module.
#
from __future__ import annotations

import collections
import hashlib
import json
import time
from typing import Any, Deque, Dict, List, Optional, Tuple

from .locks import named_lock
from .registry import counter, gauge

# one lock for every piece of fleet state below: clock samples, pass
# bookkeeping, drift-window caches.  Never held across a KV wait.
_fleet_lock = named_lock("fleet_state")

# retained (ts_send, t_recv) pairs per peer; minutes of heartbeat
# history at the default 2 s cadence — enough for a stable min
_MAX_CLOCK_SAMPLES = 64

# heartbeat values below this are not wall-clock timestamps (the
# pre-observatory protocol wrote the literal "1"); rejecting them keeps
# a mixed-version pod from poisoning the offset estimate
_MIN_PLAUSIBLE_TS = 1e9

_clock_samples: Dict[int, Deque[Tuple[float, float]]] = {}

# last completed pass report, for telemetry/report.py's stamp-gated
# copy (same last-run-state discipline as FUSED_METRICS)
LAST_PASS_REPORT: Dict[str, Any] = {}

# current pass bookkeeping: id + perf_counter/wall start of the window
_pass_state: Dict[str, Any] = {}

# fleet drift exchange state, all under _fleet_lock:
#   _drift_pub_seq[model]        next seq this rank publishes
#   _drift_next_seq[(model, r)]  next seq to probe from peer r
#   _drift_latest[model][r]      latest blob seen from peer r
_drift_pub_seq: Dict[str, int] = {}
_drift_next_seq: Dict[Tuple[str, int], int] = {}
_drift_latest: Dict[str, Dict[int, bytes]] = {}

# bounded per-key probe for peer drift blobs — same "is it there right
# now" shape as the liveness probe, never a real wait
_DRIFT_PROBE_MS = 50

STRAGGLER_SECONDS = gauge(
    "pod_straggler_seconds",
    "Per-rank wall seconds by pass phase from the last pod pass report",
)

POD_INCIDENTS = counter(
    "pod_incidents_total",
    "Pod-scale incidents minted, by reason",
)

# utilization-timeline kinds -> the pass-report phase names the
# straggler table speaks (the ISSUE's decode / device-accumulate /
# reduce-wait vocabulary)
_PHASE_KINDS = {
    "decode": "host_prep",
    "device_accumulate": "device",
    "reduce_wait": "reduce_wait",
}


# ---------------------------------------------------------------------------
# Clock-offset estimation
# ---------------------------------------------------------------------------


def note_clock_sample(rank: int, ts_send: float, t_recv: float) -> None:
    """Record one heartbeat clock observation from `rank`: the wall
    clock the peer wrote into its beat value (`ts_send`) and our wall
    clock when the probe read it (`t_recv`).  Implausible senders
    (legacy beats, zeroed clocks) are dropped.  Cheap; never raises."""
    try:
        ts_send = float(ts_send)
        t_recv = float(t_recv)
    except (TypeError, ValueError):
        return
    if ts_send < _MIN_PLAUSIBLE_TS or t_recv < _MIN_PLAUSIBLE_TS:
        return
    with _fleet_lock:
        dq = _clock_samples.get(int(rank))
        if dq is None:
            dq = _clock_samples[int(rank)] = collections.deque(
                maxlen=_MAX_CLOCK_SAMPLES
            )
        dq.append((ts_send, t_recv))


def clock_offsets() -> Dict[int, Tuple[float, float]]:
    """Per-peer clock offset estimates: rank -> (offset_s, err_s).

    Each sample observes `t_recv - ts_send = skew + delay` where
    `skew = local_clock - peer_clock` and `delay >= 0` is the
    beat-to-probe delivery lag; the minimum over retained samples is
    therefore an UPPER bound on the skew, off by at most the smallest
    delay that occurred.  Delivery lag is bounded by one heartbeat
    probe cadence, so the documented error bar is
    `min(observed spread, heartbeat interval)`.  Adding `offset_s` to a
    peer timestamp maps it onto this process's clock."""
    from ..resilience.pod import heartbeat_interval_s

    hb = heartbeat_interval_s()
    out: Dict[int, Tuple[float, float]] = {}
    with _fleet_lock:
        items = {r: list(dq) for r, dq in _clock_samples.items() if dq}
    for r, samples in items.items():
        diffs = [t_recv - ts_send for ts_send, t_recv in samples]
        lo = min(diffs)
        spread = max(diffs) - lo
        out[r] = (lo, min(spread, hb) if len(diffs) > 1 else hb)
    return out


def merge_chrome_traces(
    traces_by_rank: Dict[int, Dict[str, Any]],
    offsets: Optional[Dict[int, Tuple[float, float]]] = None,
) -> Dict[str, Any]:
    """Fold per-rank Chrome-trace dicts into ONE Perfetto-loadable
    trace: each rank becomes its own track group (`pid` = rank, a
    `process_name` metadata row labels it), and every event from a
    non-reference rank is shifted by that rank's estimated clock
    offset.  The shift is uniform per rank, so event order within a
    track is preserved — merged tracks are monotone wherever the
    per-rank dumps were.  `offsets` defaults to `clock_offsets()`
    (ranks without an estimate merge unshifted); the offsets and their
    error bars land in `otherData` so a reader knows how far to trust
    cross-track alignment."""
    if offsets is None:
        offsets = clock_offsets()
    events: List[Dict[str, Any]] = []
    applied: Dict[str, List[float]] = {}
    for rank in sorted(traces_by_rank):
        trace = traces_by_rank[rank] or {}
        off_s, err_s = offsets.get(rank, (0.0, 0.0))
        shift_us = off_s * 1e6
        applied[str(rank)] = [round(off_s, 6), round(err_s, 6)]
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": rank,
                "tid": 0,
                "args": {"name": f"rank{rank}"},
            }
        )
        for e in trace.get("traceEvents", []):
            if e.get("ph") == "M":
                e = dict(e)
                e["pid"] = rank
                events.append(e)
                continue
            e = dict(e)
            e["pid"] = rank
            if "ts" in e:
                e["ts"] = float(e["ts"]) + shift_us
            events.append(e)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "clock_offsets_s": applied,
            "offset_note": (
                "peer ts shifted by min(t_recv-ts_send) over heartbeat "
                "samples; error bounded by the heartbeat interval"
            ),
        },
    }


# ---------------------------------------------------------------------------
# Pod-correlated passes + straggler attribution
# ---------------------------------------------------------------------------


def begin_pod_pass() -> str:
    """Start one pod-correlated accumulate pass: rank 0 mints the
    `pass_id`, every other rank receives it over the generation-
    namespaced broadcast seam, and every rank stamps it onto its trace
    events (`tracing.set_current_pass_id`) until `complete_pod_pass`.
    MUST be called from an SPMD site (every rank, same order) — the
    broadcast is a collective.  Falls back to a locally minted id when
    the pod seam is down; never raises."""
    from ..tracing import event, mint_run_id, set_current_pass_id

    pass_id = mint_run_id("pass")
    try:
        from ..parallel.context import (
            broadcast_bytes,
            cross_process_reduce_ready,
            process_topology,
        )

        nranks, rank = process_topology()
        if nranks > 1 and cross_process_reduce_ready():
            payload = pass_id.encode("ascii") if rank == 0 else None
            pass_id = broadcast_bytes("pass_id", payload).decode("ascii")
    except Exception:
        pass  # local id still correlates this rank's own spans
    with _fleet_lock:
        _pass_state.clear()
        _pass_state.update(
            {
                "pass_id": pass_id,
                "t0_pc": time.perf_counter(),
                "t0_wall": time.time(),
            }
        )
    set_current_pass_id(pass_id)
    event(f"pod_pass_begin[{pass_id}]")
    return pass_id


def _local_phase_seconds(t0_pc: float, t1_pc: float) -> Dict[str, float]:
    """This rank's per-phase wall seconds over the pass window, from
    the utilization timeline: intervals are merged per kind and clipped
    to [t0_pc, t1_pc], so a long-lived producer can't charge time from
    a previous pass to this one."""
    from .utilization import merge_intervals, timeline

    evs = timeline()
    out: Dict[str, float] = {}
    for phase, kind in _PHASE_KINDS.items():
        iv = [
            (max(e[3], t0_pc), min(e[4], t1_pc))
            for e in evs
            if e[1] == kind and e[4] > t0_pc and e[3] < t1_pc
        ]
        out[phase] = round(
            sum(hi - lo for lo, hi in merge_intervals(iv) if hi > lo), 6
        )
    return out


def complete_pod_pass(run_id: str = "") -> Optional[Dict[str, Any]]:
    """Close the current pod pass: compute this rank's per-phase
    seconds, ride them on a `reduce_blob_list` exchange (SPMD — every
    rank reaches this site after the pass reduction), and fold every
    rank's blob into the straggler table all ranks agree on.  Publishes
    `pod_straggler_seconds{rank,phase}` and stamps `LAST_PASS_REPORT`
    for the fit report.  A failed exchange (peer died after the main
    reduce) degrades to a local-only report; never raises."""
    from ..tracing import set_current_pass_id

    with _fleet_lock:
        state = dict(_pass_state)
        _pass_state.clear()
    if not state:
        return None
    pass_id = state["pass_id"]
    t1_pc = time.perf_counter()
    phases = _local_phase_seconds(state["t0_pc"], t1_pc)
    try:
        from ..parallel.context import process_topology, reduce_blob_list

        nranks, rank = process_topology()
        blob = json.dumps(
            {"rank": rank, "pass_id": pass_id, "phases": phases}
        ).encode("ascii")
        if nranks > 1:
            blobs = reduce_blob_list("pass_report", blob)
        else:
            blobs = [blob]
        per_rank: Dict[int, Dict[str, float]] = {}
        for b in blobs:
            try:
                d = json.loads(b.decode("ascii"))
                per_rank[int(d["rank"])] = {
                    p: float(v) for p, v in d.get("phases", {}).items()
                }
            except Exception:
                continue
    except Exception:
        # recovery owns the failure; the local view still reports
        try:
            from ..parallel.context import process_topology

            rank = process_topology()[1]
        except Exception:
            rank = 0
        per_rank = {rank: phases}
    slowest: Dict[str, Any] = {}
    for phase in _PHASE_KINDS:
        rows = {r: p.get(phase, 0.0) for r, p in per_rank.items()}
        if not rows:
            continue
        worst = max(rows, key=lambda r: rows[r])
        slowest[phase] = {
            "rank": worst,
            "seconds": rows[worst],
            "spread_s": round(rows[worst] - min(rows.values()), 6),
        }
        for r, s in rows.items():
            STRAGGLER_SECONDS.set(s, rank=str(r), phase=phase)
    report = {
        "pass_id": pass_id,
        "wall_s": round(t1_pc - state["t0_pc"], 6),
        "ranks": {str(r): per_rank[r] for r in sorted(per_rank)},
        "slowest": slowest,
        "run_id": run_id,
        "stamp": round(time.time(), 3),
    }
    with _fleet_lock:
        LAST_PASS_REPORT.clear()
        LAST_PASS_REPORT.update(report)
    set_current_pass_id("")
    return report


def pass_report() -> Dict[str, Any]:
    """The last completed pass report (stamped), or {}."""
    with _fleet_lock:
        return dict(LAST_PASS_REPORT)


# ---------------------------------------------------------------------------
# Pod incident bundles
# ---------------------------------------------------------------------------


def mint_incident_id(
    reason: str, token: str, generation: int = 0
) -> str:
    """One DETERMINISTIC incident id per pod-scale event: a hash of
    (reason, detection generation, caller token — e.g. the sorted dead
    set).  Every survivor of the same event computes the same id
    without a round of communication, so their bundles share it and
    fleet aggregation can group per incident instead of per rank."""
    h = hashlib.blake2b(digest_size=6)
    h.update(f"{reason}|g{int(generation)}|{token}".encode())
    incident_id = f"inc-{h.hexdigest()}"
    POD_INCIDENTS.inc(reason=reason)
    return incident_id


def _own_ring_trace() -> Dict[str, Any]:
    from ..config import get_config
    from .exporters import chrome_trace
    from .flight_recorder import RECORDER

    window_s = float(get_config("flight_recorder_window_s"))
    return chrome_trace(events=RECORDER.events(window_s=window_s))


def exchange_incident_rings(
    incident_id: str, dead=(),
) -> Dict[str, Any]:
    """Best-effort cross-rank evidence collection for one incident:
    publish this rank's recent flight-recorder ring (as a Chrome trace)
    to an incident-scoped KV key, then pull every live peer's ring
    under one shared deadline (`pod_incident_ring_deadline_s`).  A
    dead or slow peer's ring is simply ABSENT — named in the returned
    `pod_incident.json`, never waited on past the deadline.  Returns
    flight-recorder attachments: the merged `pod_trace.json` (every
    collected ring on the common corrected timeline) plus the incident
    manifest.  Single-process or seam-down: {}.  Never raises."""
    try:
        from ..config import get_config
        from ..parallel.context import (
            coordination_client,
            kv_fetch,
            kv_publish,
        )
        from ..resilience.pod import _current_boot_ranks, _my_boot_rank

        client = coordination_client()
        if client is None:
            return {}
        me = _my_boot_rank()
        ranks = _current_boot_ranks()
        dead = {int(d) for d in (dead or ())}
        own = _own_ring_trace()
        try:
            kv_publish(
                f"inc/{incident_id}/{me}",
                json.dumps(own).encode("ascii"),
            )
        except Exception:
            pass  # publishing is for the peers; the pull still runs
        deadline_s = float(get_config("pod_incident_ring_deadline_s"))
        t_end = time.monotonic() + max(0.1, deadline_s)
        traces: Dict[int, Dict[str, Any]] = {me: own}
        absent: Dict[str, str] = {}
        for r in sorted(dead):
            absent[str(r)] = "rank dead at detection; ring lost with it"
        for r in sorted(set(ranks) - dead - {me}):
            left_ms = int(max(50, (t_end - time.monotonic()) * 1000))
            if t_end - time.monotonic() <= 0:
                absent[str(r)] = "incident ring deadline exhausted"
                continue
            try:
                payload = kv_fetch(
                    f"inc/{incident_id}/{r}",
                    timeout_ms=left_ms,
                    tag=f"incident/{incident_id}",
                    peer=r,
                )
                traces[r] = json.loads(payload.decode("ascii"))
            except Exception as e:
                absent[str(r)] = f"{type(e).__name__}: {e}"
        merged = merge_chrome_traces(traces)
        return {
            "pod_trace.json": json.dumps(merged).encode("ascii"),
            "pod_incident": {
                "incident_id": incident_id,
                "dumping_rank": me,
                "ranks_present": sorted(traces),
                "ranks_absent": absent,
                "clock_offsets_s": merged["otherData"][
                    "clock_offsets_s"
                ],
            },
        }
    except Exception:
        return {}


# ---------------------------------------------------------------------------
# Fleet-merged drift windows
# ---------------------------------------------------------------------------


def _drift_key(model: str) -> str:
    # model names may hold characters the KV store treats as
    # separators; a short digest keeps the key flat and collision-free
    return hashlib.blake2b(model.encode(), digest_size=6).hexdigest()


def fleet_drift_enabled() -> bool:
    from ..config import get_config

    return str(get_config("drift_fleet_merge")).lower() != "off"


def publish_drift_window(model: str, payload: bytes) -> None:
    """Publish one closed drift-window builder blob to this rank's next
    monotonic incident-free KV key for `model`.  NON-collective: the
    busy rank publishes whenever its window closes; idle peers owe
    nothing.  No-op single-process or seam-down; never raises."""
    try:
        from ..parallel.context import (
            coordination_client,
            kv_publish,
            process_topology,
        )
        from ..resilience.pod import _my_boot_rank

        if process_topology()[0] == 1 or not fleet_drift_enabled():
            return
        if coordination_client() is None:
            return
        me = _my_boot_rank()
        mk = _drift_key(model)
        with _fleet_lock:
            seq = _drift_pub_seq.get(model, 0)
            _drift_pub_seq[model] = seq + 1
        kv_publish(f"drift/{mk}/{me}/{seq}", payload)
    except Exception:
        pass


def fetch_peer_drift_windows(model: str) -> Dict[int, bytes]:
    """Drain peers' newly published drift blobs with tiny bounded
    probes (the liveness-probe shape: present-now or skip, never a
    real wait) and return the LATEST blob per peer rank seen so far.
    Pull-based and non-collective — a rank that never serves traffic
    never publishes, and that's fine: its absence just means the pod
    view equals the publishers' merge.  Never raises."""
    out: Dict[int, bytes] = {}
    try:
        from ..parallel.context import (
            coordination_client,
            kv_fetch,
            process_topology,
        )
        from ..resilience.pod import _current_boot_ranks, _my_boot_rank

        if process_topology()[0] == 1 or not fleet_drift_enabled():
            return {}
        client = coordination_client()
        if client is None:
            return {}
        me = _my_boot_rank()
        mk = _drift_key(model)
        for r in sorted(set(_current_boot_ranks()) - {me}):
            while True:
                with _fleet_lock:
                    seq = _drift_next_seq.get((model, r), 0)
                try:
                    payload = kv_fetch(
                        f"drift/{mk}/{r}/{seq}",
                        timeout_ms=_DRIFT_PROBE_MS,
                        tag=f"drift/{model}",
                        peer=r,
                    )
                except Exception:
                    break  # nothing new from this peer right now
                with _fleet_lock:
                    _drift_next_seq[(model, r)] = seq + 1
                    _drift_latest.setdefault(model, {})[r] = bytes(
                        payload
                    )
        with _fleet_lock:
            out = dict(_drift_latest.get(model, {}))
    except Exception:
        pass
    return out


# ---------------------------------------------------------------------------
# Summaries / lifecycle
# ---------------------------------------------------------------------------


def fleet_summary() -> Dict[str, Any]:
    """Small pod-observatory block for serving `_totals` / reports:
    the last pass report, the live clock-offset table, incident count
    families are on the registry already."""
    out: Dict[str, Any] = {}
    rep = pass_report()
    if rep:
        out["pass_report"] = rep
    offs = clock_offsets()
    if offs:
        out["clock_offsets_s"] = {
            str(r): [round(o, 6), round(e, 6)]
            for r, (o, e) in sorted(offs.items())
        }
    return out


def reset_fleet() -> None:
    """Tests / operator reset: drop every piece of fleet state."""
    from ..tracing import set_current_pass_id

    with _fleet_lock:
        _clock_samples.clear()
        _pass_state.clear()
        LAST_PASS_REPORT.clear()
        _drift_pub_seq.clear()
        _drift_next_seq.clear()
        _drift_latest.clear()
    set_current_pass_id("")


def on_reinit() -> None:
    """Pod re-bootstrap (resilience/pod.on_reinit): peer clocks and
    drift seq counters belong to the OLD runtime — a re-bootstrapped
    peer restarts its heartbeat numbering and its drift keys live
    under a new generation prefix.  The last pass report survives (it
    describes a completed pass, not live state)."""
    with _fleet_lock:
        _clock_samples.clear()
        _pass_state.clear()
        _drift_pub_seq.clear()
        _drift_next_seq.clear()
        _drift_latest.clear()


__all__ = [
    "LAST_PASS_REPORT",
    "begin_pod_pass",
    "clock_offsets",
    "complete_pod_pass",
    "exchange_incident_rings",
    "fetch_peer_drift_windows",
    "fleet_drift_enabled",
    "fleet_summary",
    "merge_chrome_traces",
    "mint_incident_id",
    "note_clock_sample",
    "on_reinit",
    "pass_report",
    "publish_drift_window",
    "reset_fleet",
]
