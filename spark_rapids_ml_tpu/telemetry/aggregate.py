#
# Cross-process metric aggregation — one fleet, one page.  Every process
# of a multi-host pod (and every serving front end) keeps its OWN
# registry; until now two processes could not merge them, so "how many
# retries did the fleet take" meant ssh-ing into N hosts.  This module
# merges per-process Prometheus pages (the existing text round-trip —
# exporters.dump_prometheus / parse_prometheus_families — is the wire
# format, so a page can come from an in-process dump, a file a rank
# wrote, or a scrape of a per-host `telemetry_port` endpoint) by family:
#
#   counters     SUM across processes per labelset — `retries_total`
#                over the fleet is exact, not approximate
#   gauges       keep per-process series, tagged with a `process` label
#                (summing point-in-time values like `solver_iteration`
#                or resident-byte gauges would manufacture nonsense)
#   histograms   merge BUCKET-WISE per labelset: per-`le` counts, sums
#                and totals add (cumulative buckets stay cumulative), so
#                fleet-level latency quantiles come out of the merged
#                buckets with no per-process resampling.  EXEMPLARS on
#                the bucket lines (request ids) are PRESERVED across the
#                merge — the newest `MERGE_MAX_EXEMPLARS` per labelset
#                by timestamp — so a fleet-level latency bucket still
#                names the requests that landed in it (request-id
#                forensics survive aggregation)
#   untyped      treated like gauges (per-process, labeled)
#
# A process that is GONE is reported absent — `scrape_endpoints` returns
# the failed targets separately instead of folding zeros into the merge
# (a dead rank showing `retries_total 0` would read as "healthy and
# idle", the exact lie an aggregator must not tell).
#
# Pure stdlib, no jax import: aggregation runs on whatever box watches
# the fleet.
#
from __future__ import annotations

import glob as _glob
import json
import os
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .exporters import parse_prometheus_families, render_families

LabelPairs = Tuple[Tuple[str, str], ...]

# exemplars retained per histogram labelset across a merge (newest by
# timestamp win): enough to answer "which request was that" at fleet
# level without the merged page growing with process count — each
# source page already carries at most Metric._MAX_EXEMPLARS per
# labelset, this bounds the union
MERGE_MAX_EXEMPLARS = 8


def _with_process(labels: LabelPairs, process: str) -> LabelPairs:
    """Tag a series with the process it came from.  A series that
    ALREADY carries a `process` label (this page is itself a merge —
    the tiered host -> pod -> fleet case) gets namespaced
    (`pod1/hostA`), never a duplicate label name: duplicate names make
    the rendered page invalid and subset matches ambiguous."""
    nested = None
    rest = []
    for k, v in labels:
        if k == "process":
            nested = v
        else:
            rest.append((k, v))
    tag = f"{process}/{nested}" if nested else str(process)
    return tuple(sorted(rest + [("process", tag)]))


def merge_prometheus(pages: Dict[str, str]) -> Dict[str, Dict[str, Any]]:
    """Merge `{process_name: prometheus_text}` pages into one family
    table (the `parse_prometheus_families` structure): counters sum,
    gauges/untyped keep per-process series under a `process` label,
    histograms merge bucket-wise.  Families only some processes report
    merge over the reporters; a page that fails to parse raises (a torn
    scrape must not silently vanish from the fleet view).  Render the
    result with `dump_merged`."""
    merged: Dict[str, Dict[str, Any]] = {}
    for process in sorted(pages):
        fams = parse_prometheus_families(pages[process])
        for name, entry in fams.items():
            kind = entry.get("kind", "untyped")
            tgt = merged.setdefault(
                name,
                {"kind": kind, "help": entry.get("help", ""), "samples": {}},
            )
            if tgt["kind"] != kind and tgt["kind"] == "untyped":
                tgt["kind"] = kind  # a later page knew the type
            if not tgt.get("help") and entry.get("help"):
                tgt["help"] = entry["help"]
            out = tgt["samples"]
            if kind == "counter":
                for lk, v in entry["samples"].items():
                    out[lk] = out.get(lk, 0) + v
            elif kind == "histogram":
                for lk, h in entry["samples"].items():
                    acc = out.setdefault(
                        lk, {"buckets": {}, "sum": 0.0, "count": 0}
                    )
                    for le, c in h["buckets"].items():
                        acc["buckets"][le] = acc["buckets"].get(le, 0) + c
                    acc["sum"] += h["sum"]
                    acc["count"] += h["count"]
                    if h.get("exemplars"):
                        merged_ex = sorted(
                            list(acc.get("exemplars", ()))
                            + [dict(e) for e in h["exemplars"]],
                            key=lambda e: e.get("t", 0.0),
                        )
                        acc["exemplars"] = merged_ex[-MERGE_MAX_EXEMPLARS:]
            else:  # gauge / untyped: per-process series
                for lk, v in entry["samples"].items():
                    out[_with_process(lk, process)] = v
    return merged


def dump_merged(merged: Dict[str, Dict[str, Any]]) -> str:
    """A merged family table as Prometheus text — itself parseable by
    `parse_prometheus_families`, so aggregation tiers stack (host pages
    -> pod page -> fleet page)."""
    return render_families(merged)


class ScrapeResult:
    """One aggregation round over per-host endpoints: the pages that
    answered, the merged family table, and — separately — the targets
    that did NOT answer.  `absent` maps the dead process name to the
    error string; its series are MISSING from `merged`, never zero."""

    def __init__(
        self,
        pages: Dict[str, str],
        absent: Dict[str, str],
    ) -> None:
        self.pages = pages
        self.absent = absent
        self.merged = merge_prometheus(pages)

    def dump(self) -> str:
        return dump_merged(self.merged)

    def __repr__(self) -> str:
        return (
            f"ScrapeResult(processes={sorted(self.pages)}, "
            f"absent={sorted(self.absent)})"
        )


def _expand_file_globs(
    targets: Dict[str, str], absent: Dict[str, str]
) -> Dict[str, str]:
    """Expand `file://<glob>` targets in place: ONE pattern covering
    every rank's on-disk dump (`file:///run/telemetry/rank*.prom`)
    becomes one target per matching file, named `{name}:{basename}` —
    the no-URL-list form pod CI smokes and air-gapped runs use.  The
    dead-rank contract is preserved: a pattern matching NOTHING is
    reported absent under its own name (a rank that never wrote its
    dump must not silently vanish from the merge), and matched files
    that fail to read land in `.absent` individually."""
    out: Dict[str, str] = {}
    for name in sorted(targets):
        url = targets[name]
        if not str(url).startswith("file://"):
            out[name] = url
            continue
        pattern = str(url)[len("file://"):]
        matches = sorted(_glob.glob(pattern))
        if not matches:
            absent[name] = f"no files matched {pattern!r}"
            continue
        if len(matches) == 1 and matches[0] == pattern:
            out[name] = url  # literal single-file target keeps its name
            continue
        for path in matches:
            out[f"{name}:{os.path.basename(path)}"] = f"file://{path}"
    return out


def scrape_endpoints(
    targets: Dict[str, str], timeout_s: float = 5.0
) -> ScrapeResult:
    """Scrape `{process_name: url}` `telemetry_port` endpoints (each url
    is the full `http://host:port/metrics`) and merge what answered.
    `file://` targets may be GLOB patterns — one pattern matching every
    rank's written dump expands to one page per matching file (named
    `{name}:{basename}`); a pattern matching nothing is absent under
    its own name.  Unreachable/erroring endpoints land in `.absent`
    with the error — the fleet view names its blind spots instead of
    zero-filling them.  Targets fetch CONCURRENTLY (bounded pool), so a
    round over a fleet with dead hosts costs ~one timeout, not one per
    dead host."""

    def _fetch(url: str) -> str:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            return resp.read().decode("utf-8")

    pages: Dict[str, str] = {}
    absent: Dict[str, str] = {}
    targets = _expand_file_globs(targets, absent)
    names = sorted(targets)
    if names:
        with ThreadPoolExecutor(
            max_workers=min(32, len(names)), thread_name_prefix="scrape"
        ) as pool:
            futs = {n: pool.submit(_fetch, targets[n]) for n in names}
        for name in names:
            try:
                pages[name] = futs[name].result()
            except Exception as e:
                absent[name] = f"{type(e).__name__}: {e}"
    return ScrapeResult(pages, absent)


def endpoints_for_hosts(
    hosts: Iterable[str], port: int, scheme: str = "http"
) -> Dict[str, str]:
    """Convenience: the `{host: url}` target table for a fleet whose
    processes all serve `/metrics` on one `telemetry_port`."""
    return {
        str(h): f"{scheme}://{h}:{int(port)}/metrics" for h in hosts
    }


def counter_total(
    merged: Dict[str, Dict[str, Any]],
    family: str,
    **labels: str,
) -> Optional[Any]:
    """Sum of a merged counter family's samples matching `labels`
    (subset match over the label pairs); None when the family is absent.
    The one-liner tests and dashboards want for 'fleet-wide
    retries_total{action=oom}'."""
    fam = merged.get(family)
    if fam is None:
        return None
    want = set((str(k), str(v)) for k, v in labels.items())
    total: Any = 0
    seen = False
    for lk, v in fam.get("samples", {}).items():
        if want <= set(lk):
            total += v
            seen = True
    return total if seen else None


def merge_pages_from_files(
    paths: Dict[str, str],
) -> Dict[str, Dict[str, Any]]:
    """Merge pages ranks wrote to disk (`{process_name: path}`) — the
    no-network form multi-process CI uses: each rank calls
    `dump_prometheus()` into a shared directory, the controller merges
    after the barrier."""
    pages = {}
    for name in sorted(paths):
        with open(paths[name], "r") as f:
            pages[name] = f.read()
    return merge_prometheus(pages)


def group_postmortems_by_incident(
    base_dirs: Iterable[str],
) -> Dict[str, List[str]]:
    """Group flight-recorder bundles (`postmortem_*` directories under
    each base dir) by the pod incident id in their manifests: one
    rank-loss event makes every survivor dump, so a fleet sum of
    `postmortems_total` counts it N times — grouping per incident id
    restores "one event, one row".  Bundles WITHOUT an incident id
    (ordinary per-process failures) each form their own group, keyed by
    their bundle path; unreadable manifests are skipped.  Returns
    `{group_key: [bundle_dir, ...]}` sorted within each group."""
    groups: Dict[str, List[str]] = {}
    for base in base_dirs:
        for mpath in sorted(
            _glob.glob(os.path.join(str(base), "postmortem_*", "manifest.json"))
        ):
            bdir = os.path.dirname(mpath)
            try:
                with open(mpath, "r") as f:
                    manifest = json.load(f)
            except Exception:
                continue
            key = str(manifest.get("incident_id") or "") or bdir
            groups.setdefault(key, []).append(bdir)
    return {k: sorted(v) for k, v in groups.items()}


__all__ = [
    "ScrapeResult",
    "counter_total",
    "dump_merged",
    "endpoints_for_hosts",
    "group_postmortems_by_incident",
    "merge_pages_from_files",
    "merge_prometheus",
    "scrape_endpoints",
]
