#
# Failure flight recorder — the always-on black box.  Per-fit reports
# (`telemetry_dir`) only exist for fits the operator instrumented ahead
# of time; when an UN-instrumented fit dies, the evidence dies with it.
# The recorder closes that gap: a bounded ring of recent trace events
# (fed by a tracing tap — every span and instant marker, regardless of
# thread), plus rate-limited metric deltas, all O(1) memory.  The typed
# failure paths the resilience layer can classify —
#
#   retry exhaustion      resilience/retry.py `retry_call` (and the
#                         serving dispatcher's inline per-request
#                         budget, serving/server.py)
#   DispatchTimeout       resilience/guard.py watchdog expiry
#   device-loss recovery  resilience/elastic.py `recover_from_device_loss`
#   sustained overload    serving/server.py admission control
#
# — call `note_failure(reason, ...)`, which writes a post-mortem BUNDLE
# (rate-limited per reason) to `flight_recorder_dir` (default:
# `telemetry_dir`):
#
#   manifest.json   reason/detail/time/pid, the run ids seen in the
#                   window, the live solver gauges (which iteration each
#                   in-flight solver had reached), recent metric deltas
#   trace.json      Chrome trace of the last `flight_recorder_window_s`
#                   seconds of ring events — loads in Perfetto next to
#                   any per-fit trace (absolute timestamps align)
#   metrics.prom    full Prometheus snapshot, exemplars included
#   config.json     the effective value of every conf key
#
# Recording must stay cheap enough to leave on under serving traffic:
# one deque append per event plus a 5-second-rate-limited registry
# snapshot; `measure_overhead()` reports the per-event cost and the
# bench `serving` section publishes it.
#
from __future__ import annotations

import collections
import json
import os

from .locks import named_lock
import time
from typing import Any, Deque, Dict, List, Optional

from .registry import REGISTRY, counter, delta

POSTMORTEMS = counter(
    "postmortems_total", "Flight-recorder post-mortem bundles by reason"
)

# seconds between metric-delta snapshots appended to the delta ring
_DELTA_INTERVAL_S = 5.0
# retained metric-delta entries (bounded like the event ring)
_MAX_DELTAS = 64
# conf re-read cadence: the enabled flag / capacity are re-checked every
# this many record() calls so toggling `flight_recorder` takes effect
# without a per-event config-lock acquisition
_CONF_REFRESH_EVENTS = 256
# per-reason dump cooldown: a failure storm (every queued request timing
# out at once) writes ONE bundle, not hundreds
_DUMP_COOLDOWN_S = 30.0


class FlightRecorder:
    """The process-global ring + dump machinery.  Thread-safe; installed
    onto the tracing tap at telemetry import (`install()`)."""

    def __init__(self) -> None:
        # REENTRANT: the tracing tap re-enters record() when the
        # slow-wait instrumentation (telemetry/locks.py) emits an
        # event while this very lock is held — a plain Lock here
        # self-deadlocks the whole trace-emission path
        self._lock = named_lock("flight_recorder", kind="rlock")
        self._ring: Optional[Deque[Any]] = None  # built lazily from conf
        self._deltas: Deque[Dict[str, Any]] = collections.deque(
            maxlen=_MAX_DELTAS
        )
        self._last_snap: Dict[str, Dict[str, Any]] = {}
        self._last_snap_t = 0.0
        self._enabled = True
        self._conf_countdown = 0
        self._last_dump: Dict[str, float] = {}  # reason -> monotonic t
        # pod incidents already dumped by THIS process: one pod-scale
        # event (rank loss detected, then its reduce timing out, then
        # the retry failing) must write one bundle here, not one per
        # typed failure path it cascades through
        self._seen_incidents: Dict[str, float] = {}
        self.cooldown_s = _DUMP_COOLDOWN_S

    # -- recording (the hot path) -------------------------------------------

    def _refresh_conf_locked(self) -> None:
        from ..config import get_config

        self._enabled = str(get_config("flight_recorder")).lower() != "off"
        cap = max(64, int(get_config("flight_recorder_events")))
        if self._ring is None or self._ring.maxlen != cap:
            self._ring = collections.deque(
                self._ring or (), maxlen=cap
            )
        self._conf_countdown = _CONF_REFRESH_EVENTS

    def record(self, event: Any) -> None:
        """Tracing-tap entry point: retain one TraceEvent.  O(1) — a
        deque append; every `_DELTA_INTERVAL_S` it also snapshots the
        registry and keeps the delta (what moved since the last one)."""
        with self._lock:
            if self._conf_countdown <= 0:
                self._refresh_conf_locked()
            self._conf_countdown -= 1
            if not self._enabled:
                return
            self._ring.append(event)
            now = time.time()
            take_snap = now - self._last_snap_t >= _DELTA_INTERVAL_S
            if take_snap:
                self._last_snap_t = now
        if not take_snap:
            return
        # the snapshot walks every registry family: done OUTSIDE the
        # recorder lock so concurrent record() calls never queue on it
        snap = REGISTRY.snapshot()
        with self._lock:
            if self._last_snap:
                d = delta(self._last_snap, snap)
                if d:
                    self._deltas.append({"t": round(now, 3), "delta": d})
            self._last_snap = snap

    # -- queries -------------------------------------------------------------

    def events(self, window_s: Optional[float] = None) -> List[Any]:
        """The retained events, oldest first; `window_s` keeps only the
        last that-many seconds (by span END time, so a long span still
        in its window survives)."""
        with self._lock:
            evs = list(self._ring or ())
        if window_s is not None:
            cutoff = time.time() - float(window_s)
            evs = [e for e in evs if max(e.t0, e.t1) >= cutoff]
        return evs

    def metric_deltas(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(d) for d in self._deltas]

    def clear(self) -> None:
        """Tests / operator reset: drop the retained history (the
        registry itself is untouched)."""
        with self._lock:
            if self._ring is not None:
                self._ring.clear()
            self._deltas.clear()
            self._last_snap = {}
            self._last_snap_t = 0.0
            self._last_dump.clear()
            self._seen_incidents.clear()

    # -- dumping -------------------------------------------------------------

    def _bundle_dir(self) -> str:
        from ..config import get_config

        return str(
            get_config("flight_recorder_dir")
            or get_config("telemetry_dir")
            or ""
        )

    def note_failure(
        self, reason: str, detail: str = "",
        log: Optional[object] = None,
        attachments: Optional[Dict[str, Any]] = None,
        incident_id: str = "",
    ) -> Optional[str]:
        """A typed failure path fired: write a post-mortem bundle
        (rate-limited — one per `reason` per cooldown window) and return
        its directory, or None when skipped (cooldown, recorder off, no
        destination configured).  `attachments` adds caller evidence to
        the bundle (the drift monitor ships both distribution
        fingerprints + the divergence table): `bytes` values write
        verbatim under their key, anything else as `<key>.json`.
        `incident_id` marks a pod-scale event (telemetry/fleet.py mints
        one deterministic id per incident): it lands in the manifest so
        fleet aggregation can group the pod's bundles per incident, and
        this process dedupes on it — the same incident cascading
        through several typed failure paths writes ONE bundle.  NEVER
        raises: the black box must not add a second failure to the one
        being recorded."""
        prev = None
        claimed = False
        inc_claimed = False
        try:
            with self._lock:
                if self._conf_countdown <= 0:
                    self._refresh_conf_locked()
                if not self._enabled:
                    return None
                now = time.monotonic()
                if incident_id and incident_id in self._seen_incidents:
                    return None
                prev = self._last_dump.get(reason)
                if prev is not None and now - prev < self.cooldown_s:
                    return None
                # claim the cooldown slot BEFORE the (unlocked) dump so
                # a concurrent storm writes one bundle, not N...
                self._last_dump[reason] = now
                claimed = True
                if incident_id:
                    self._seen_incidents[incident_id] = now
                    inc_claimed = True
            bdir = self.dump(reason, detail, log=log,
                             attachments=attachments,
                             incident_id=incident_id)
            if bdir is None:
                # ...but a dump that wrote NOTHING (no destination
                # configured yet) must not burn the slot: the operator
                # who sets flight_recorder_dir after the first failure
                # still gets a bundle from the next one
                with self._lock:
                    if claimed:
                        if prev is None:
                            self._last_dump.pop(reason, None)
                        else:
                            self._last_dump[reason] = prev
                    if inc_claimed:
                        self._seen_incidents.pop(incident_id, None)
            return bdir
        except Exception as e:  # pragma: no cover - defensive
            with self._lock:
                if claimed:
                    if prev is None:
                        self._last_dump.pop(reason, None)
                    else:
                        self._last_dump[reason] = prev
                if inc_claimed:
                    self._seen_incidents.pop(incident_id, None)
            _warn(log, f"flight-recorder dump failed "
                       f"({type(e).__name__}: {e})")
            return None

    def dump(
        self, reason: str, detail: str = "",
        log: Optional[object] = None,
        attachments: Optional[Dict[str, Any]] = None,
        incident_id: str = "",
    ) -> Optional[str]:
        """Write the bundle unconditionally (no cooldown — operator/test
        entry point).  Returns the bundle directory, or None when no
        destination is configured."""
        from ..config import config_snapshot, get_config

        base = self._bundle_dir()
        if not base:
            _warn(
                log,
                f"flight recorder has a '{reason}' post-mortem to write "
                "but neither flight_recorder_dir nor telemetry_dir is "
                "set; the in-memory ring stays queryable",
            )
            return None
        window_s = float(get_config("flight_recorder_window_s"))
        evs = self.events(window_s=window_s)
        stamp = time.strftime("%Y%m%d_%H%M%S")
        bdir = os.path.join(
            base, f"postmortem_{reason}_{stamp}_{os.getpid()}"
        )
        n = 0
        while os.path.exists(bdir):  # same reason+second: suffix
            n += 1
            bdir = os.path.join(
                base, f"postmortem_{reason}_{stamp}_{os.getpid()}.{n}"
            )
        os.makedirs(bdir)
        from .exporters import chrome_trace, dump_prometheus

        with open(os.path.join(bdir, "trace.json"), "w") as f:
            json.dump(chrome_trace(events=evs), f)
        with open(os.path.join(bdir, "metrics.prom"), "w") as f:
            f.write(dump_prometheus(exemplars=True))
        with open(os.path.join(bdir, "config.json"), "w") as f:
            json.dump(config_snapshot(), f, indent=1, default=str)
        attached = []
        for key in sorted(attachments or {}):
            val = (attachments or {})[key]
            if isinstance(val, (bytes, bytearray)):
                fname = key
                with open(os.path.join(bdir, fname), "wb") as f:
                    f.write(val)
            else:
                fname = f"{key}.json"
                with open(os.path.join(bdir, fname), "w") as f:
                    json.dump(val, f, indent=1, default=str)
            attached.append(fname)
        manifest = {
            "reason": reason,
            "detail": detail,
            "t": round(time.time(), 3),
            "pid": os.getpid(),
            "window_s": window_s,
            "n_events": len(evs),
            "run_ids": sorted({e.run_id for e in evs if e.run_id}),
            "solver_state": _solver_state(),
            "metric_deltas": self.metric_deltas(),
            **({"incident_id": incident_id} if incident_id else {}),
            **({"attachments": attached} if attached else {}),
        }
        with open(os.path.join(bdir, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=1)
        POSTMORTEMS.inc(reason=reason)
        _warn(
            log,
            f"flight recorder: '{reason}' post-mortem bundle written to "
            f"{bdir} ({len(evs)} event(s), "
            f"{len(manifest['run_ids'])} run(s))",
        )
        return bdir


def _solver_state() -> Dict[str, Any]:
    """The live solver-progress gauges at dump time: which iteration
    each still-open solver loop had reached (a COMPLETED fit's heartbeat
    closed and removed its series — see Heartbeat.close)."""
    out: Dict[str, Any] = {}
    for fam in ("solver_iteration", "solver_loss"):
        m = REGISTRY.get(fam)
        if m is None:
            continue
        out[fam] = {
            ",".join(f"{k}={v}" for k, v in lk): val
            for lk, val in m.samples().items()
        }
    return out


def _warn(log: Optional[object], msg: str) -> None:
    if log is None:
        from ..utils import get_logger

        log = get_logger("spark_rapids_ml_tpu.telemetry")
    log.warning(msg)


# the process-global recorder every failure hook talks to
RECORDER = FlightRecorder()

_installed = False
_install_lock = named_lock("flight_recorder_install")


def install() -> FlightRecorder:
    """Hook the recorder onto the tracing tap (idempotent).  Called at
    telemetry import, so the ring is recording before the first fit."""
    global _installed
    with _install_lock:
        if not _installed:
            from ..tracing import add_trace_tap

            add_trace_tap(RECORDER.record)
            _installed = True
    return RECORDER


def note_failure(
    reason: str, detail: str = "", log: Optional[object] = None,
    attachments: Optional[Dict[str, Any]] = None,
    incident_id: str = "",
) -> Optional[str]:
    """Module-level convenience over `RECORDER.note_failure` — the one
    call the failure hooks (retry exhaustion, DispatchTimeout,
    device-loss recovery, sustained overload, sustained drift, pod rank
    loss) make."""
    return RECORDER.note_failure(reason, detail, log=log,
                                 attachments=attachments,
                                 incident_id=incident_id)


def measure_overhead(n: int = 2000) -> float:
    """Measured per-event recording cost in MICROSECONDS: pushes `n`
    synthetic events through a THROWAWAY FlightRecorder (same code
    path, same conf reads) and returns the mean.  The bench `serving`
    section reports this next to the QPS numbers, so 'request tracing
    ON' stays an accounted cost, not an article of faith.  The live
    RECORDER ring is untouched — flooding the real black box with 2000
    probe events would evict exactly the recent history a post-mortem
    exists to keep."""
    from ..tracing import TraceEvent

    now = time.time()
    ev = TraceEvent(
        "flight_recorder_probe", 0.0, 0, t0=now, t1=now, kind="instant"
    )
    probe = FlightRecorder()
    t0 = time.perf_counter()
    for _ in range(n):
        probe.record(ev)
    return (time.perf_counter() - t0) / n * 1e6


__all__ = [
    "FlightRecorder",
    "RECORDER",
    "install",
    "measure_overhead",
    "note_failure",
]
