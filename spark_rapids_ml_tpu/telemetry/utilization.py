#
# Device idle-gap attribution — the time half of the progress
# observatory.  The overlap numbers the perf PRs live on (fused
# stage-and-solve, the statistic-program engine, the staging pipeline)
# were each computed ad hoc from their own interval lists; this module
# generalizes that interval-intersection math (fused._interval_overlap_s)
# into ONE utilization timeline per run:
#
#   note_interval(kind, t0, t1, cause)   producers append labeled
#       wall-clock intervals — "device" (the chip had work), "host_prep"
#       (chunk decode/pad/cast), "stage" (host->device transfers),
#       "dispatch"/"collect" (serving aggregate phases) with
#       "compute"/"scatter" sub-windows from the staged dispatch
#       pipeline, "lock_wait" (contended named-lock acquires,
#       telemetry/locks.py)
#
#   summarize(run_id=..., window_s=...)   folds them into
#       `device_busy_fraction` plus a RANKED gap-attribution table: the
#       complement of the device-busy union is the idle time, and each
#       gap second is attributed to whichever non-device activity
#       covered it (top causes by stolen seconds, residual reported as
#       `unattributed`).
#
# Consumers: the fit report's new `utilization` section
# (telemetry/report.py), `ServingServer.report()`'s `_totals`
# utilization block, and the bench `utilization` section.  The
# `device_busy_fraction{scope}` gauge feeds the planned SLO controller
# (ROADMAP item 2) its missing utilization sensor.
#
# Timestamps are `time.perf_counter()` values (the clock every existing
# interval producer already uses — monotonic, cross-thread comparable on
# this platform).  Storage is one bounded process-global deque;
# `collections.deque.append` is GIL-atomic, so producers pay no lock.
#
from __future__ import annotations

import collections
import time
from typing import Any, Dict, List, Optional, Tuple

from .registry import gauge

# interval kinds producers may record; "device" is the busy series the
# gaps are measured against, everything else is attribution evidence.
# "dispatch"/"collect" are the serving pipeline's aggregate phases;
# "stage"/"compute"/"scatter" are its finer-grained sub-windows (the
# depth-tuning evidence: stage/compute stealing gap seconds means a
# deeper `serving_pipeline_depth` pays, scatter stealing means the
# collect worker is the bottleneck)
KINDS = (
    "device",
    "host_prep",
    "stage",
    "compute",
    "dispatch",
    "collect",
    "scatter",
    "lock_wait",
    # bounded cross-process waits (resilience/pod.py kv_wait): time a
    # rank spent parked on a peer's KV payload — the pod-scale analog of
    # lock_wait, cause carries "<reduce tag>:rank<peer>"
    "reduce_wait",
)

# retained intervals, process-wide: at fused-chunk granularity this is
# hours of history; serving batches recycle it faster but a report only
# ever looks at one run / one window
_MAX_INTERVALS = 8192

# (run_id, kind, cause, t0, t1) in perf_counter seconds
_intervals: collections.deque = collections.deque(maxlen=_MAX_INTERVALS)

_busy_gauge = gauge(
    "device_busy_fraction",
    "Fraction of the observed wall the device was busy, by scope",
)

# gap-attribution rows reported per summary
_TOP_CAUSES = 8


def note_interval(
    kind: str,
    t0: float,
    t1: float,
    cause: str = "",
    run_id: Optional[str] = None,
    domain: str = "fit",
) -> None:
    """Record one labeled wall-clock interval (perf_counter endpoints).
    `run_id` defaults to the thread's active run (tracing.run_context);
    an empty run id still lands in window-scoped summaries.  `domain`
    scopes window summaries: "fit" (default — staging/fused/solver
    producers), "serving" (the dispatcher's windows), or "any" (lock
    waits, which belong to whichever view asks).  Cheap and lock-free
    (one deque append); never raises."""
    if t1 <= t0:
        return
    try:
        if run_id is None:
            from ..tracing import current_run_id

            run_id = current_run_id()
        _intervals.append(
            (run_id or "", kind, cause, float(t0), float(t1), domain)
        )
    except Exception:
        pass


def note_intervals(
    kind: str,
    intervals,
    cause: str = "",
    run_id: Optional[str] = None,
    domain: str = "fit",
) -> None:
    """Bulk form for producers that already hold an interval list (the
    fused engine's per-pass prep/accumulate windows): intervals are
    coalesced FIRST so a 10k-chunk pass lands as a handful of merged
    spans, not 10k deque entries."""
    for lo, hi in merge_intervals(list(intervals)):
        note_interval(kind, lo, hi, cause=cause, run_id=run_id,
                      domain=domain)


def clear() -> None:
    """Tests / operator reset: drop the retained timeline."""
    _intervals.clear()


# ---------------------------------------------------------------------------
# Interval math (the PR-8 primitives, promoted to the shared surface)
# ---------------------------------------------------------------------------


def merge_intervals(iv: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    """Sort + coalesce possibly-overlapping intervals into a disjoint
    sorted list."""
    if not iv:
        return []
    iv = sorted(iv)
    out = [list(iv[0])]
    for lo, hi in iv[1:]:
        if lo <= out[-1][1]:
            out[-1][1] = max(out[-1][1], hi)
        else:
            out.append([lo, hi])
    return [(lo, hi) for lo, hi in out]


def interval_overlap_s(
    a: List[Tuple[float, float]], b: List[Tuple[float, float]]
) -> float:
    """Total length of the pairwise intersection of two sorted disjoint
    interval lists — how long both sides were simultaneously active."""
    total = 0.0
    i = j = 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


def complement(
    busy: List[Tuple[float, float]], lo: float, hi: float
) -> List[Tuple[float, float]]:
    """The gaps: [lo, hi] minus the (disjoint, sorted) busy intervals."""
    gaps: List[Tuple[float, float]] = []
    cur = lo
    for b0, b1 in busy:
        if b0 > cur:
            gaps.append((cur, min(b0, hi)))
        cur = max(cur, b1)
        if cur >= hi:
            break
    if cur < hi:
        gaps.append((cur, hi))
    return [(a, b) for a, b in gaps if b > a]


def _total(iv: List[Tuple[float, float]]) -> float:
    return sum(hi - lo for lo, hi in iv)


# ---------------------------------------------------------------------------
# Summaries
# ---------------------------------------------------------------------------


def timeline(
    run_id: Optional[str] = None,
    window_s: Optional[float] = None,
    domain: Optional[str] = None,
) -> List[tuple]:
    """The retained intervals, filtered by run, trailing window and/or
    domain ("any"-domain intervals — lock waits — match every domain).
    Window-filtered intervals are CLIPPED to the window start, so one
    long span ending just now cannot stretch the observed wall far past
    the window."""
    evs = list(_intervals)
    if run_id is not None:
        evs = [e for e in evs if e[0] == run_id]
    if domain is not None:
        evs = [e for e in evs if e[5] in (domain, "any")]
    if window_s is not None:
        cutoff = time.perf_counter() - float(window_s)
        evs = [
            e if e[3] >= cutoff
            else (e[0], e[1], e[2], cutoff, e[4], e[5])
            for e in evs
            if e[4] >= cutoff
        ]
    return evs


def summarize(
    run_id: Optional[str] = None,
    window_s: Optional[float] = None,
    scope: str = "",
    domain: Optional[str] = None,
) -> Dict[str, Any]:
    """Fold the selected intervals into the utilization verdict:

    - `device_busy_fraction` = |union of device intervals| / observed wall
    - `gap_attribution`: ranked causes of the idle gaps — for each
      (kind, cause) series, how many gap seconds it covered ("stolen"),
      plus the `unattributed` residual no recorded activity explains.

    A cause can "steal" the same gap second another cause also covers
    (host prep and a lock wait can genuinely co-occur), so attribution
    rows may sum past `gap_s`; the residual uses the UNION of all
    non-device activity and is exact.  Returns {} when nothing was
    recorded.  `scope` additionally publishes the fraction on the
    `device_busy_fraction{scope}` gauge."""
    evs = timeline(run_id=run_id, window_s=window_s, domain=domain)
    if not evs:
        if scope:
            # the busy gauge must not report the last burst forever
            # once every interval ages out of the window — an idle
            # device reads as NO series, not as hours-stale "93% busy"
            _busy_gauge.remove(scope=scope)
        return {}
    lo = min(e[3] for e in evs)
    hi = max(e[4] for e in evs)
    wall = hi - lo
    if wall <= 0:
        return {}
    by_series: Dict[Tuple[str, str], List[Tuple[float, float]]] = {}
    device: List[Tuple[float, float]] = []
    for _rid, kind, cause, t0, t1, _domain in evs:
        if kind == "device":
            device.append((t0, t1))
        else:
            by_series.setdefault((kind, cause), []).append((t0, t1))
    busy = merge_intervals(device)
    busy_s = _total(busy)
    gaps = complement(busy, lo, hi)
    gap_s = _total(gaps)
    rows: List[Dict[str, Any]] = []
    non_device_union: List[Tuple[float, float]] = []
    for (kind, cause), iv in by_series.items():
        merged = merge_intervals(iv)
        non_device_union.extend(merged)
        stolen = interval_overlap_s(gaps, merged)
        if stolen <= 0:
            continue
        rows.append({
            "kind": kind,
            **({"cause": cause} if cause else {}),
            "stolen_s": round(stolen, 4),
            "active_s": round(_total(merged), 4),
        })
    rows.sort(key=lambda r: -r["stolen_s"])
    attributed = interval_overlap_s(gaps, merge_intervals(non_device_union))
    fraction = max(0.0, min(busy_s / wall, 1.0))
    out: Dict[str, Any] = {
        "wall_s": round(wall, 4),
        "device_busy_s": round(busy_s, 4),
        "device_busy_fraction": round(fraction, 4),
        "gap_s": round(gap_s, 4),
        "gap_attribution": rows[:_TOP_CAUSES],
        "unattributed_s": round(max(gap_s - attributed, 0.0), 4),
    }
    if scope:
        _busy_gauge.set(out["device_busy_fraction"], scope=scope)
    return out


# the package-facade name (tracing has its own `summarize`)
summarize_utilization = summarize

__all__ = [
    "KINDS",
    "summarize_utilization",
    "clear",
    "complement",
    "interval_overlap_s",
    "merge_intervals",
    "note_interval",
    "note_intervals",
    "summarize",
    "timeline",
]
