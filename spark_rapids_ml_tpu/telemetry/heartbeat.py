#
# Progress heartbeat for long iterative solvers — the KMeans Lloyd,
# L-BFGS/OWL-QN, FISTA and epoch-streaming loops can run for hours at
# beyond-HBM scale with nothing on the controller log between "fit
# started" and the result.  A `Heartbeat` beats once per solver
# iteration: every beat updates the progress gauges (queryable live via
# the `telemetry_port` endpoint), and every `heartbeat_interval_s`
# seconds one INFO line lands in the log with the iteration, the current
# loss and the iteration throughput.  `heartbeat_interval_s <= 0`
# silences the log line; the gauges still track.
#
from __future__ import annotations

import threading

from .locks import named_lock
import time
from typing import Any, Optional

from .registry import gauge

_iter_gauge = gauge(
    "solver_iteration", "Current iteration of the running solver loop"
)
_loss_gauge = gauge(
    "solver_loss", "Current loss/objective of the running solver loop"
)

# the most recent Heartbeat per label OWNS the gauge series: close()
# only removes the series while its caller is still the owner, so a
# fit completing while ANOTHER fit of the same solver type is mid-loop
# (parallel CV, tuning) cannot erase the live fit's state from a
# flight-recorder post-mortem — and an interrupted loop's abandoned
# heartbeat (device-loss resume creates a fresh one) never blocks the
# resumed loop's close from end-marking.  Bounded by the solver-label
# vocabulary (METRIC_CATALOG cardinality 16).
_owners_lock = named_lock("heartbeat_owners")
_owners: dict = {}


class Heartbeat:
    """Per-solver-loop progress reporter.  Construct once before the
    loop, call `beat(it, loss=...)` once per iteration.

    `label` names the solver (`kmeans_lloyd`, `lbfgs`, ...), `total` the
    iteration bound when known.  The interval defaults to the
    `heartbeat_interval_s` conf, read at construction so a long fit
    honors the setting it started under."""

    def __init__(
        self,
        label: str,
        total: Optional[int] = None,
        log: Optional[object] = None,
        interval: Optional[float] = None,
    ) -> None:
        from ..config import get_config

        self.label = label
        self.total = int(total) if total else None
        self.interval = (
            float(get_config("heartbeat_interval_s"))
            if interval is None
            else float(interval)
        )
        if log is None:
            from ..utils import get_logger

            log = get_logger("spark_rapids_ml_tpu.telemetry")
        self.log = log
        self._t0 = time.monotonic()
        self._last = self._t0
        self._first_it: Optional[int] = None  # resumed loops start at k>0
        self._lock = threading.Lock()
        self._closed = False
        with _owners_lock:
            _owners[self.label] = self

    def beat(self, it: int, loss: Any = None, detail: str = "") -> None:
        """Record one completed iteration.  Cheap when quiet: two gauge
        writes and a monotonic read."""
        it = int(it)
        _iter_gauge.set(it, solver=self.label)
        if loss is not None:
            try:
                _loss_gauge.set(float(loss), solver=self.label)
            except (TypeError, ValueError):
                pass  # non-scalar diagnostics never break the solver
        # the solver loop is where mid-fit HBM peaks live (accumulators,
        # line-search temporaries); rate-limited so a fast loop pays one
        # sample per interval, not per iteration
        from .memory import maybe_sample

        maybe_sample()
        if self.interval <= 0:
            return
        now = time.monotonic()
        with self._lock:
            if self._first_it is None:
                self._first_it = it
            if now - self._last < self.interval:
                return
            self._last = now
            done = it - self._first_it + 1
            rate = done / max(now - self._t0, 1e-9)
        bound = f"/{self.total}" if self.total else ""
        try:
            # same tolerance as the gauge above: a non-scalar diagnostic
            # must not crash the solver from inside its progress log
            loss_s = "" if loss is None else f" loss={float(loss):.6g}"
        except (TypeError, ValueError):
            loss_s = ""
        extra = f" {detail}" if detail else ""
        self.log.info(
            f"[heartbeat] {self.label}: it={it}{bound}{loss_s} "
            f"({rate:.2f} it/s){extra}"
        )
        from ..tracing import event

        # an instant marker too, so long solves show their pulse on the
        # Chrome-trace marker track
        event(
            f"heartbeat[{self.label}]",
            detail=f"it={it}{bound}{loss_s}".strip(),
        )

    def close(self) -> None:
        """End-mark the solver: REMOVE this label's
        `solver_iteration`/`solver_loss` samples so a scrape after the
        fit completes shows no live series for it.  Without this the
        gauges keep reporting the LAST iteration/loss forever and a
        finished fit is indistinguishable from a running one.  Solver
        loops call it on normal completion only — a fit that dies
        mid-loop deliberately leaves its last state visible for the
        flight recorder's post-mortem bundle.  Idempotent.

        Only the CURRENT owner of the label's series removes it: a
        concurrent fit of the same solver type that beat more recently
        keeps its state (its next beat re-sets the gauges anyway)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        with _owners_lock:
            if _owners.get(self.label) is not self:
                return  # a newer loop owns the series; leave it live
            del _owners[self.label]
        _iter_gauge.remove(solver=self.label)
        _loss_gauge.remove(solver=self.label)

    def __enter__(self) -> "Heartbeat":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        # as a context manager the gauges clear on ANY exit; the bare
        # construct-and-close form keeps the die-mid-loop state visible
        self.close()


__all__ = ["Heartbeat"]
