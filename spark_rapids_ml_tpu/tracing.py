#
# Tracing / profiling — the analog of the reference's observability tier
# (cuML verbose levels 0-6 routed to executors, reference core.py:413-436;
# per-stage wall-clock logs in ANN, knn.py:1571-1687; benchmark
# `with_benchmark` wrappers).  Two mechanisms:
#
#   - `trace(stage)`: a nestable per-process stage timer.  Events are
#     recorded in-process (inspect with `get_trace_events` / `summarize`);
#     at `verbose >= 1` each stage logs its wall-clock on exit, giving the
#     per-stage timing breakdown the reference's verbose levels provide.
#   - `profile_dir` config: when set, fits run under `jax.profiler.trace`,
#     producing a TensorBoard/XProf trace of the actual device execution —
#     the TPU-native deep-profiling path (there is no cuML logger to
#     forward to; XLA's profiler is strictly more detailed).
#
from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass
from typing import Callable, Iterator, List, Optional

from .config import get_config
from .utils import get_logger

logger = get_logger("spark_rapids_ml_tpu.tracing")

_tls = threading.local()

# bounded event history per thread: long-lived serving processes transform
# repeatedly and must not grow memory without bound
MAX_EVENTS = 4096


@dataclass
class TraceEvent:
    name: str
    seconds: float
    depth: int
    # instantaneous events (retries, injected faults, dispatch timeouts —
    # resilience/) carry their context here; timed stages leave it empty
    detail: str = ""


def _records() -> List[TraceEvent]:
    rec = getattr(_tls, "records", None)
    if rec is None:
        rec = _tls.records = []
    return rec


def _append(event: TraceEvent) -> None:
    rec = _records()
    if len(rec) >= MAX_EVENTS:
        del rec[: MAX_EVENTS // 2]  # drop the oldest half
    rec.append(event)


def get_trace_events() -> List[TraceEvent]:
    """Events recorded on this thread since the last `reset_trace`."""
    return list(_records())


def adopt_trace_context() -> Callable[[], None]:
    """Capture this thread's trace buffer and depth for adoption by a
    worker thread (resilience/guard.py): the returned thunk, called on the
    worker, makes its trace()/event() calls land in the CALLER's record
    list.  Without this the watchdog thread's thread-local storage
    swallows every event recorded inside a guarded dispatch.  list.append
    is atomic under the GIL, so a caller reading while an abandoned worker
    still appends is safe."""
    rec = _records()
    depth = getattr(_tls, "depth", 0)

    def _adopt() -> None:
        _tls.records = rec
        _tls.depth = depth

    return _adopt


def reset_trace() -> None:
    _records().clear()


def summarize() -> str:
    """Indented per-stage timing table for the recorded events."""
    lines = [
        f"{'  ' * e.depth}{e.name}: {e.seconds:.4f}s"
        + (f" [{e.detail}]" if e.detail else "")
        for e in _records()
    ]
    return "\n".join(lines)


def event(name: str, detail: str = "", log: Optional[object] = None) -> None:
    """Record an INSTANTANEOUS event (zero-duration TraceEvent) — failure/
    recovery markers from the resilience layer: retries, injected faults,
    dispatch timeouts, checkpoint resumes.  Always logged at `verbose >= 1`
    like timed stages."""
    depth = getattr(_tls, "depth", 0)
    _append(TraceEvent(name, 0.0, depth, detail))
    if int(get_config("verbose") or 0) >= 1:
        suffix = f" [{detail}]" if detail else ""
        (log or logger).info(f"[trace] {'  ' * depth}{name}{suffix}")


@contextlib.contextmanager
def trace(name: str, log: Optional[object] = None) -> Iterator[None]:
    """Time a stage.  Nested stages indent; `verbose >= 1` logs on exit."""
    depth = getattr(_tls, "depth", 0)
    _tls.depth = depth + 1
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        _tls.depth = depth
        _append(TraceEvent(name, dt, depth))
        if int(get_config("verbose") or 0) >= 1:
            (log or logger).info(f"[trace] {'  ' * depth}{name}: {dt:.4f}s")


_profile_lock = threading.Lock()
_profile_active = False


@contextlib.contextmanager
def device_profile() -> Iterator[None]:
    """Wrap a region in `jax.profiler.trace` when `profile_dir` is set —
    the XLA/TPU execution profile (TensorBoard `xprof` format).  The jax
    profiler is process-global, so concurrent fits (fitMultiple consumers)
    share one trace: only the first caller starts/stops it."""
    global _profile_active
    profile_dir = get_config("profile_dir")
    if not profile_dir:
        yield
        return
    with _profile_lock:
        owner = not _profile_active
        if owner:
            import jax

            jax.profiler.start_trace(str(profile_dir))
            _profile_active = True
    try:
        yield
    finally:
        if owner:
            with _profile_lock:
                # only stop a trace that actually started: if start_trace
                # raised, _profile_active never became True and calling
                # stop_trace would mask the original error
                if _profile_active:
                    import jax

                    jax.profiler.stop_trace()
                    _profile_active = False
                    logger.info(f"Wrote device profile to {profile_dir}")
