#
# Tracing / profiling — the analog of the reference's observability tier
# (cuML verbose levels 0-6 routed to executors, reference core.py:413-436;
# per-stage wall-clock logs in ANN, knn.py:1571-1687; benchmark
# `with_benchmark` wrappers).  Two mechanisms:
#
#   - `trace(stage)`: a nestable per-process stage timer recording SPANS —
#     absolute t0/t1 timestamps, the recording thread id, and the active
#     `run_id` (minted per fit/transform by core.py) — so a degraded-mesh
#     CV run can be reconstructed after the fact.  Events are recorded
#     in-process (inspect with `get_trace_events` / `summarize`); at
#     `verbose >= 1` each stage logs its wall-clock on exit.  The
#     telemetry exporters (telemetry/exporters.py) render the recorded
#     spans as Chrome trace-event JSON (one track per thread, instant
#     markers on their own track — loads in Perfetto).
#   - `profile_dir` config: when set, fits run under `jax.profiler.trace`,
#     producing a TensorBoard/XProf trace of the actual device execution —
#     the TPU-native deep-profiling path (there is no cuML logger to
#     forward to; XLA's profiler is strictly more detailed).
#
from __future__ import annotations

import contextlib
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Callable, Iterator, List, Optional

from .config import get_config
from .utils import get_logger

logger = get_logger("spark_rapids_ml_tpu.tracing")

_tls = threading.local()

# bounded event history per thread: long-lived serving processes transform
# repeatedly and must not grow memory without bound
MAX_EVENTS = 4096


@dataclass
class TraceEvent:
    name: str
    seconds: float
    depth: int
    # instantaneous events (retries, injected faults, dispatch timeouts —
    # resilience/) carry their context here; timed stages leave it empty
    detail: str = ""
    # -- span fields (this PR): correlation + absolute placement ----------
    t0: float = 0.0  # absolute start, epoch seconds (time.time clock)
    t1: float = 0.0  # absolute end; == t0 for instant events
    thread_id: int = 0  # threading.get_ident() of the recording thread
    run_id: str = ""  # the fit/transform run this event belongs to
    kind: str = "span"  # "span" (timed stage) | "instant" (marker)
    # the pod-global pass id active when the event was recorded
    # (telemetry/fleet.py: rank 0 mints it at begin_pass and broadcasts
    # over the KV seam, so the SAME id lands on every rank's spans) —
    # the cross-rank correlation key a merged pod trace is joined on
    pass_id: str = ""


# every thread's record list, registered once at creation so the
# telemetry exporters can merge a PROCESS-wide view (the lists themselves
# stay thread-local for lock-free appends; list.append is atomic under
# the GIL).  Worker threads that adopt a caller's buffer share its
# already-registered list — no duplicate registration.  Entries hold a
# WEAK reference to the recording thread and are pruned (lazily, on the
# next registration) once that thread is gone: a thread-per-request
# service must not accumulate dead buffers — and their MAX_EVENTS of
# history — forever.
_buffers_lock = threading.Lock()
_buffers: List[tuple] = []  # (thread_name, weakref-to-thread, records)


def _records() -> List[TraceEvent]:
    rec = getattr(_tls, "records", None)
    if rec is None:
        import weakref

        rec = _tls.records = []
        t = threading.current_thread()
        with _buffers_lock:
            _buffers[:] = [b for b in _buffers if b[1]() is not None]
            _buffers.append((t.name, weakref.ref(t), rec))
    return rec


# process-wide observers of EVERY recorded event, regardless of which
# thread's buffer it lands in — the flight recorder's feed
# (telemetry/flight_recorder.py).  Registration is rare (guarded by
# _buffers_lock); the hot-path iteration reads the list lock-free
# (list object replaced atomically on registration, append-only reads).
_taps: List[Callable[[TraceEvent], None]] = []


def add_trace_tap(fn: Callable[[TraceEvent], None]) -> None:
    """Register `fn` to observe every TraceEvent recorded by any thread
    of this process (spans on exit, instants immediately).  Idempotent.
    A tap must be cheap and never raise — it runs inline on the
    recording thread."""
    global _taps
    with _buffers_lock:
        if fn not in _taps:
            _taps = _taps + [fn]


def remove_trace_tap(fn: Callable[[TraceEvent], None]) -> None:
    global _taps
    with _buffers_lock:
        _taps = [t for t in _taps if t is not fn]


def _append(event: TraceEvent) -> None:
    rec = _records()
    if len(rec) >= MAX_EVENTS:
        del rec[: MAX_EVENTS // 2]  # drop the oldest half
    rec.append(event)
    for tap in _taps:
        try:
            tap(event)
        except Exception:  # a broken observer must never fail the span
            pass


def get_trace_events() -> List[TraceEvent]:
    """Events recorded on this thread since the last `reset_trace`."""
    return list(_records())


def get_all_trace_events(run_id: Optional[str] = None) -> List[TraceEvent]:
    """Events recorded on EVERY thread of this process, in start order
    (parents sort before their children).  `run_id` filters to one
    fit/transform run.  This is the exporters' view: a guarded dispatch's
    worker thread adopts its caller's buffer, so cross-thread spans of
    one run appear exactly once."""
    with _buffers_lock:
        bufs = [rec for _, _, rec in _buffers]
    seen = set()
    events: List[TraceEvent] = []
    for rec in bufs:
        if id(rec) in seen:  # adopted buffers are shared, not duplicated
            continue
        seen.add(id(rec))
        events.extend(list(rec))
    if run_id is not None:
        events = [e for e in events if e.run_id == run_id]
    # (t0, -t1): a parent starts no later than its children and ends no
    # earlier, so ties break parent-first
    events.sort(key=lambda e: (e.t0, -e.t1))
    return events


# ---------------------------------------------------------------------------
# Run correlation — one id per fit/transform
# ---------------------------------------------------------------------------

# the pod-global pass id (telemetry/fleet.py begin_pod_pass): PROCESS-
# global, not thread-local — the producer/prefetch threads of a fused
# pass must stamp the same id as the consumer that minted it.  A str
# assignment is GIL-atomic, so readers never need the lock.
_current_pass_id = ""


def current_pass_id() -> str:
    """The pod-global pass id active in this process ('' outside any
    pod-correlated pass)."""
    return _current_pass_id


def set_current_pass_id(pass_id: str) -> None:
    """Install (or clear, with '') the process-global pass id every
    subsequently recorded span/instant is stamped with.  Called by
    telemetry/fleet.py at begin/complete of a pod-correlated pass."""
    global _current_pass_id
    _current_pass_id = str(pass_id or "")


def mint_run_id(prefix: str = "run") -> str:
    """A fresh globally-unique run id (`<prefix>-<12 hex>`); core.py
    mints one per fit/transform so retries, device-loss recoveries and
    checkpoint resumes stamp the run they interrupted."""
    return f"{prefix}-{uuid.uuid4().hex[:12]}"


def current_run_id() -> str:
    """The run id active on this thread ('' outside any run)."""
    return getattr(_tls, "run_id", "")


@contextlib.contextmanager
def run_context(
    run_id: Optional[str] = None, prefix: str = "run"
) -> Iterator[str]:
    """Scope a run id onto this thread: every span/event recorded inside
    carries it.  Nests — an inner fit's run restores the outer run on
    exit.  `run_id=None` mints a fresh id."""
    rid = run_id or mint_run_id(prefix)
    prev = getattr(_tls, "run_id", "")
    _tls.run_id = rid
    try:
        yield rid
    finally:
        _tls.run_id = prev


def adopt_trace_context() -> Callable[[], None]:
    """Capture this thread's trace buffer, depth AND run id for adoption
    by a worker thread (resilience/guard.py): the returned thunk, called
    on the worker, makes its trace()/event() calls land in the CALLER's
    record list, at the caller's depth, stamped with the caller's run —
    so a watchdog-guarded dispatch's stage timings and resilience markers
    correlate with the fit that issued it.  Without this the watchdog
    thread's thread-local storage swallows every event recorded inside a
    guarded dispatch.  list.append is atomic under the GIL, so a caller
    reading while an abandoned worker still appends is safe."""
    rec = _records()
    depth = getattr(_tls, "depth", 0)
    run_id = getattr(_tls, "run_id", "")
    # compile-event attribution rides along: a dispatch's XLA compiles
    # happen on the worker thread, but they belong to the caller's label
    # scope (telemetry/compile.py)
    from .telemetry.compile import adopt_labels, snapshot_labels

    labels = snapshot_labels()

    def _adopt() -> None:
        _tls.records = rec
        _tls.depth = depth
        _tls.run_id = run_id
        adopt_labels(labels)

    return _adopt


def reset_trace() -> None:
    _records().clear()


def summarize() -> str:
    """Indented per-stage timing table for the recorded events, rendered
    in START order (each span carries its t0): a parent prints before its
    children and siblings print in execution order.  Events used to
    append on stage EXIT, which printed nested stages before their
    parents and interleaved siblings misleadingly."""
    events = sorted(_records(), key=lambda e: (e.t0, -e.t1))
    lines = [
        f"{'  ' * e.depth}{e.name}: {e.seconds:.4f}s"
        + (f" [{e.detail}]" if e.detail else "")
        for e in events
    ]
    return "\n".join(lines)


def event(name: str, detail: str = "", log: Optional[object] = None) -> None:
    """Record an INSTANTANEOUS event (zero-duration TraceEvent) — failure/
    recovery markers from the resilience layer: retries, injected faults,
    dispatch timeouts, checkpoint resumes.  Stamped with the active run
    id, so a recovery marker attributes to the fit it interrupted.
    Always logged at `verbose >= 1` like timed stages."""
    depth = getattr(_tls, "depth", 0)
    now = time.time()
    _append(
        TraceEvent(
            name,
            0.0,
            depth,
            detail,
            t0=now,
            t1=now,
            thread_id=threading.get_ident(),
            run_id=getattr(_tls, "run_id", ""),
            kind="instant",
            pass_id=_current_pass_id,
        )
    )
    if int(get_config("verbose") or 0) >= 1:
        suffix = f" [{detail}]" if detail else ""
        (log or logger).info(f"[trace] {'  ' * depth}{name}{suffix}")


@contextlib.contextmanager
def trace(name: str, log: Optional[object] = None) -> Iterator[None]:
    """Time a stage.  Nested stages indent; `verbose >= 1` logs on exit.
    The recorded span carries absolute t0/t1, the recording thread id and
    the active run id (see `run_context`)."""
    depth = getattr(_tls, "depth", 0)
    _tls.depth = depth + 1
    t0_abs = time.time()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        _tls.depth = depth
        _append(
            TraceEvent(
                name,
                dt,
                depth,
                t0=t0_abs,
                t1=t0_abs + dt,
                thread_id=threading.get_ident(),
                run_id=getattr(_tls, "run_id", ""),
                kind="span",
                pass_id=_current_pass_id,
            )
        )
        if int(get_config("verbose") or 0) >= 1:
            (log or logger).info(f"[trace] {'  ' * depth}{name}: {dt:.4f}s")


def record_span(
    name: str, t0_abs: float, t1_abs: float, detail: str = ""
) -> None:
    """Record an already-timed span from absolute epoch endpoints — for
    producers that measured a window themselves (the pod layer's bounded
    cross-process waits) and only want it on the trace after the fact.
    Stamped with the active run id and the pod-global pass id exactly
    like `trace()`."""
    _append(
        TraceEvent(
            name,
            max(t1_abs - t0_abs, 0.0),
            getattr(_tls, "depth", 0),
            detail,
            t0=float(t0_abs),
            t1=float(t1_abs),
            thread_id=threading.get_ident(),
            run_id=getattr(_tls, "run_id", ""),
            kind="span",
            pass_id=_current_pass_id,
        )
    )


_profile_lock = threading.Lock()
_profile_active = False


@contextlib.contextmanager
def device_profile() -> Iterator[None]:
    """Wrap a region in `jax.profiler.trace` when `profile_dir` is set —
    the XLA/TPU execution profile (TensorBoard `xprof` format).  The jax
    profiler is process-global, so concurrent fits (fitMultiple consumers)
    share one trace: only the first caller starts/stops it."""
    global _profile_active
    profile_dir = get_config("profile_dir")
    if not profile_dir:
        yield
        return
    with _profile_lock:
        owner = not _profile_active
        if owner:
            import jax

            jax.profiler.start_trace(str(profile_dir))
            _profile_active = True
    try:
        yield
    finally:
        if owner:
            with _profile_lock:
                # only stop a trace that actually started: if start_trace
                # raised, _profile_active never became True and calling
                # stop_trace would mask the original error
                if _profile_active:
                    import jax

                    jax.profiler.stop_trace()
                    _profile_active = False
                    logger.info(f"Wrote device profile to {profile_dir}")
