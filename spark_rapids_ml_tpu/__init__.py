#
# spark_rapids_ml_tpu — a TPU-native distributed ML framework with the
# capabilities of spark-rapids-ml (reference: /root/reference).
#
# The reference is a pyspark.ml-compatible orchestration layer dispatching to
# cuML/CUDA multi-GPU kernels synchronized by NCCL/UCX.  This framework is a
# standalone re-design for TPU: the same estimator/model API surface
# (fit/transform/save/load, Param system, CPU fallback, single-pass
# CrossValidator, Pipeline) over a JAX SPMD runtime — row-sharded device
# arrays on a `jax.sharding.Mesh`, XLA collectives (psum/all_gather/ppermute)
# over ICI/DCN instead of NCCL/UCX, and jit/shard_map kernels instead of cuML.
#
# Layer map (analog of reference SURVEY.md §1):
#   L6 API facade   models/{feature,clustering,classification,regression,knn,umap}
#   L5 Param system params.py
#   L4 Core runtime core.py  (_TpuEstimator/_TpuModel, staging, persistence)
#   L3 Comm         parallel/ (Mesh, TpuContext, collectives over ICI/DCN)
#   L2 Device/mem   parallel/mesh.py + data.py (host staging, sharded device put)
#   L1 Compute      ops/ (jax.jit / shard_map / pallas kernels)
#
import sys as _sys

__version__ = "0.1.0"

from . import config  # noqa: F401
from . import evaluation, metrics, pipeline, stats, tuning  # noqa: F401
from .data import DeviceDataset  # noqa: F401
from .parallel import init_distributed  # noqa: F401

# Re-export algorithm modules at the top level so imports mirror the
# reference package layout (`spark_rapids_ml.feature` etc., reference
# python/src/spark_rapids_ml/__init__.py).
from .models import (  # noqa: F401
    classification,
    clustering,
    feature,
    knn,
    regression,
    umap,
)

_sys.modules[__name__ + ".feature"] = feature
_sys.modules[__name__ + ".clustering"] = clustering
_sys.modules[__name__ + ".classification"] = classification
_sys.modules[__name__ + ".regression"] = regression
_sys.modules[__name__ + ".knn"] = knn
_sys.modules[__name__ + ".umap"] = umap
