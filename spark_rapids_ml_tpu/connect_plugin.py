#
# Connect-plugin worker — the analog of the reference's Spark Connect
# backend (`connect_plugin.py:68-273`, spawned per request by the JVM
# `PythonEstimatorRunner`/`PythonModelRunner`, jvm/.../Plugin.scala:26-57).
# The reference worker receives (operator_name, params, dataset) over a
# py4j gateway, fits/transforms, and returns JSON model attributes or a
# transformed DataFrame handle.
#
# Here the JVM gateway is replaced by a transport any host process (a
# Spark 4.0 Connect server plugin, a service, a test) can speak: one JSON
# request per line on stdin, one JSON response per line on stdout.
# Datasets travel as parquet paths — the natural exchange format for a
# JVM caller (df.write.parquet) and exactly what the streaming ingest
# path consumes.
#
#   {"op": "fit", "operator": "LogisticRegression", "params": {...},
#    "data": "<parquet path>", "model_path": "<dir>"}
#      -> {"status": "ok", "attributes": {...scalar attrs...},
#          "model_path": ...}
#   {"op": "transform", "operator": "LogisticRegressionModel",
#    "params": {...}, "data": "<parquet path>", "model_path": "<dir>",
#    "output_path": "<parquet path>"}
#      -> {"status": "ok", "output_path": ..., "num_rows": N}
#
# The operator registry mirrors the 6 plugin-supported algorithms
# (reference connect_plugin.py:127-243).
#
from __future__ import annotations

import json
import sys
import traceback
from typing import IO, Any, Dict


def _registry() -> Dict[str, Any]:
    from .classification import (
        LogisticRegression,
        LogisticRegressionModel,
        RandomForestClassificationModel,
        RandomForestClassifier,
    )
    from .clustering import KMeans, KMeansModel
    from .feature import PCA, PCAModel
    from .regression import (
        LinearRegression,
        LinearRegressionModel,
        RandomForestRegressionModel,
        RandomForestRegressor,
    )

    return {
        "LogisticRegression": (LogisticRegression, LogisticRegressionModel),
        "RandomForestClassifier": (
            RandomForestClassifier, RandomForestClassificationModel,
        ),
        "RandomForestRegressor": (
            RandomForestRegressor, RandomForestRegressionModel,
        ),
        "LinearRegression": (LinearRegression, LinearRegressionModel),
        "KMeans": (KMeans, KMeansModel),
        "PCA": (PCA, PCAModel),
    }


def _sanitize_nonfinite(v):
    """Recursively stringify non-finite floats (strict-JSON wire format
    for the JVM side, which maps the strings back in ModelBuilder)."""
    import math

    if isinstance(v, float) and not math.isfinite(v):
        return "NaN" if math.isnan(v) else (
            "Infinity" if v > 0 else "-Infinity"
        )
    if isinstance(v, list):
        return [_sanitize_nonfinite(x) for x in v]
    return v


def _scalar_attributes(
    model, max_inline_elems: float = 0
) -> Dict[str, Any]:
    """JSON-safe model attributes.  Numeric arrays up to
    `max_inline_elems` elements are INLINE (nested lists) — the Scala
    ModelBuilder reconstructs native Spark models from them
    (TpuModels.scala `attrs \\ "coef_"` etc.), matching the reference's
    py4j inline-attribute transport.  Larger arrays (RF node tables,
    UMAP embeddings) stay path-resident in the model directory and only
    their `_shape` ships; those models transform via the Python-backed
    round trip instead."""
    import numpy as np

    out: Dict[str, Any] = {}
    for k, v in model._get_model_attributes().items():
        if isinstance(v, (np.integer, np.floating, np.bool_)):
            v = v.item()
        v = _sanitize_nonfinite(v)
        if isinstance(v, (str, int, float, bool)) or v is None:
            out[k] = v
        elif isinstance(v, list) and all(
            isinstance(x, (str, int, float, bool)) for x in v
        ):
            out[k] = v  # e.g. classes_
        elif isinstance(v, np.ndarray):
            out[k + "_shape"] = list(v.shape)
            if v.size <= max_inline_elems and (
                np.issubdtype(v.dtype, np.number)
                or v.dtype == np.bool_
            ):
                out[k] = _sanitize_nonfinite(v.tolist())
    return out


_model_cache: Dict[Any, Any] = {}


def handle_request(req: Dict[str, Any]) -> Dict[str, Any]:
    registry = _registry()
    op = req.get("op")
    operator = str(req.get("operator", ""))
    params = dict(req.get("params") or {})
    data = req.get("data")

    base = operator[:-5] if operator.endswith("Model") else operator
    # model class names do not all strip to their estimator's name
    # (RandomForestClassificationModel -> RandomForestClassifier)
    base = {
        "RandomForestClassification": "RandomForestClassifier",
        "RandomForestRegression": "RandomForestRegressor",
    }.get(base, base)
    if base not in registry:
        return {
            "status": "error",
            "error": f"unsupported operator '{operator}'; supported: "
            + ", ".join(sorted(registry)),
        }
    est_cls, model_cls = registry[base]

    if op == "fit":
        est = est_cls(**params)
        model = est.fit(data)
        model_path = req.get("model_path")
        if model_path:
            model.save(model_path)
        # a JVM caller building a real Spark model (jvm/ ModelBuilder)
        # sends inline_arrays=true: the full array payload ships inline
        # (reference py4j semantics); other callers get scalars + shapes
        # and read arrays from model_path
        attributes = _scalar_attributes(
            model,
            max_inline_elems=(
                float("inf") if req.get("inline_arrays") else 0
            ),
        )
        return {
            "status": "ok",
            "operator": base + "Model",
            "attributes": attributes,
            "model_path": model_path,
        }

    if op == "transform":
        model_path = req.get("model_path")
        if not model_path:
            return {"status": "error", "error": "transform requires model_path"}
        # long-lived workers serve many transforms per model: cache the
        # loaded model (and with it the lazily staged device index)
        key = (operator, str(model_path))
        model = _model_cache.get(key)
        if model is None:
            model = model_cls.load(model_path)
            _model_cache.clear()  # one resident model keeps HBM bounded
            _model_cache[key] = model
        if params:
            model._set_params(**params)
        from .data import _to_pandas

        pdf = _to_pandas(data)
        out_df = model.transform(pdf)
        output_path = req.get("output_path")
        num_rows = int(len(out_df))
        if output_path:
            out_df.to_parquet(output_path)
        return {"status": "ok", "output_path": output_path, "num_rows": num_rows}

    return {"status": "error", "error": f"unknown op '{op}' (fit|transform)"}


def main(infile: IO = sys.stdin, outfile: IO = sys.stdout) -> None:
    """Serve line-JSON requests until EOF (one worker can handle many
    requests; the reference spawns one worker per request, which also
    works — a single line then EOF)."""
    import os

    from ._jax_env import apply_jax_platforms_env

    apply_jax_platforms_env()
    for line in infile:
        line = line.strip()
        if not line:
            continue
        try:
            resp = handle_request(json.loads(line))
        except Exception as e:
            resp = {
                "status": "error",
                "error": f"{type(e).__name__}: {e}",
                "traceback": traceback.format_exc()[-2000:],
            }
        outfile.write(json.dumps(resp) + "\n")
        outfile.flush()


if __name__ == "__main__":
    main()
