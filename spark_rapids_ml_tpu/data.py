#
# Data plane — the analog of the reference's input pre-processing
# (`_CumlCaller._pre_process_data` core.py:467-568: column selection, dtype
# cast, VectorUDT unwrap / vector_to_array, dimension probe) and the worker
# staging loop (core.py:886-957).  Without Spark, the accepted dataset types
# are: numpy 2-D arrays, (X, y) tuples, scipy CSR matrices, pandas
# DataFrames (array-valued features column — the VectorUDT analog — or
# multiple scalar columns, reference HasFeaturesCols params.py:69-88),
# pyarrow Tables, and parquet paths.
#
from __future__ import annotations

import os
from typing import Any, List, Optional, Sequence, Tuple, Union

import numpy as np

from .utils import _ArrayBatch

try:  # scipy is baked in but keep the import soft
    import scipy.sparse as sp
except Exception:  # pragma: no cover
    sp = None


DatasetLike = Any  # np.ndarray | pd.DataFrame | pa.Table | str | tuple | csr_matrix


def _is_sparse(x: Any) -> bool:
    return sp is not None and sp.issparse(x)


def _ensure_dense(X: Any) -> np.ndarray:
    """Densify sparse host matrices before device staging.  TPU has no
    cusparse analog (SURVEY.md §7 hard part (e)); until the BCOO kernel path
    lands, CSR inputs densify on the host (the reference's LogReg similarly
    switches representations at staging, classification.py:960-966)."""
    if _is_sparse(X):
        from .native import densify_csr

        csr = X.tocsr()
        return densify_csr(csr, csr.shape[0], csr.dtype)
    return X


def densify_to_device(X, dtype, row_transform=None):
    """Assemble a DENSE single-device jax array from a host CSR matrix in
    row chunks, bounded by the `host_batch_bytes` budget — the TPU-first
    analog of the reference's sparse fit staging (cuML UMAP `_sparse_fit`
    umap.py:904-969 concatenates CSR chunks on the GPU).  TPU kernels take
    dense operands (no cusparse analog), so the dense matrix must exist in
    HBM; what this avoids is ever materializing more than one dense chunk
    in HOST memory.

    `row_transform` (optional) is applied to each dense host chunk before
    the transfer (metric row preprocessing, ops/distances.preprocess_rows).
    Returns a (n, d) jax array on the default device.
    """
    import jax
    import jax.numpy as jnp

    from .native import densify_csr
    from .streaming import chunk_rows_for

    X = X.tocsr()
    n, d = X.shape
    dtype = np.dtype(dtype)
    chunk = max(1, int(chunk_rows_for(d, dtype.itemsize)))
    if n <= chunk:
        dense = densify_csr(X, n, dtype)
        if row_transform is not None:
            dense = np.asarray(row_transform(dense), dtype=dtype)
        return jnp.asarray(dense)
    return assemble_dense_chunks(X, n, dtype, chunk, row_transform)


def assemble_dense_chunks(
    X, n_rows_out: int, dtype, chunk: int, row_transform=None,
    out_shardings=None,
):
    """The chunk-bounded CSR -> dense device assembly (used by
    `densify_to_device` and `RowStager.stage_sparse`): each host chunk
    densifies then lands in the device buffer via the shared
    bounded-upload loop (`mesh.assemble_rows_chunked`).  Rows past the
    input length stay zero (padding)."""
    from .native import densify_csr
    from .parallel.mesh import _MAX_PUT_BYTES, assemble_rows_chunked

    n, d = X.shape
    dtype = np.dtype(dtype)
    # host_batch_bytes is a host-RAM knob; the per-piece device transfer
    # must still respect the single-put ceiling regardless of its value
    chunk = max(1, min(chunk, _MAX_PUT_BYTES // max(d * dtype.itemsize, 1)))

    def pieces():
        for lo in range(0, n, chunk):
            hi = min(lo + chunk, n)
            dense = densify_csr(X[lo:hi], hi - lo, dtype)
            if row_transform is not None:
                dense = np.asarray(row_transform(dense), dtype=dtype)
            yield lo, dense

    return assemble_rows_chunked(
        (n_rows_out, d), dtype, pieces(), out_shardings=out_shardings
    )


def _to_pandas(dataset: DatasetLike):
    import pandas as pd
    import pyarrow as pa

    if isinstance(dataset, pd.DataFrame):
        return dataset
    if isinstance(dataset, pa.Table):
        return dataset.to_pandas()
    from .spark_interop import is_spark_dataframe, spark_dataframe_to_pandas

    if is_spark_dataframe(dataset):
        return spark_dataframe_to_pandas(dataset)
    if isinstance(dataset, str):
        import pyarrow.parquet as pq

        if os.path.isdir(dataset) or dataset.endswith(".parquet"):
            return pq.read_table(dataset).to_pandas()
        raise ValueError(f"Unsupported dataset path: {dataset}")
    raise TypeError(f"Cannot interpret dataset of type {type(dataset)} as a DataFrame")


def _features_from_pandas(
    pdf,
    features_col: Optional[str],
    features_cols: Sequence[str],
    dtype: Optional[np.dtype],
) -> np.ndarray:
    """Extract the feature matrix from a pandas DataFrame.

    Array-valued column == the reference's VectorUDT input unwrapped via
    `vector_to_array` (core.py:493-537); multiple scalar columns == the
    reference's HasFeaturesCols fast path that skips VectorAssembler
    (params.py:69-88, pipeline.py:85-119).
    """
    if len(pdf) == 0:
        # reference raises on empty partitions (core.py:959-962)
        raise ValueError("Dataset is empty: nothing to fit/transform")
    if features_cols:
        missing = [c for c in features_cols if c not in pdf.columns]
        if missing:
            raise ValueError(f"featuresCols {missing} not found in dataset")
        return np.ascontiguousarray(pdf[list(features_cols)].to_numpy(dtype=dtype))
    assert features_col is not None
    if features_col not in pdf.columns:
        raise ValueError(f"featuresCol '{features_col}' not found in dataset")
    col = pdf[features_col]
    first = col.iloc[0]
    if np.isscalar(first):
        return np.ascontiguousarray(col.to_numpy(dtype=dtype).reshape(-1, 1))
    rows = col.to_numpy()
    first_arr = np.asarray(first)
    out_dtype = dtype if dtype is not None else (
        first_arr.dtype
        if np.issubdtype(first_arr.dtype, np.floating)
        else np.float64
    )
    from .native import pack_rows

    return pack_rows(rows, len(rows), out_dtype)


def extract_arrays(
    dataset: DatasetLike,
    features_col: Optional[str] = None,
    features_cols: Sequence[str] = (),
    label_col: Optional[str] = None,
    weight_col: Optional[str] = None,
    id_col: Optional[str] = None,
    dtype: Union[np.dtype, type, None] = None,
    supervised: bool = False,
) -> _ArrayBatch:
    """Normalize any accepted dataset into host numpy arrays.

    The analog of `_pre_process_data` + the worker staging loop
    (reference core.py:467-568, 886-957) collapsed into one host-side step:
    there is no Spark/Arrow process boundary to cross, so the controller
    assembles the full (X, y, w) arrays and `shard_rows` splits them onto
    the mesh.
    """
    dtype = np.dtype(dtype) if dtype is not None else None
    y = w = rid = None

    if isinstance(dataset, (tuple, list)) and len(dataset) == 2:
        X, y = dataset
        if not _is_sparse(X) and dtype is not None:
            X = np.asarray(X, dtype=dtype)
        y = np.asarray(y)
    elif isinstance(dataset, np.ndarray):
        X = np.asarray(dataset, dtype=dtype)
        if X.ndim == 1:
            X = X.reshape(-1, 1)
    elif _is_sparse(dataset):
        X = dataset.tocsr()
    else:
        pdf = _to_pandas(dataset)
        X = _features_from_pandas(pdf, features_col, list(features_cols), dtype)
        if supervised:
            if label_col is None or label_col not in pdf.columns:
                raise ValueError(f"labelCol '{label_col}' not found in dataset")
            y = pdf[label_col].to_numpy()
        if weight_col and weight_col in pdf.columns:
            w = pdf[weight_col].to_numpy(dtype=dtype)
        if id_col and id_col in pdf.columns:
            rid = pdf[id_col].to_numpy()

    if supervised and y is None:
        raise ValueError("Supervised fit requires labels: pass (X, y) or a DataFrame with labelCol")
    if y is not None:
        y = np.ascontiguousarray(np.asarray(y).reshape(-1))
    if not _is_sparse(X):
        X = np.asarray(X, dtype=dtype)
        if not np.issubdtype(X.dtype, np.floating):
            # integer/bool features promote to float64 (Spark double semantics)
            X = X.astype(np.float64)
        X = np.ascontiguousarray(X)
    return _ArrayBatch(X=X, y=y, weight=w, row_id=rid)


class DeviceDataset:
    """A dataset staged once onto the device mesh and reused across fits —
    the analog of benchmarking against a cached Spark DataFrame (the
    reference's benchmarks `.cache()` the input before timing fit,
    python/benchmark/benchmark_runner.py workloads).

    `fit(DeviceDataset)` skips host extraction and host->HBM staging
    entirely: the rows already live sharded over the mesh.  Build one with
    `DeviceDataset.from_host(X, y)` or from any accepted dataset type via
    `DeviceDataset.persist(dataset, ...)`.
    """

    def __init__(self, mesh, X, n_valid: int, y=None, weight=None,
                 stager=None) -> None:
        self.mesh = mesh
        self.X = X  # jax.Array (N_pad, d), rows sharded over DATA_AXIS
        self.y = y  # jax.Array (N_pad,) or None
        self.weight = weight  # jax.Array (N_pad,) validity * sample weight
        self.n_valid = int(n_valid)
        self._stager = stager  # RowStager used at staging (padding layout)

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.n_valid, int(self.X.shape[1]))

    def to_host_batch(self) -> _ArrayBatch:
        """Pull the valid rows back to host (used by CPU-fallback fits)."""
        import jax

        if self._stager is not None:
            # honors the staging layout: multi-process padding interleaves
            # at each process-block tail, and sharded arrays are not fully
            # addressable from one process — RowStager.fetch handles both
            st = self._stager
            return _ArrayBatch(
                X=st.fetch(self.X),
                y=st.fetch(self.y) if self.y is not None else None,
                weight=st.fetch(self.weight) if self.weight is not None else None,
            )
        if jax.process_count() > 1:
            raise RuntimeError(
                "to_host_batch on a directly-constructed DeviceDataset is "
                "single-process only; build via DeviceDataset.from_host"
            )
        fetch = {"X": self.X}
        if self.y is not None:
            fetch["y"] = self.y
        if self.weight is not None:
            fetch["w"] = self.weight
        host = jax.device_get(fetch)
        n = self.n_valid
        return _ArrayBatch(
            X=np.asarray(host["X"])[:n],
            y=np.asarray(host["y"])[:n] if "y" in host else None,
            weight=np.asarray(host["w"])[:n] if "w" in host else None,
        )

    @classmethod
    def from_host(
        cls,
        X: np.ndarray,
        y: Optional[np.ndarray] = None,
        weight: Optional[np.ndarray] = None,
        num_workers: Optional[int] = None,
        dtype: Union[np.dtype, type] = np.float32,
        label_dtype: Union[np.dtype, type, None] = None,
    ) -> "DeviceDataset":
        from .parallel import get_mesh
        from .parallel.mesh import RowStager

        dtype = np.dtype(dtype)
        mesh = get_mesh(num_workers)
        X = _ensure_dense(np.asarray(X))
        st = RowStager(X.shape[0], mesh)
        Xs = st.stage(X, dtype)
        w = st.mask(dtype, weights=weight)
        yd = None
        if y is not None:
            ldt = np.dtype(label_dtype) if label_dtype is not None else dtype
            yd = st.stage(np.asarray(y).reshape(-1).astype(ldt), ldt)
        return cls(mesh, Xs, st.n_valid, y=yd, weight=w, stager=st)

    @classmethod
    def persist(
        cls,
        dataset: DatasetLike,
        features_col: Optional[str] = None,
        features_cols: Sequence[str] = (),
        label_col: Optional[str] = None,
        weight_col: Optional[str] = None,
        num_workers: Optional[int] = None,
        dtype: Union[np.dtype, type] = np.float32,
    ) -> "DeviceDataset":
        batch = extract_arrays(
            dataset,
            features_col=features_col,
            features_cols=features_cols,
            label_col=label_col,
            weight_col=weight_col,
            supervised=label_col is not None,
        )
        return cls.from_host(
            _ensure_dense(batch.X),
            y=batch.y,
            weight=batch.weight,
            num_workers=num_workers,
            dtype=dtype,
        )


def read_parquet_batches(
    path: str, columns: Optional[List[str]] = None, batch_rows: int = 1_000_000
):
    """Stream a parquet dataset in record-batch chunks — the host-side
    staging loop used for out-of-core inputs (reference reserved-memory
    loader utils.py:403-522 streams Arrow batches straight into a
    pre-reserved GPU buffer; here batches stream host->HBM per chunk)."""
    import pyarrow.dataset as ds

    dataset = ds.dataset(path, format="parquet")
    for batch in dataset.to_batches(columns=columns, batch_size=batch_rows):
        yield batch.to_pandas()


def infer_dimension(batch: _ArrayBatch) -> int:
    return int(batch.X.shape[1])
