#
# Data plane — the analog of the reference's input pre-processing
# (`_CumlCaller._pre_process_data` core.py:467-568: column selection, dtype
# cast, VectorUDT unwrap / vector_to_array, dimension probe) and the worker
# staging loop (core.py:886-957).  Without Spark, the accepted dataset types
# are: numpy 2-D arrays, (X, y) tuples, scipy CSR matrices, pandas
# DataFrames (array-valued features column — the VectorUDT analog — or
# multiple scalar columns, reference HasFeaturesCols params.py:69-88),
# pyarrow Tables, and parquet paths.
#
from __future__ import annotations

import os
from typing import Any, List, Optional, Sequence, Tuple, Union

import numpy as np

from .utils import _ArrayBatch, _concat_and_free

try:  # scipy is baked in but keep the import soft
    import scipy.sparse as sp
except Exception:  # pragma: no cover
    sp = None


DatasetLike = Any  # np.ndarray | pd.DataFrame | pa.Table | str | tuple | csr_matrix


def _is_sparse(x: Any) -> bool:
    return sp is not None and sp.issparse(x)


def _ensure_dense(X: Any) -> np.ndarray:
    """Densify sparse host matrices before device staging.  TPU has no
    cusparse analog (SURVEY.md §7 hard part (e)); until the BCOO kernel path
    lands, CSR inputs densify on the host (the reference's LogReg similarly
    switches representations at staging, classification.py:960-966)."""
    if _is_sparse(X):
        return np.ascontiguousarray(X.toarray())
    return X


def _to_pandas(dataset: DatasetLike):
    import pandas as pd
    import pyarrow as pa

    if isinstance(dataset, pd.DataFrame):
        return dataset
    if isinstance(dataset, pa.Table):
        return dataset.to_pandas()
    if isinstance(dataset, str):
        import pyarrow.parquet as pq

        if os.path.isdir(dataset) or dataset.endswith(".parquet"):
            return pq.read_table(dataset).to_pandas()
        raise ValueError(f"Unsupported dataset path: {dataset}")
    raise TypeError(f"Cannot interpret dataset of type {type(dataset)} as a DataFrame")


def _features_from_pandas(
    pdf,
    features_col: Optional[str],
    features_cols: Sequence[str],
    dtype: np.dtype,
) -> np.ndarray:
    """Extract the feature matrix from a pandas DataFrame.

    Array-valued column == the reference's VectorUDT input unwrapped via
    `vector_to_array` (core.py:493-537); multiple scalar columns == the
    reference's HasFeaturesCols fast path that skips VectorAssembler
    (params.py:69-88, pipeline.py:85-119).
    """
    if len(pdf) == 0:
        # reference raises on empty partitions (core.py:959-962)
        raise ValueError("Dataset is empty: nothing to fit/transform")
    if features_cols:
        missing = [c for c in features_cols if c not in pdf.columns]
        if missing:
            raise ValueError(f"featuresCols {missing} not found in dataset")
        return np.ascontiguousarray(pdf[list(features_cols)].to_numpy(dtype=dtype))
    assert features_col is not None
    if features_col not in pdf.columns:
        raise ValueError(f"featuresCol '{features_col}' not found in dataset")
    col = pdf[features_col]
    first = col.iloc[0]
    if np.isscalar(first):
        return np.ascontiguousarray(col.to_numpy(dtype=dtype).reshape(-1, 1))
    return np.ascontiguousarray(np.stack([np.asarray(v, dtype=dtype) for v in col]))


def extract_arrays(
    dataset: DatasetLike,
    features_col: Optional[str] = None,
    features_cols: Sequence[str] = (),
    label_col: Optional[str] = None,
    weight_col: Optional[str] = None,
    id_col: Optional[str] = None,
    dtype: Union[np.dtype, type] = np.float32,
    supervised: bool = False,
) -> _ArrayBatch:
    """Normalize any accepted dataset into host numpy arrays.

    The analog of `_pre_process_data` + the worker staging loop
    (reference core.py:467-568, 886-957) collapsed into one host-side step:
    there is no Spark/Arrow process boundary to cross, so the controller
    assembles the full (X, y, w) arrays and `shard_rows` splits them onto
    the mesh.
    """
    dtype = np.dtype(dtype)
    y = w = rid = None

    if isinstance(dataset, (tuple, list)) and len(dataset) == 2:
        X, y = dataset
        X = np.asarray(X, dtype=dtype) if not _is_sparse(X) else X
        y = np.asarray(y)
    elif isinstance(dataset, np.ndarray):
        X = np.asarray(dataset, dtype=dtype)
        if X.ndim == 1:
            X = X.reshape(-1, 1)
    elif _is_sparse(dataset):
        X = dataset.tocsr()
    else:
        pdf = _to_pandas(dataset)
        X = _features_from_pandas(pdf, features_col, list(features_cols), dtype)
        if supervised:
            if label_col is None or label_col not in pdf.columns:
                raise ValueError(f"labelCol '{label_col}' not found in dataset")
            y = pdf[label_col].to_numpy()
        if weight_col and weight_col in pdf.columns:
            w = pdf[weight_col].to_numpy(dtype=dtype)
        if id_col and id_col in pdf.columns:
            rid = pdf[id_col].to_numpy()

    if supervised and y is None:
        raise ValueError("Supervised fit requires labels: pass (X, y) or a DataFrame with labelCol")
    if y is not None:
        y = np.ascontiguousarray(np.asarray(y).reshape(-1))
    if not _is_sparse(X):
        X = np.ascontiguousarray(np.asarray(X, dtype=dtype))
    return _ArrayBatch(X=X, y=y, weight=w, row_id=rid)


def read_parquet_batches(
    path: str, columns: Optional[List[str]] = None, batch_rows: int = 1_000_000
):
    """Stream a parquet dataset in record-batch chunks — the host-side
    staging loop used for out-of-core inputs (reference reserved-memory
    loader utils.py:403-522 streams Arrow batches straight into a
    pre-reserved GPU buffer; here batches stream host->HBM per chunk)."""
    import pyarrow.dataset as ds

    dataset = ds.dataset(path, format="parquet")
    for batch in dataset.to_batches(columns=columns, batch_size=batch_rows):
        yield batch.to_pandas()


def infer_dimension(batch: _ArrayBatch) -> int:
    return int(batch.X.shape[1])
