#
# Core runtime — the analog of reference core.py (1967 LoC):
# `_CumlCaller` (core.py:439) / `_CumlEstimator` (core.py:1067) /
# `_CumlModel` (core.py:1356) re-designed for a single-controller JAX SPMD
# runtime.  The reference's orchestration shape
#   preprocess -> repartition(num_workers) -> mapInPandas barrier fit over
#   NCCL -> collect model rows -> driver model
# becomes
#   extract host arrays -> shard rows onto a Mesh -> jit'd kernel with XLA
#   collectives -> host model attributes
# with no process boundary: the controller stages data and XLA moves it.
#
from __future__ import annotations

import json
import os
import time
from abc import abstractmethod
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .data import DatasetLike, DeviceDataset, _ensure_dense, extract_arrays
from .params import Param, Params, _TpuParams
from .parallel import TpuContext
from .telemetry.locks import named_lock
from .utils import PartitionDescriptor, _ArrayBatch, get_logger


@dataclass
class FitInput:
    """Everything a kernel needs for one distributed fit — the analog of the
    `params` dict handed to `_cuml_fit_func` (reference `param_alias`
    core.py:154-175: handle/part_sizes/num_cols/rank/loop)."""

    mesh: Any  # jax.sharding.Mesh
    X: Any  # jax.Array, rows sharded over DATA_AXIS, zero-padded
    w: Any  # jax.Array (N_pad,) validity * sample weight
    y: Optional[Any]  # jax.Array or None
    pdesc: PartitionDescriptor
    dtype: np.dtype
    n_valid: int
    params: Dict[str, Any]  # resolved backend params (_tpu_params)
    extra: Dict[str, Any] = field(default_factory=dict)


# error classification now lives in the resilience layer (one classifier
# set for every dispatch site); re-exported here for back-compat
from .resilience import is_oom as _is_oom  # noqa: F401


def _fit_fingerprint(fit_input: FitInput) -> str:
    """Cheap content fingerprint binding an in-memory checkpoint tag to
    the DATA, not just its shape: scalar device reductions over the
    staged arrays (plus the label sum when present).  Without this, a
    crashed fit's checkpoint would be silently resumed by a same-shaped,
    same-hyperparameter fit on DIFFERENT data — skipping most of its
    iterations (the in-file tag check in resilience/checkpoint.py can
    only refuse what the tag encodes).  Streaming fits bind the dataset
    path instead.

    The reductions are EXACT and mesh-layout-independent: each array is
    bitcast to same-width integers and summed with modular (wraparound)
    arithmetic, which is associative + commutative — so the fingerprint
    is invariant under re-sharding and padding-row changes (padding is
    +0.0, bit pattern 0).  This is load-bearing for elastic recovery
    (resilience/elastic.py): a fit resumed on a SHRUNKEN mesh must
    derive the same tag from its re-staged arrays or its checkpoint is
    orphaned, and f32 float sums differ in the last ulp per shard count
    (per-shard partial-sum order changes with the device set)."""
    import jax
    import jax.numpy as jnp

    def _isum(arr) -> int:
        itype = {1: jnp.int8, 2: jnp.int16, 4: jnp.int32, 8: jnp.int64}[
            np.dtype(arr.dtype).itemsize
        ]
        if jnp.issubdtype(arr.dtype, jnp.floating):
            arr = jax.lax.bitcast_convert_type(arr, itype)
        return int(jax.device_get(jnp.sum(arr.astype(itype), dtype=itype)))

    parts = [f"sx={_isum(fit_input.X)}", f"swt={_isum(fit_input.w)}"]
    if fit_input.y is not None:
        parts.append(f"sy={_isum(fit_input.y)}")
    return "|".join(parts)


def _resolve_feature_params(inst: Params) -> Tuple[Optional[str], Sequence[str]]:
    """Which column(s) hold features: featuresCol/featuresCols for
    predictors, inputCol/inputCols for feature transformers like PCA
    (reference _PCACumlParams setInputCol feature.py:77-115)."""
    features_cols: Sequence[str] = ()
    if inst.hasParam("featuresCols") and inst.isSet("featuresCols"):
        features_cols = inst.getOrDefault("featuresCols")
    elif inst.hasParam("inputCols") and inst.isSet("inputCols"):
        features_cols = inst.getOrDefault("inputCols")
    features_col: Optional[str] = None
    if inst.hasParam("featuresCol") and inst.isDefined("featuresCol"):
        features_col = inst.getOrDefault("featuresCol")
    if inst.hasParam("inputCol") and inst.isSet("inputCol"):
        features_col = inst.getOrDefault("inputCol")
    return features_col, features_cols


class Estimator(Params):
    """pyspark.ml.Estimator-compatible base."""

    def fit(self, dataset: DatasetLike, params: Optional[Dict[Param, Any]] = None):
        est = self.copy(params) if params else self
        # every fit runs under a minted run_id and a root `fit[<Est>]`
        # span (telemetry/report.py): retries, device-loss recoveries and
        # checkpoint resumes recorded anywhere below stamp this run, and
        # the assembled per-fit report lands on the model
        # (`model.fit_report()`; JSON artifact when `telemetry_dir` is
        # set)
        from .monitor.baseline import baseline_mode, baseline_scope
        from .telemetry.report import FitTelemetry

        tel = FitTelemetry(type(est).__name__)
        with tel.span():
            # drift-baseline capture (monitor/): the chunked fit paths
            # (fused stage-and-solve, streamed statistics) fold their
            # decoded host chunks into a baseline fingerprint when a
            # collector is armed — zero extra data passes; conf "on"
            # additionally folds in-memory batches (one host pass)
            with baseline_scope(baseline_mode() != "off") as coll:
                model = est._fit(dataset)
            fp = coll.fingerprint() if coll is not None else None
            if fp is not None:
                model._drift_baseline = fp
        tel.attach(model, log=getattr(est, "logger", None))
        return model

    @abstractmethod
    def _fit(self, dataset: DatasetLike):
        ...


class Transformer(Params):
    """pyspark.ml.Transformer-compatible base."""

    def transform(self, dataset: DatasetLike, params: Optional[Dict[Param, Any]] = None):
        from .tracing import current_run_id, run_context

        tr = self.copy(params) if params else self
        # a TOP-LEVEL transform mints its own run_id; a transform running
        # inside an active run (Pipeline._fit driving its stages, CV
        # eval) inherits it, so its spans and retry markers stay attached
        # to the fit that issued them
        if current_run_id():
            return tr._transform(dataset)
        with run_context(prefix="transform"):
            return tr._transform(dataset)

    @abstractmethod
    def _transform(self, dataset: DatasetLike):
        ...


class Model(Transformer):
    def fit_report(self) -> Optional[Dict[str, Any]]:
        """The telemetry report of the fit that produced this model
        (telemetry/report.py): stage timing tree, bytes staged, cache
        hits, retries/recoveries, solver iteration/loss curve.  None for
        models not produced by `Estimator.fit` in this process (loaded
        from disk, hand-built).  The same dict is written to
        `telemetry_dir` as a JSON artifact when that conf is set."""
        return getattr(self, "_fit_report", None)


# ---------------------------------------------------------------------------
# Persistence (reference _CumlEstimatorWriter/Reader core.py:268-307 and
# _CumlModelWriter/Reader core.py:310-355).  Directory layout:
#   <path>/metadata.json   class, uid, params, _tpu_params, scalar attributes
#   <path>/arrays.npz      ndarray model attributes
# ---------------------------------------------------------------------------


def _json_default(o: Any) -> Any:
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not JSON serializable: {type(o)}")


class _Writer:
    def __init__(self, instance: "_TpuParams") -> None:
        self.instance = instance
        self._overwrite = False

    def overwrite(self) -> "_Writer":
        self._overwrite = True
        return self

    def save(self, path: str) -> None:
        if os.path.exists(path) and not self._overwrite:
            raise IOError(f"Path {path} already exists; use .write().overwrite().save()")
        os.makedirs(path, exist_ok=True)
        inst = self.instance
        metadata: Dict[str, Any] = {
            "class": type(inst).__module__ + "." + type(inst).__qualname__,
            "uid": inst.uid,
            "timestamp": int(time.time() * 1000),
            "paramMap": {p.name: v for p, v in inst._paramMap.items()},
            "defaultParamMap": {p.name: v for p, v in inst._defaultParamMap.items()},
            "tpu_params": inst._tpu_params,
            "num_workers": inst._num_workers,
            "float32_inputs": inst._float32_inputs,
        }
        arrays: Dict[str, np.ndarray] = {}
        if isinstance(inst, _TpuModel):
            from .data import _is_sparse

            attrs: Dict[str, Any] = {}
            sparse_attrs: List[str] = []
            for k, v in inst._get_model_attributes().items():
                if _is_sparse(v):
                    # CSR attributes (sparse kNN item sets, sparse UMAP raw
                    # data) persist as their three component arrays + shape;
                    # np.savez has no sparse container
                    csr = v.tocsr()
                    arrays[k + "__csr_data"] = np.asarray(csr.data)
                    arrays[k + "__csr_indices"] = np.asarray(csr.indices)
                    arrays[k + "__csr_indptr"] = np.asarray(csr.indptr)
                    arrays[k + "__csr_shape"] = np.asarray(csr.shape, np.int64)
                    sparse_attrs.append(k)
                elif isinstance(v, np.ndarray):
                    arrays[k] = v
                else:
                    attrs[k] = v
            metadata["attributes"] = attrs
            metadata["array_attributes"] = sorted(arrays)
            if sparse_attrs:
                metadata["sparse_attributes"] = sorted(sparse_attrs)
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(metadata, f, default=_json_default)
        npz_path = os.path.join(path, "arrays.npz")
        if os.path.exists(npz_path):
            os.remove(npz_path)  # stale arrays from a previous overwrite-save
        if arrays:
            np.savez(npz_path, **arrays)
        # drift baseline (monitor/fingerprint.py): the fit-time
        # distribution fingerprint persists NEXT TO the model arrays so
        # a loaded model can register with the serving drift monitor
        fp_path = os.path.join(path, "drift_baseline.bin")
        if os.path.exists(fp_path):
            os.remove(fp_path)  # stale baseline from an overwrite-save
        fp = getattr(inst, "_drift_baseline", None)
        if fp is not None:
            with open(fp_path, "wb") as f:
                f.write(fp.to_bytes())


def _load_metadata(path: str) -> Dict[str, Any]:
    with open(os.path.join(path, "metadata.json")) as f:
        return json.load(f)


def _load_arrays(path: str) -> Dict[str, np.ndarray]:
    npz_path = os.path.join(path, "arrays.npz")
    if not os.path.exists(npz_path):
        return {}
    with np.load(npz_path, allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


class _ReadWriteMixin:
    """save/load entry points shared by estimators and models."""

    def write(self) -> _Writer:
        return _Writer(self)  # type: ignore[arg-type]

    def save(self, path: str) -> None:
        self.write().save(path)

    @classmethod
    def _restore_params(cls, inst: "_TpuParams", meta: Dict[str, Any]) -> None:
        for name, v in meta.get("defaultParamMap", {}).items():
            if inst.hasParam(name):
                inst._defaultParamMap[inst.getParam(name)] = v
        for name, v in meta.get("paramMap", {}).items():
            if inst.hasParam(name):
                inst._paramMap[inst.getParam(name)] = v
        inst._tpu_params = dict(meta.get("tpu_params", {}))
        inst._num_workers = meta.get("num_workers")
        inst._float32_inputs = meta.get("float32_inputs", True)

    @classmethod
    def load(cls, path: str):
        meta = _load_metadata(path)
        if issubclass(cls, _TpuModel):
            arrays = _load_arrays(path)
            wanted = meta.get("array_attributes")
            if wanted is not None:
                arrays = {k: v for k, v in arrays.items() if k in wanted}
            for name in meta.get("sparse_attributes", []):
                import scipy.sparse as sp

                arrays[name] = sp.csr_matrix(
                    (
                        arrays.pop(name + "__csr_data"),
                        arrays.pop(name + "__csr_indices"),
                        arrays.pop(name + "__csr_indptr"),
                    ),
                    shape=tuple(arrays.pop(name + "__csr_shape")),
                )
            attrs = dict(meta.get("attributes", {}))
            attrs.update(arrays)
            inst = cls._from_attributes(attrs)
            fp_path = os.path.join(path, "drift_baseline.bin")
            if os.path.exists(fp_path):
                from .monitor.fingerprint import Fingerprint

                with open(fp_path, "rb") as f:
                    inst._drift_baseline = Fingerprint.from_bytes(f.read())
        else:
            inst = cls()
        cls._restore_params(inst, meta)
        return inst

    @classmethod
    def read(cls):
        class _Reader:
            @staticmethod
            def load(path: str):
                return cls.load(path)

        return _Reader()


# ---------------------------------------------------------------------------
# _TpuCaller: shared fit-calling logic (reference _CumlCaller core.py:439)
# ---------------------------------------------------------------------------


class _TpuCaller(_TpuParams, _ReadWriteMixin):
    def _out_dtype(self, X: np.ndarray) -> np.dtype:
        # float64 stays float64 only when float32_inputs is disabled
        # (reference _float32_inputs handling, core.py:514-537).
        if X.dtype == np.float64 and not self._float32_inputs:
            return np.dtype(np.float64)
        return np.dtype(np.float32)

    def _require_p2p(self) -> bool:
        """Analog of `_require_nccl_ucx` (reference core.py:570-577): whether
        the kernel needs p2p-style all-to-all (exact kNN, DBSCAN)."""
        return False

    def _validate_device_input(self, ds: DeviceDataset) -> None:
        """Device-side analog of `_validate_input` for device-resident
        datasets (runs BEFORE any label dtype cast)."""

    def _fit_label_dtype(self) -> Optional[np.dtype]:
        return np.dtype(np.float32)

    def _use_sparse_kernel(self, batch: _ArrayBatch) -> bool:
        """Whether a sparse host batch should stage as ELL for a sparse
        kernel instead of densifying (the analog of `_use_sparse_in_cuml`,
        reference core.py:183-216).  Estimators with sparse kernels
        override; default densifies."""
        return False

    def _fit_streaming_csr(self, batch: _ArrayBatch) -> Optional[Dict[str, Any]]:
        """Fit from blocked-densify sufficient statistics over a host CSR
        batch (bounded host + device memory).  Estimators with streamed
        statistics (PCA, LinearRegression) override; default None means
        the generic whole-densify staging runs instead."""
        return None

    def _over_device_budget(self, need_bytes: float) -> bool:
        """Whether a staged dataset estimate exceeds the device-memory
        budget (or force_streaming_stats is set) — ONE formula for the
        parquet and sparse streamed-stats decisions AND the device-cache
        residency accounting (parallel/device_cache.py shares it via
        `device_data_budget_bytes`).  Bytes the cache holds RESIDENT
        count against the estimate — but residency is re-creatable, so
        entries are LRU-evicted first rather than pushing this fit onto
        the much slower streamed-statistics path while droppable data
        holds the room."""
        from .config import get_config
        from .parallel.device_cache import (
            cache_resident_bytes,
            device_data_budget_bytes,
            evict_to_fit,
        )
        from .telemetry.memory import record_budget_decision

        if bool(get_config("force_streaming_stats")):
            # the answer is True regardless — do not evict a warm cache
            # for a decision the force flag already made
            record_budget_decision("fit_dataset", need_bytes, True)
            return True
        budget = device_data_budget_bytes()
        if need_bytes + cache_resident_bytes() > budget:
            evict_to_fit(need_bytes, budget)
        over = need_bytes + cache_resident_bytes() > budget
        # the prediction side of budget_drift_ratio (telemetry/memory.py):
        # the measured peak watermark lands in the same fit report, so
        # the n_dev+2 gather factors and reservation math get checked
        # against the chips instead of stayed faith-based
        record_budget_decision("fit_dataset", need_bytes, over)
        return over

    def _supports_fold_weights(self) -> bool:
        """Whether this estimator's kernels honor the zero-weight-row
        contract (ops SUPPORTS_ZERO_WEIGHT_ROWS) AND its fit trajectory
        is row-count insensitive, so a CV fold may be selected by weight
        MASK over the resident full dataset instead of a gather view
        (parallel/device_cache.py).  Weight-capable deterministic solvers
        (LinearRegression, LogisticRegression, PCA) override to True;
        the default (gather/compaction fallback) is always correct."""
        return False

    def _sparse_over_budget(self, batch: _ArrayBatch) -> bool:
        """Whether a sparse batch's DENSE form exceeds the device budget
        — the sparse analog of the parquet streamed-stats decision."""
        from .data import _is_sparse

        if not _is_sparse(batch.X):
            return False
        n, d = batch.X.shape
        return self._over_device_budget(
            n * d * np.dtype(self._out_dtype(batch.X)).itemsize
        )

    def _maybe_fit_sparse_stats(
        self, batch: _ArrayBatch
    ) -> Optional[Dict[str, Any]]:
        """Route a sparse over-budget batch to the blocked-CSR statistics
        fit (reference keeps such data CSR end-to-end,
        classification.py:960-966)."""
        if not self._sparse_over_budget(batch):
            return None
        attrs = self._fit_streaming_csr(batch)
        if attrs is not None:
            self.logger.info(
                "Sparse dataset beyond the device budget: fit from "
                "blocked-CSR streamed statistics."
            )
        return attrs

    def _stage_fit_input(
        self,
        batch: _ArrayBatch,
        paramMaps: Optional[Sequence[Dict[str, Any]]] = None,
    ) -> FitInput:
        """Stage host arrays onto the mesh — the analog of the executor-side
        staging loop + CumlContext entry (reference core.py:886-994).

        In multi-process (pod) mode, `batch` holds only this process's LOCAL
        rows; the `RowStager` assembles the global sharded arrays without
        any process materializing the full dataset (the analog of each
        Spark barrier task staging its own partition)."""
        from .data import _is_sparse
        from .parallel.mesh import RowStager

        with TpuContext(self.num_workers, require_p2p=self._require_p2p()) as ctx:
            mesh = ctx.mesh
        n_dev = mesh.devices.size
        extra: Dict[str, Any] = {}
        if self._use_sparse_kernel(batch):
            import scipy.sparse as sp

            from .ops.sparse import ell_from_csr

            csr = (
                batch.X if _is_sparse(batch.X) else sp.csr_matrix(batch.X)
            )  # enable_sparse_data_optim=True forces sparse staging
            vals_host, cols_host = ell_from_csr(csr)
            import jax

            if jax.process_count() > 1:
                # the ELL width K is the LOCAL max nnz/row; processes must
                # agree on the global array shape, so widen to the global max
                from jax.experimental import multihost_utils

                k_all = np.asarray(
                    multihost_utils.process_allgather(
                        np.asarray(vals_host.shape[1], np.int64)
                    )
                ).reshape(-1)
                k_max = int(k_all.max())
                if vals_host.shape[1] < k_max:
                    # widen with the (0.0, col 0) no-op entries ell_from_csr
                    # uses for its own padding
                    pad = k_max - vals_host.shape[1]
                    vals_host = np.pad(vals_host, ((0, 0), (0, pad)))
                    cols_host = np.pad(cols_host, ((0, 0), (0, pad)))
            dtype = self._out_dtype(vals_host)
            st = RowStager(vals_host.shape[0], mesh)
            Xs = st.stage(vals_host, dtype)
            extra = {"ell_cols": st.stage(cols_host, np.int32)}
        else:
            X_host = _ensure_dense(batch.X)
            dtype = self._out_dtype(X_host)
            st = RowStager(X_host.shape[0], mesh)
            Xs = st.stage(X_host, dtype)
        n_padded = Xs.shape[0]
        w = st.mask(dtype, weights=batch.weight)
        y = None
        if batch.y is not None:
            ldt = self._fit_label_dtype() or dtype
            y = st.stage(np.asarray(batch.y).reshape(-1).astype(ldt), ldt)
        per_shard = [n_padded // n_dev] * n_dev
        pdesc = PartitionDescriptor.build(per_shard, int(batch.X.shape[1]))
        return FitInput(
            mesh=mesh,
            X=Xs,
            w=w,
            y=y,
            pdesc=pdesc,
            dtype=dtype,
            n_valid=st.n_valid,
            params=dict(self._tpu_params),
            extra=extra,
        )

    def _stage_from_device(self, ds: DeviceDataset) -> FitInput:
        """Zero-copy staging from an already-device-resident DeviceDataset
        (the cached-DataFrame fast path): only label dtype casts run, on
        device."""
        supervised = getattr(self, "_is_supervised", lambda: False)()
        if supervised and ds.y is None:
            raise ValueError("Supervised fit requires a DeviceDataset with labels")
        self._validate_device_input(ds)
        dtype = np.dtype(ds.X.dtype)
        y = ds.y
        ldt = self._fit_label_dtype() if supervised else None
        if y is not None and ldt is not None and np.dtype(y.dtype) != ldt:
            y = y.astype(ldt)
        n_dev = ds.mesh.devices.size
        per_shard = [ds.X.shape[0] // n_dev] * n_dev
        pdesc = PartitionDescriptor.build(per_shard, int(ds.X.shape[1]))
        return FitInput(
            mesh=ds.mesh,
            X=ds.X,
            w=ds.weight,
            y=y,
            pdesc=pdesc,
            dtype=dtype,
            n_valid=ds.n_valid,
            params=dict(self._tpu_params),
        )


# ---------------------------------------------------------------------------
# _TpuEstimator (reference _CumlEstimator core.py:1067)
# ---------------------------------------------------------------------------


class _TpuEstimator(Estimator, _TpuCaller):
    def __init__(self) -> None:
        super().__init__()
        self._init_tpu_params()
        self.logger = get_logger(type(self))

    # -- subclass contract ---------------------------------------------------

    @abstractmethod
    def _fit_array(self, fit_input: FitInput) -> Dict[str, Any]:
        """Run the distributed kernel, return host model attributes — the
        analog of the closure returned by `_get_cuml_fit_func`
        (e.g. reference classification.py:968-1221)."""

    @abstractmethod
    def _create_model(self, attrs: Dict[str, Any]) -> "_TpuModel":
        """Build the Model from fit attributes (reference
        `_create_pyspark_model` core.py:1267-1279)."""

    def _is_supervised(self) -> bool:
        return False

    def _validate_input(self, batch: _ArrayBatch) -> None:
        """Validate the raw host batch before dtype casting/staging (the
        analog of `_validate_parameters` + label checks, reference
        core.py:585-608)."""

    def _enable_fit_multiple_in_single_pass(self) -> bool:
        # Reference core.py:1172-1175.
        return True

    def _supports_cpu_fallback(self) -> bool:
        return self._cpu_fit is not _TpuEstimator._cpu_fit

    def _cpu_fit(self, batch: _ArrayBatch) -> "_TpuModel":
        """sklearn fallback fit (the reference falls back to pyspark.ml,
        core.py:1283-1297; without Spark the CPU engine is sklearn)."""
        raise NotImplementedError(
            f"{type(self).__name__} has no CPU fallback implementation"
        )

    # -- fit orchestration ---------------------------------------------------

    def _run_fit_kernel(
        self,
        fit_input: FitInput,
        restage: Optional[Callable[[], FitInput]] = None,
    ) -> Dict[str, Any]:
        """Dispatch the distributed fit kernel through the resilience
        layer (resilience/): the `fit_kernel` fault-injection site, the
        `guarded` watchdog (`dispatch_deadline_s` — a hang raises a typed
        DispatchTimeout instead of blocking the controller), and the
        configured RetryPolicy: transient RPC/DEADLINE errors back off and
        re-dispatch, OOM drops the failed dispatch's temporaries and
        re-dispatches, a preemption re-inits `jax.distributed` first —
        and iterative solvers with `checkpoint_dir` set then resume from
        their per-iteration checkpoint rather than iteration 0.

        `restage` is the elastic-recovery hook: when a DEVICE LOSS is
        recovered by shrinking the mesh (resilience/elastic.py), the
        staged inputs must move to the surviving devices before the
        re-dispatch — the callable rebuilds the FitInput against the
        degraded mesh (a fresh `_stage_fit_input` of the same host
        batch).  Without it (or when the recovery falls back to the
        full-retry path) the re-dispatch reuses the original staging."""
        from .resilience import guarded, maybe_inject, retry_call

        cell = {"fi": fit_input}
        # the cell owns the staging from here: dropping the parameter
        # binding (and callers not keeping their own locals) lets a
        # successful restage actually free the pre-loss arrays
        fit_input = None  # type: ignore[assignment]

        def _kernel() -> Dict[str, Any]:
            maybe_inject("fit_kernel")
            from .telemetry import utilization

            t0 = time.perf_counter()
            try:
                return self._fit_array(cell["fi"])
            finally:
                # the blocking kernel window is device activity on the
                # run's utilization timeline (telemetry/utilization.py):
                # the two-phase fit paths get a device-busy series even
                # though their solve is one opaque dispatch
                utilization.note_interval(
                    "device", t0, time.perf_counter(), cause="fit_kernel"
                )

        def _on_device_loss() -> None:
            from .resilience.elastic import recover_from_device_loss

            if recover_from_device_loss(self.logger) and restage is not None:
                # the old staging is held for fallback only: a restage
                # can itself fail (on real hardware a host round-trip
                # through arrays sharded over the dead chip raises) —
                # then the retry keeps the original staging and behaves
                # like the pre-elastic full retry instead of crashing
                # the fit with an opaque hook error
                old, cell["fi"] = cell["fi"], None
                from .tracing import trace

                try:
                    with trace("elastic_restage", self.logger):
                        cell["fi"] = restage()
                except Exception as e:
                    cell["fi"] = old
                    self.logger.warning(
                        f"Elastic restage failed ({type(e).__name__}: "
                        f"{e}); retrying with the original staging"
                    )

        return retry_call(
            lambda: guarded(_kernel, label="fit_kernel", log=self.logger),
            label="fit_kernel",
            log=self.logger,
            on_device_loss=_on_device_loss,
        )

    def _extract(self, dataset: DatasetLike) -> _ArrayBatch:
        features_col, features_cols = _resolve_feature_params(self)
        label_col = (
            self.getOrDefault("labelCol")
            if self._is_supervised() and self.hasParam("labelCol")
            else None
        )
        weight_col = (
            self.getOrDefault("weightCol")
            if self.hasParam("weightCol") and self.isSet("weightCol")
            else None
        )
        return extract_arrays(
            dataset,
            features_col=features_col,
            features_cols=features_cols,
            label_col=label_col,
            weight_col=weight_col,
            dtype=None,  # preserve input precision; _out_dtype decides
            supervised=self._is_supervised(),
        )

    # -- fused stage-and-solve (fused.py) ------------------------------------

    def _supports_fused_stats(self) -> bool:
        """Whether this estimator can fit from chunk-accumulated
        sufficient statistics folded in WHILE the data stages (the fused
        stage-and-solve engine, fused.py) — PCA/LinearRegression
        override.  Distinct from `_supports_streaming_stats` only in
        intent: the same statistics, but accumulated mesh-sharded with
        the host producer thread overlapped, for datasets that would
        otherwise stage fully and then solve."""
        return False

    def _fit_fused(self, batch: _ArrayBatch) -> Dict[str, Any]:
        """Fused fit of an in-memory host batch (estimators declaring
        `_supports_fused_stats` implement)."""
        raise NotImplementedError

    def _fit_fused_parquet(self, path: str) -> Dict[str, Any]:
        """Fused fit streaming chunks straight from parquet (the decode
        is the overlapped host prep)."""
        raise NotImplementedError

    def _maybe_fit_fused(
        self, source, est_bytes: Optional[float] = None
    ) -> Optional[Dict[str, Any]]:
        """Route an eligible fit through the fused stage-and-solve path
        (conf `fused_stage_solve`): sufficient statistics accumulate on
        the mesh as each chunk lands instead of staging everything and
        then solving.  Multi-process pods fuse too: each rank decodes
        only its row-group share (fused.process_row_group_shares), folds
        on its local devices, and the partials meet in one cross-process
        reduction at pass completion — the path degrades only when the
        reduce seam has no transport (parallel/context.py
        `cross_process_reduce_ready`).  Returns model attrs, or None to
        keep the two-phase path — sparse batches, conf off/below the
        auto threshold, and estimators without the capability all
        degrade.  `source` is a host `_ArrayBatch` or a parquet path.

        The dispatch runs under the retry policy with the accumulators
        treated as RE-CREATABLE state: any mid-pass failure (the
        `fused_accumulate` fault site — OOM, device loss) restarts the
        whole pass with fresh accumulators on the (possibly shrunken)
        mesh, never resuming half-accumulated sums, so a retried chunk
        can never double-count."""
        if not self._supports_fused_stats():
            return None
        from .fused import fused_enabled

        is_path = isinstance(source, str)
        if not is_path:
            from .data import _is_sparse

            if _is_sparse(source.X) or self._use_sparse_kernel(source):
                return None
            if est_bytes is None:
                est_bytes = (
                    int(source.X.shape[0])
                    * int(source.X.shape[1])
                    * np.dtype(self._out_dtype(source.X)).itemsize
                )
        if est_bytes is None or not fused_enabled(est_bytes):
            return None
        from .fused import fused_mode
        from .resilience import retry_call
        from .tracing import trace

        self.logger.info(
            "Fused stage-and-solve: accumulating sufficient statistics "
            "on the mesh while the data stages (fused_stage_solve="
            f"{fused_mode()}, ~{est_bytes / 2**20:.0f} MiB)."
        )
        with trace("fused_fit", self.logger):
            return retry_call(
                (lambda: self._fit_fused_parquet(source))
                if is_path
                else (lambda: self._fit_fused(source)),
                label="fused_fit",
                log=self.logger,
            )

    # -- streaming ingest (reference reserved-memory loader utils.py:403-522) --

    def _supports_streaming_stats(self) -> bool:
        """Whether `_fit_streaming` can fit from multi-pass streamed
        sufficient statistics (beyond-HBM datasets).  PCA/LinReg override."""
        return False

    def _fit_streaming(self, path: str) -> Dict[str, Any]:
        raise NotImplementedError

    def _streaming_io_params(self):
        features_col, features_cols = _resolve_feature_params(self)
        label_col = (
            self.getOrDefault("labelCol")
            if self._is_supervised() and self.hasParam("labelCol")
            else None
        )
        weight_col = (
            self.getOrDefault("weightCol")
            if self.hasParam("weightCol") and self.isSet("weightCol")
            else None
        )
        dtype = np.float32 if self._float32_inputs else np.float64
        return features_col, features_cols, label_col, weight_col, dtype

    def _stage_or_stream(self, path: str) -> Optional[Dict[str, Any]]:
        """Fit a parquet dataset without the controller ever holding the
        full array: multi-pass streaming stats when the data exceeds the
        device-memory budget (capable estimators only), else chunked
        stream-staging into HBM + the normal device-resident fit.  Returns
        model attrs, or None to fall back to in-memory extraction."""
        from .config import get_config
        from .streaming import (
            chunk_rows_for,
            parquet_row_count,
            probe_num_features,
            stage_parquet,
        )

        if (
            self.hasParam("enable_sparse_data_optim")
            and self.getOrDefault("enable_sparse_data_optim") is True
        ):
            return None  # CSR staging needs the host matrix
        fcol, fcols, label_col, weight_col, dtype = self._streaming_io_params()
        if self._supports_streaming_stats():
            n = parquet_row_count(path)
            d = probe_num_features(path, fcol, fcols)
            need = n * d * np.dtype(dtype).itemsize
            if self._over_device_budget(need):
                self.logger.info(
                    f"Dataset (~{need/2**30:.1f} GiB) beyond the device "
                    "budget or force_streaming_stats set; fitting from "
                    "multi-pass streamed statistics."
                )
                return self._run_streaming_fit(path)
            # within budget: the fused stage-and-solve path accumulates
            # the statistics while the parquet chunks decode — the
            # 220s-stage + 193s-solve additivity this collapses is the
            # refconfig gap (fused.py; conf fused_stage_solve)
            attrs = self._maybe_fit_fused(path, est_bytes=need)
            if attrs is not None:
                return attrs
        ds_dev = fit_input = None
        try:
            from .resilience import maybe_inject

            def _stage_all() -> FitInput:
                maybe_inject("stage_parquet")
                ds = stage_parquet(
                    path,
                    features_col=fcol,
                    features_cols=fcols,
                    label_col=label_col,
                    weight_col=weight_col,
                    num_workers=self.num_workers,
                    dtype=dtype,
                    label_dtype=self._fit_label_dtype() if label_col else None,
                    chunk_rows=None,
                )
                return self._stage_from_device(ds)

            # no local binding: the kernel runner's cell is the only
            # owner of the staging, so an elastic restage can free it.
            # Restage re-ingests the parquet chunks onto the degraded
            # mesh (the streaming reader re-resolves the mesh).
            return self._run_fit_kernel(_stage_all(), restage=_stage_all)
        except Exception as e:
            # drop the staged buffers BEFORE any retry — keeping them alive
            # would hold the very HBM whose exhaustion we are recovering from
            ds_dev = fit_input = None  # noqa: F841
            # OOM backoff (the analog of the reference's reserved-memory
            # retry loop, utils.py:403-522): fall back to the multi-pass
            # streamed-statistics fit when the estimator supports it
            if not _is_oom(e):
                raise
            if not self._supports_streaming_stats():
                raise RuntimeError(
                    "Dataset exceeds device memory while stream-staging and "
                    f"{type(self).__name__} cannot fit from streamed "
                    "statistics; raise num_workers (more chips) or reduce "
                    "the dataset"
                ) from e
        # the retry runs OUTSIDE the except block: while handling, the
        # interpreter's exception state (sys.exc_info) pins the solver's
        # inner frames via the traceback, whose locals reference the
        # staged device arrays — a retry inside the block would run with
        # the exhausted HBM still held (observed live: the refconfig
        # kmeans retry itself died RESOURCE_EXHAUSTED, BENCH_r05 first
        # capture).  Leaving the block pops the exception and frees them.
        import gc

        # resident cache entries are re-creatable; they must not starve
        # an OOM recovery (the registry's claim is dropped — in-flight
        # consumers of an entry keep their views alive)
        from .parallel.device_cache import clear_device_cache

        clear_device_cache()
        gc.collect()
        self.logger.warning(
            "Device staging exhausted HBM; retrying as a "
            "multi-pass streaming-statistics fit."
        )
        return self._run_streaming_fit(path)

    def _run_streaming_fit(self, path: str) -> Dict[str, Any]:
        """Dispatch a multi-pass streaming fit through the retry policy.
        Streaming fits re-resolve the mesh and re-stage every chunk each
        epoch, so a device-loss recovery needs no explicit restage hook:
        the re-dispatched fit lands on the degraded mesh by construction
        and (with `checkpoint_dir` set) resumes from its last completed
        iteration."""
        from .resilience import retry_call

        return retry_call(
            lambda: self._fit_streaming(path),
            label="fit_streaming",
            log=self.logger,
        )

    def _fit(self, dataset: DatasetLike) -> "_TpuModel":
        if self._use_cpu_fallback():
            self.logger.warning(
                "Unsupported params set; falling back to CPU (sklearn) fit "
                "(analog of spark.rapids.ml.cpu.fallback, reference core.py:1283-1297)."
            )
            if isinstance(dataset, DeviceDataset):
                batch = dataset.to_host_batch()
            else:
                batch = self._extract(dataset)
            self._validate_input(batch)
            model = self._cpu_fit(batch)
            self._copyValues(model)
            return model
        t0 = time.time()
        from .tracing import device_profile, trace

        # large Spark DataFrames route around the controller: executors
        # write parquet to the exchange dir and the streaming-ingest path
        # below takes over (spark_interop.spark_dataframe_to_staging)
        from .spark_interop import is_spark_dataframe

        exchange_cleanup = None
        if is_spark_dataframe(dataset):
            from .spark_interop import spark_dataframe_to_staging

            dataset, exchange_cleanup = spark_dataframe_to_staging(dataset)
        attrs = None
        try:
            with device_profile():
                if isinstance(dataset, DeviceDataset):
                    with trace("stage_from_device", self.logger):
                        # single-element hand-off: popping below leaves
                        # the kernel runner's cell as the only owner, so
                        # an elastic restage can free the old staging
                        staged = [self._stage_from_device(dataset)]
                    with trace("fit_kernel", self.logger):
                        # elastic restage: the resident DeviceDataset is
                        # sharded over the PRE-loss mesh, so a recovery
                        # must round-trip through the host to land the
                        # rows on the survivors (that fetch can fail on
                        # real hardware — the runner then falls back to
                        # the original staging)
                        attrs = self._run_fit_kernel(
                            staged.pop(),
                            restage=lambda: self._stage_fit_input(
                                dataset.to_host_batch()
                            ),
                        )
                else:
                    from .config import get_config
                    from .streaming import is_parquet_path

                    if is_parquet_path(dataset) and get_config("streaming_ingest"):
                        with trace("stream_ingest_fit", self.logger):
                            attrs = self._stage_or_stream(dataset)
                    if attrs is None:
                        with trace("extract", self.logger):
                            batch = self._extract(dataset)
                            self._validate_input(batch)
                        from .data import _is_sparse as _sparse_chk
                        from .monitor.baseline import (
                            baseline_mode,
                            fold_batch,
                        )

                        if (
                            baseline_mode() == "on"
                            and not _sparse_chk(batch.X)
                            and np.ndim(batch.X) == 2
                        ):
                            # conf "on": in-memory fits capture their
                            # baseline from one host pass over the
                            # extracted batch (no staging, no device
                            # work; the chunked paths still prefer
                            # their zero-cost chunk fold — fold_batch
                            # no-ops once a pass has captured)
                            fold_batch(batch.X, batch.weight)
                        attrs = self._maybe_fit_sparse_stats(batch)
                    if attrs is None:
                        # fused stage-and-solve for in-memory host
                        # batches: statistics accumulate chunk-by-chunk
                        # as the rows land on the mesh (fused.py) —
                        # None keeps the two-phase stage-then-solve path
                        attrs = self._maybe_fit_fused(batch)
                    if attrs is None:
                        with trace("stage", self.logger):
                            # hand-off list: see the DeviceDataset branch
                            staged = [self._stage_fit_input(batch)]
                        with trace("fit_kernel", self.logger):
                            attrs = self._run_fit_kernel(
                                staged.pop(),
                                restage=lambda: self._stage_fit_input(batch),
                            )
        finally:
            if exchange_cleanup:
                import shutil

                shutil.rmtree(exchange_cleanup, ignore_errors=True)
        model = self._create_model(attrs)
        self._copyValues(model)
        model._num_workers = self._num_workers
        model._float32_inputs = self._float32_inputs
        self.logger.info(f"Finished fit in {time.time() - t0:.3f}s")
        return model

    def fitMultiple(
        self, dataset: DatasetLike, paramMaps: Sequence[Dict[Param, Any]]
    ) -> Iterator[Tuple[int, "_TpuModel"]]:
        """Fit one model per param map in a SINGLE pass over the data: the
        dataset is staged onto the mesh once and every param map re-runs the
        (cached-compile) kernel on the resident device arrays — the analog of
        the reference's single-pass fitMultiple (core.py:1177-1228,
        `_FitMultipleIterator` core.py:1022-1064)."""
        estimator = self.copy()

        single_pass = estimator._enable_fit_multiple_in_single_pass()
        batch = None
        if (
            single_pass
            and not isinstance(dataset, DeviceDataset)
            and type(estimator)._fit_streaming_csr
            is not _TpuCaller._fit_streaming_csr
        ):
            # extract ONCE: the same batch either proves the dataset is a
            # sparse over-budget one (per-model fits route each map
            # through the blocked-CSR statistics path; whole-densify
            # staging is impossible) or is reused for staging below
            batch = estimator._extract(dataset)
            if estimator._sparse_over_budget(batch):
                single_pass = False

        if single_pass:
            if isinstance(dataset, DeviceDataset):
                staged = {"fi": estimator._stage_from_device(dataset)}

                def _restage() -> FitInput:
                    return estimator._stage_fit_input(dataset.to_host_batch())

            else:
                if batch is None:
                    batch = estimator._extract(dataset)
                estimator._validate_input(batch)
                staged = {"fi": estimator._stage_fit_input(batch)}

                def _restage() -> FitInput:
                    return estimator._stage_fit_input(batch)

            def fit_single(index: int) -> Tuple[int, "_TpuModel"]:
                from .tracing import run_context

                est_i = estimator.copy(paramMaps[index])

                def _with_params(fi: FitInput) -> FitInput:
                    return FitInput(
                        **{**fi.__dict__, "params": dict(est_i._tpu_params)}
                    )

                def _elastic_restage() -> FitInput:
                    # elastic device-loss recovery mid-grid: re-stage
                    # onto the degraded mesh and PUBLISH the new staging
                    # so the remaining param maps fit from it instead of
                    # the arrays sharded over the lost device (a benign
                    # race: a concurrent fit holding the old staging
                    # just fails once more and restages again)
                    staged["fi"] = _restage()
                    return _with_params(staged["fi"])

                # one run_id per grid member, so a retry/recovery inside
                # fitMultiple attributes to the param map it interrupted
                with run_context(prefix="fit"):
                    attrs = est_i._run_fit_kernel(
                        _with_params(staged["fi"]), restage=_elastic_restage
                    )
                    model = est_i._create_model(attrs)
                est_i._copyValues(model, paramMaps[index])
                return index, model

        else:

            def fit_single(index: int) -> Tuple[int, "_TpuModel"]:
                return index, estimator.fit(dataset, paramMaps[index])

        return _FitMultipleIterator(fit_single, len(paramMaps))

    def _cached_fit_entry(self, dataset: DatasetLike):
        """Resident-cache entry for `dataset` (parallel/device_cache.py):
        extract + validate the host batch, fingerprint it, and return the
        cached staged arrays — staging ONCE on a miss.  Returns None (the
        caller keeps the legacy host-slicing path) when the cache is off,
        the run is multi-process, a CPU fallback/sparse kernel is
        selected, or the entry exceeds the residency budget."""
        from .parallel.device_cache import cache_enabled, get_or_stage

        if not cache_enabled():
            return None
        import jax

        if jax.process_count() > 1:
            # fold views index the GLOBAL staged layout; the per-process
            # block layout is not derivable host-side — legacy path
            return None
        if self._use_cpu_fallback():
            return None
        if not self._enable_fit_multiple_in_single_pass():
            return None
        from .data import _is_sparse

        batch = self._extract(dataset)
        if _is_sparse(batch.X) or self._use_sparse_kernel(batch):
            return None  # dense resident views only (ELL staging differs)
        self._validate_input(batch)
        X = _ensure_dense(batch.X)
        dtype = self._out_dtype(X)
        ldt = self._fit_label_dtype() if self._is_supervised() else None
        from .parallel.mesh import get_mesh

        # EVERY cached CV run gathers at least its eval rows per fold
        # (and gather-path estimators their train views too), and the
        # cross-shard take lowers to an XLA all-gather that transiently
        # replicates the full resident array on every device (~n_dev x
        # cluster-wide) plus the compacted view itself; reserve that
        # headroom up front — mask path included — or the per-fold
        # gather OOMs after the budget check said yes
        factor = float(get_mesh(self.num_workers).devices.size + 2)
        return get_or_stage(
            np.asarray(X, dtype=X.dtype),
            batch.y,
            batch.weight,
            dtype=dtype,
            label_dtype=ldt,
            num_workers=self.num_workers,
            logger=self.logger,
            working_factor=factor,
        )


class _FitMultipleIterator:
    """Thread-safe (index, model) iterator (reference core.py:1022-1064)."""

    def __init__(self, fitSingleModel: Callable[[int], Tuple[int, Any]], numModels: int):
        self.fitSingleModel = fitSingleModel
        self.numModels = numModels
        self.counter = 0
        self.lock = named_lock("fit_multiple")

    def __iter__(self) -> "_FitMultipleIterator":
        return self

    def __next__(self) -> Tuple[int, Any]:
        with self.lock:
            index = self.counter
            if index >= self.numModels:
                raise StopIteration("No models remaining.")
            self.counter += 1
        return self.fitSingleModel(index)


class _TpuEstimatorSupervised(_TpuEstimator):
    """Supervised variant (reference _CumlEstimatorSupervised core.py:1314)."""

    def _is_supervised(self) -> bool:
        return True


# ---------------------------------------------------------------------------
# _TpuModel (reference _CumlModel core.py:1356, _CumlModelWithColumns
# core.py:1756, _CumlModelWithPredictionCol core.py:1957)
# ---------------------------------------------------------------------------


class _TpuModel(Model, _TpuCaller):
    def __init__(self, **model_attributes: Any) -> None:
        super().__init__()
        self._init_tpu_params()
        self._model_attributes = model_attributes
        self.logger = get_logger(type(self))

    def _get_model_attributes(self) -> Dict[str, Any]:
        return self._model_attributes

    @classmethod
    def _from_attributes(cls, attrs: Dict[str, Any]) -> "_TpuModel":
        return cls(**attrs)

    # -- transform contract --------------------------------------------------

    def _transform_device(self, Xs: Any) -> Optional[Dict[str, Any]]:
        """Device-side transform: map a row-sharded (n_pad, d) device
        feature block to `{col: device array}` outputs (row-leading shapes).
        Row-wise models implement this; the base `_transform_array` then
        runs it data-parallel over the mesh in host-bounded chunks — the
        analog of the reference's partition-parallel `pandas_udf` transform
        (core.py:1846-1881).  Models that manage their own staging (DBSCAN,
        UMAP, kNN) leave it unimplemented."""
        return None

    def _transform_array(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        """Map a host feature block to output columns ({col_name: values}).
        Default: the distributed batched driver over `_transform_device`.
        The analog of the per-batch predict closure from
        `_get_cuml_transform_func` (reference core.py:1846-1881)."""
        outs = self._transform_mesh(X)
        if outs is None:
            raise NotImplementedError(
                f"{type(self).__name__} implements neither _transform_array "
                "nor _transform_device"
            )
        return outs

    def _fetch_transform_outputs(self, st, dev) -> Dict[str, np.ndarray]:
        """Fetch a `_transform_device` output dict back to host: device
        arrays trim their padding and restore the input row order via
        the staging layout (`RowStager.fetch`); host-computed outputs
        (degenerate-model paths) head-trim.  The one fetch contract
        shared by the chunked `_transform_mesh` driver below and the
        serving dispatcher (serving/server.py), which stages coalesced
        micro-batches itself and reuses the model's compiled
        `_transform_device` program over them."""
        import jax

        # one compute sync for ALL columns before the per-column fetch:
        # fetching column-by-column would serialize each column's
        # compute wait behind the previous column's transfer — on the
        # serving collect path that wait bills to the collect worker's
        # window instead of overlapping with later columns' compute
        dev_arrays = [v for v in dev.values() if isinstance(v, jax.Array)]
        if dev_arrays:
            jax.block_until_ready(dev_arrays)
        return {
            col: (
                st.fetch(v)
                if isinstance(v, jax.Array)
                else st.trim_host(np.asarray(v))
            )
            for col, v in dev.items()
        }

    def _transform_mesh(self, X: np.ndarray) -> Optional[Dict[str, np.ndarray]]:
        """Distributed, batched inference (reference strategy 6, SURVEY
        §2.12: non-barrier data-parallel transform).  Rows are chunked by
        the `host_batch_bytes` budget, each chunk staged row-sharded over
        the mesh, and the model's `_transform_device` runs SPMD — transform
        throughput scales with mesh size and one chip never holds more
        than a chunk.  Multi-process: every process stages its block of the
        (replicated) input and fetch reassembles global rows."""
        if type(self)._transform_device is _TpuModel._transform_device:
            return None
        import jax

        from .data import _is_sparse
        from .parallel.mesh import RowStager, get_mesh
        from .streaming import chunk_rows_for

        sparse_in = _is_sparse(X)
        if sparse_in:
            # keep CSR; each chunk densifies separately below, so peak
            # host memory is one dense chunk (not the whole matrix)
            X = X.tocsr()
            x_dtype = self._out_dtype(X)
        else:
            X = _ensure_dense(X)
            x_dtype = X.dtype
        n = int(X.shape[0])
        d = int(X.shape[1]) if X.ndim == 2 else 1
        mesh = get_mesh(
            self._num_workers if jax.process_count() == 1 else None
        )
        from .config import get_config
        from .parallel.mesh import bucket_rows_floor

        # floor the chunk to the bucket grid: full chunks then carry ZERO
        # bucket padding and still share one compilation; only the tail
        # chunk buckets up (moot when bucketing is off)
        chunk = max(
            int(chunk_rows_for(d, np.dtype(x_dtype).itemsize)),
            mesh.devices.size,
        )
        if get_config("shape_bucketing"):
            chunk = max(bucket_rows_floor(chunk), mesh.devices.size)
        if n == 0:
            # transform one dummy row, trim everything (static-shape kernels
            # can't run on 0 rows)
            dummy = self._transform_mesh(np.zeros((1, d), x_dtype))
            return {c: v[:0] for c, v in dummy.items()}
        from .tracing import trace

        n_dev = mesh.devices.size

        def _floor_chunk(c: int) -> int:
            """Keep a (re)halved chunk on the bucket grid so full chunks
            stay zero-bucket-padding (the invariant the initial floor
            above establishes)."""
            c = max(c, n_dev)
            if get_config("shape_bucketing"):
                c = max(bucket_rows_floor(c), n_dev)
            return c

        outs: Dict[str, List[np.ndarray]] = {}
        lo = 0
        def _dispatch(lo: int):
            """Stage one chunk and launch its device program (ASYNC — jax
            dispatch returns with the transfer/compute in flight)."""
            from .resilience import maybe_inject

            maybe_inject("transform_dispatch")
            hi = min(lo + chunk, n)
            with trace(f"dispatch_chunk[{lo}:{hi}]", self.logger):
                if sparse_in:
                    from .native import densify_csr

                    Xc = densify_csr(X[lo:hi], hi - lo, x_dtype)
                else:
                    Xc = np.ascontiguousarray(X[lo:hi])
                st = RowStager.for_replicated(Xc.shape[0], mesh)
                dev = self._transform_device(st.stage(Xc, x_dtype))
            return lo, hi, st, dev

        def _collect(pending) -> None:
            """Fetch one in-flight chunk (the sync point) and publish it
            whole: a failure on a later column must not leave earlier
            columns appended (the retry would duplicate their rows)."""
            lo_p, hi_p, st, dev = pending
            with trace(f"transform_chunk[{lo_p}:{hi_p}]", self.logger):
                fetched = self._fetch_transform_outputs(st, dev)
            for col, v in fetched.items():
                outs.setdefault(col, []).append(v)

        # one-deep pipeline: chunk i+1's host->device transfer rides the
        # wire while chunk i computes and fetches — on transfer-dominated
        # attachments (the axon tunnel) this overlaps the two directions
        # instead of serializing stage -> compute -> fetch per chunk.
        # Two chunks are in flight, so each gets HALF the single-chunk
        # budget (same peak device footprint as the serial loop), re-floored
        # to the bucket grid
        chunk = _floor_chunk(chunk // 2)
        # recovery is policy-driven (resilience/retry.py): OOM halves the
        # chunk (the policy's shrink-batch action, bounded by the n_dev
        # floor) while transient/preemption errors back off and re-dispatch
        # the SAME chunk size, bounded by max_attempts since the last
        # successfully published chunk
        from .resilience import RetryPolicy

        policy = RetryPolicy.from_config()
        transient_attempts = 0
        pending = None
        while lo < n or pending is not None:
            current = None  # a dispatch failure must not reuse last round's
            try:
                current = _dispatch(lo) if lo < n else None
                if lo < n:
                    lo = current[1]
                if pending is not None:
                    _collect(pending)
                    transient_attempts = 0  # progress resets the budget
                pending = current
            except Exception as e:
                # async errors surface at the fetch, so both in-flight
                # chunks are discarded and re-run from the first
                # unpublished row (completed chunks are kept — the analog
                # of the reference's reserved-memory OOM loop,
                # utils.py:403-522)
                action = policy.classify(e)
                if action == "fatal" or (action == "oom" and chunk <= n_dev):
                    raise
                if action != "oom":
                    transient_attempts += 1
                    if transient_attempts >= policy.max_attempts:
                        raise
                resume_at = pending[0] if pending is not None else (
                    current[0] if current is not None else lo
                )
                to_drain, pending, current = (pending, current), None, None
            else:
                continue
            # the recovery runs OUTSIDE the except block (same
            # poisoned-buffer rule as _stage_or_stream: the exception
            # state pins the failed dispatch's frames, and its locals
            # reference the very device buffers being recovered).
            # Drain the discarded in-flight programs BEFORE the retry:
            # dropping the refs only queues deletion, and an immediate
            # re-dispatch would contend with their unfreed buffers.
            # OOM ONLY: after a preemption the backing runtime is gone and
            # after a watchdog timeout the program is by definition still
            # hung — block_until_ready on either can block forever, which
            # is the very hang class this layer removes
            if action == "oom":
                for inflight in to_drain:
                    if inflight is None:
                        continue
                    for v in inflight[3].values():
                        if isinstance(v, jax.Array):
                            try:
                                v.block_until_ready()
                            except Exception:
                                pass  # the original error already surfaced
            lo = resume_at
            from .resilience.retry import RETRIES
            from .tracing import event

            # same counter family as retry_call: the inline chunk loop
            # must not diverge from the policy wrapper in the metrics
            RETRIES.inc(label="transform_dispatch", action=action)
            event(
                "retry[transform_dispatch]",
                detail=f"action={action} resume_row={lo}",
                log=self.logger,
            )
            if action == "oom":
                # drop re-creatable cache residency before shrinking the
                # chunk — the resident entries may BE the pressure
                from .parallel.device_cache import clear_device_cache

                clear_device_cache()
                chunk = _floor_chunk(chunk // 2)
                self.logger.warning(
                    f"Transform chunk exhausted device memory; resuming at "
                    f"row {lo} with chunk={chunk} rows"
                )
            elif action == "preemption":
                from .resilience.retry import _default_preemption_hook

                # the fit path's repair hook: reinit_distributed guarded so
                # a failed re-bootstrap still lets the retry run
                _default_preemption_hook()
                self.logger.warning(
                    f"Transform dispatch preempted; resuming at row {lo}"
                )
            elif action == "device_loss":
                from .resilience.elastic import recover_from_device_loss

                if recover_from_device_loss(self.logger):
                    # shrink to the surviving mesh: every remaining chunk
                    # stages fresh per dispatch, so adopting the rebuilt
                    # mesh is the whole repair (no resident state to move)
                    mesh = get_mesh(
                        self._num_workers if jax.process_count() == 1 else None
                    )
                    n_dev = mesh.devices.size
                    chunk = _floor_chunk(chunk)
                self.logger.warning(
                    f"Transform dispatch lost a device; resuming at row "
                    f"{lo} on {mesh.devices.size} device(s)"
                )
            else:  # transient
                delay = policy.backoff(transient_attempts)
                self.logger.warning(
                    f"Transform dispatch failed transiently; retrying row "
                    f"{lo} in {delay:.2f}s "
                    f"({transient_attempts}/{policy.max_attempts - 1} "
                    "retries since last progress)"
                )
                time.sleep(delay)
        if all(len(v) == 1 for v in outs.values()):
            return {c: v[0] for c, v in outs.items()}
        return {c: np.concatenate(v, axis=0) for c, v in outs.items()}

    def _output_columns(self) -> List[str]:
        if self.hasParam("predictionCol"):
            return [self.getOrDefault("predictionCol")]
        return ["prediction"]

    def _transform(self, dataset: DatasetLike):
        """Append output columns to a pandas DataFrame input, or return the
        primary output array for array input (reference
        `_CumlModelWithColumns._transform` core.py:1797-1941).  Spark
        DataFrames round-trip through Arrow and come back as Spark
        DataFrames (spark_interop.py)."""
        import pandas as pd

        from .spark_interop import is_spark_dataframe

        if is_spark_dataframe(dataset):
            from .spark_interop import pandas_to_spark, spark_dataframe_to_pandas

            out_pdf = self._transform(spark_dataframe_to_pandas(dataset))
            return pandas_to_spark(out_pdf, dataset)

        if isinstance(dataset, pd.DataFrame) and len(dataset) == 0:
            # empty input transforms to empty output (Spark semantics)
            out_df = dataset.copy()
            for col in self._output_columns():
                out_df[col] = []
            return out_df
        features_col, features_cols = _resolve_feature_params(self)
        batch = extract_arrays(
            dataset,
            features_col=features_col,
            features_cols=features_cols,
            dtype=None,
            supervised=False,
        )
        from .data import _is_sparse

        if _is_sparse(batch.X) and (
            type(self)._transform_device is not _TpuModel._transform_device
            or getattr(self, "_accepts_sparse_transform", False)
        ):
            # keep CSR: _transform_mesh densifies chunk-by-chunk, so peak
            # host memory is one dense chunk instead of the whole matrix
            outputs = self._transform_array(batch.X)
        else:
            X = _ensure_dense(batch.X)
            dtype = self._out_dtype(X)
            outputs = self._transform_array(np.asarray(X, dtype=dtype))
        if isinstance(dataset, pd.DataFrame):
            out_df = dataset.copy()
            for col, values in outputs.items():
                vals: Any = values
                if isinstance(values, np.ndarray) and values.ndim == 2:
                    vals = list(values)
                out_df[col] = vals
            return out_df
        if len(outputs) == 1:
            return next(iter(outputs.values()))
        return outputs

    # -- multi-model single-pass evaluation (reference core.py:1572-1753) ----

    @classmethod
    def _combine(cls, models: List["_TpuModel"]) -> "_CombinedModel":
        """Merge N models (one per param map) into one multi-model for
        single-pass eval (reference `_CumlModel._combine` core.py:1750-1753)."""
        return _CombinedModel(models)

    def _transformEvaluate(self, dataset: DatasetLike, evaluator: Any) -> List[float]:
        """Transform + metric in one logical pass (reference
        `_transformEvaluate` core.py:1725-1748).  A `CachedEvalView`
        scores against the RESIDENT device rows — no eval restaging."""
        from .parallel.device_cache import CachedEvalView

        if isinstance(dataset, CachedEvalView):
            return dataset.evaluate([self], evaluator)
        return [evaluator.evaluate(self.transform(dataset))]

    def cpu(self):
        """Equivalent sklearn model (the reference returns the pyspark.ml
        model, e.g. utils.py:585-809 tree translation)."""
        raise NotImplementedError


def _evaluate_frame(model: "_TpuModel", dataset: DatasetLike):
    """Shared front half of the Model.evaluate() surfaces (LogReg, LinReg,
    RandomForestClassifier): coerce to pandas, validate label/weight
    columns, run the standard `_transform`, and return
    `(out_df, labels, predictions, weights)`."""
    import pandas as pd

    from .data import _to_pandas

    pdf = dataset if isinstance(dataset, pd.DataFrame) else _to_pandas(dataset)
    label_col = model.getOrDefault("labelCol")
    if label_col not in pdf.columns:
        raise ValueError(f"evaluate requires the label column '{label_col}'")
    if len(pdf) == 0:
        raise ValueError("Dataset is empty: nothing to evaluate")
    out_df = model._transform(pdf)
    y = np.asarray(out_df[label_col], np.float64)
    preds = np.asarray(
        out_df[model.getOrDefault("predictionCol")], np.float64
    )
    weights = None
    if model.hasParam("weightCol") and model.isSet("weightCol"):
        wc = model.getOrDefault("weightCol")
        if wc not in out_df.columns:
            raise ValueError(
                f"weightCol '{wc}' is set on the model but absent from "
                "the evaluation dataset"
            )
        weights = np.asarray(out_df[wc], np.float64)
    return out_df, y, preds, weights


class _CombinedModel:
    """N models evaluated against one dataset staging (the analog of the
    reference's multi-model `_transform_evaluate_internal` pass with
    model_index partial-metric rows, core.py:1572-1693).  The input frame is
    materialized once; each member model's (compile-cached) transform runs
    over the same host arrays."""

    def __init__(self, models: List[_TpuModel]) -> None:
        if not models:
            raise ValueError("_combine requires at least one model")
        self.models = list(models)

    def _transformEvaluate(self, dataset: DatasetLike, evaluator: Any) -> List[float]:
        from .parallel.device_cache import CachedEvalView

        if isinstance(dataset, CachedEvalView):
            # every member model scores the RESIDENT sharded rows; only
            # the fold's output columns come back to host
            return dataset.evaluate(self.models, evaluator)
        import pandas as pd

        if not isinstance(dataset, pd.DataFrame):
            return [evaluator.evaluate(m.transform(dataset)) for m in self.models]
        # extract the feature matrix ONCE; every member model transforms the
        # same resident arrays (kernel compilations are shared)
        m0 = self.models[0]
        features_col, features_cols = _resolve_feature_params(m0)
        batch = extract_arrays(
            dataset,
            features_col=features_col,
            features_cols=features_cols,
            dtype=None,
            supervised=False,
        )
        from .data import _is_sparse

        keep_sparse = _is_sparse(batch.X) and all(
            type(m)._transform_device is not _TpuModel._transform_device
            for m in self.models
        )
        X = batch.X if keep_sparse else _ensure_dense(batch.X)
        results = []
        for m in self.models:
            outputs = m._transform_array(
                X if keep_sparse else np.asarray(X, dtype=m._out_dtype(X))
            )
            cols: Dict[str, Any] = {}
            for col, values in outputs.items():
                vals: Any = values
                if isinstance(values, np.ndarray) and values.ndim == 2:
                    vals = list(values)
                cols[col] = vals
            # no per-model deep copy of the input frame (round-1 review):
            # reference the original columns and append the outputs
            base = dataset
            overlap = [c for c in cols if c in dataset.columns]
            if overlap:
                base = dataset.drop(columns=overlap)
            # pandas>=3 copy-on-write: concat is lazy, no deep copy happens
            out_df = pd.concat(
                [base, pd.DataFrame(cols, index=dataset.index)], axis=1
            )
            results.append(evaluator.evaluate(out_df))
        return results
