#
# Param system — the analog of the reference's params.py (719 LoC): a
# pyspark.ml-style `Param`/`Params` implementation (standalone, no pyspark
# dependency) plus the declarative Spark-name -> backend-name mapping layer
# (`_CumlClass`/`_CumlParams`, reference params.py:162-257 / 260-707), here
# `_TpuClass`/`_TpuParams`.  The backend param dict is `_tpu_params` (the
# analog of `_cuml_params`) and the CPU fallback engine is scikit-learn.
#
from __future__ import annotations

import copy
from abc import ABC
from typing import Any, Callable, Dict, List, Optional, TypeVar, Union

from .config import get_config
from .utils import get_logger

P = TypeVar("P", bound="Params")


class TypeConverters:
    """Minimal pyspark.ml.param.TypeConverters equivalent."""

    @staticmethod
    def toInt(value: Any) -> int:
        if isinstance(value, bool):
            raise TypeError(f"Could not convert {value} to int")
        return int(value)

    @staticmethod
    def toFloat(value: Any) -> float:
        return float(value)

    @staticmethod
    def toBoolean(value: Any) -> bool:
        if isinstance(value, bool):
            return value
        raise TypeError(f"Boolean Param requires value of type bool. Found {type(value)}.")

    @staticmethod
    def toString(value: Any) -> str:
        return str(value)

    @staticmethod
    def toList(value: Any) -> list:
        return list(value)

    @staticmethod
    def toListInt(value: Any) -> List[int]:
        return [TypeConverters.toInt(v) for v in value]

    @staticmethod
    def toListFloat(value: Any) -> List[float]:
        return [float(v) for v in value]

    @staticmethod
    def toListString(value: Any) -> List[str]:
        return [str(v) for v in value]

    @staticmethod
    def toDict(value: Any) -> dict:
        # Reference DictTypeConverters (params.py:710-719).
        return dict(value)

    @staticmethod
    def identity(value: Any) -> Any:
        return value


class Param:
    """A param with self-contained documentation (pyspark.ml.param.Param)."""

    def __init__(
        self,
        parent: Union["Params", str],
        name: str,
        doc: str,
        typeConverter: Optional[Callable[[Any], Any]] = None,
    ):
        self.parent = parent.uid if isinstance(parent, Params) else parent
        self.name = name
        self.doc = doc
        self.typeConverter = typeConverter or TypeConverters.identity

    def _copy_new_parent(self, parent: "Params") -> "Param":
        p = copy.copy(self)
        p.parent = parent.uid
        return p

    def __str__(self) -> str:
        return f"{self.parent}__{self.name}"

    def __repr__(self) -> str:
        return f"Param(parent={self.parent!r}, name={self.name!r})"

    def __hash__(self) -> int:
        return hash(str(self))

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Param) and str(self) == str(other)


_uid_counters: Dict[str, int] = {}


def _gen_uid(cls_name: str) -> str:
    n = _uid_counters.get(cls_name, 0)
    _uid_counters[cls_name] = n + 1
    return f"{cls_name}_{n:04x}"


class Params(ABC):
    """pyspark.ml.param.Params-compatible base: a components container for
    params with user-set values and defaults.  Param objects are declared as
    class attributes with a string parent and re-bound per instance."""

    def __init__(self) -> None:
        self.uid = _gen_uid(type(self).__name__)
        self._paramMap: Dict[Param, Any] = {}
        self._defaultParamMap: Dict[Param, Any] = {}
        self._params: Optional[List[Param]] = None
        self._copy_class_params()

    def _copy_class_params(self) -> None:
        for name in dir(type(self)):
            attr = getattr(type(self), name, None)
            if isinstance(attr, Param):
                setattr(self, name, attr._copy_new_parent(self))

    @property
    def params(self) -> List[Param]:
        if self._params is None:
            self._params = sorted(
                [
                    getattr(self, x)
                    for x in dir(self)
                    if x != "params" and isinstance(getattr(self, x, None), Param)
                ],
                key=lambda p: p.name,
            )
        return self._params

    def hasParam(self, paramName: str) -> bool:
        return isinstance(getattr(self, paramName, None), Param)

    def getParam(self, paramName: str) -> Param:
        p = getattr(self, paramName, None)
        if not isinstance(p, Param):
            raise ValueError(f"Cannot find param with name {paramName}.")
        return p

    def _resolveParam(self, param: Union[str, Param]) -> Param:
        return self.getParam(param) if isinstance(param, str) else param

    def isSet(self, param: Union[str, Param]) -> bool:
        return self._resolveParam(param) in self._paramMap

    def hasDefault(self, param: Union[str, Param]) -> bool:
        return self._resolveParam(param) in self._defaultParamMap

    def isDefined(self, param: Union[str, Param]) -> bool:
        return self.isSet(param) or self.hasDefault(param)

    def getOrDefault(self, param: Union[str, Param]) -> Any:
        param = self._resolveParam(param)
        if param in self._paramMap:
            return self._paramMap[param]
        if param in self._defaultParamMap:
            return self._defaultParamMap[param]
        raise KeyError(f"Param {param.name} is neither set nor has a default value.")

    def get(self, param: Union[str, Param]) -> Any:
        return self.getOrDefault(param)

    def _set(self, **kwargs: Any) -> "Params":
        for name, value in kwargs.items():
            p = self.getParam(name)
            if value is not None:
                try:
                    value = p.typeConverter(value)
                except (TypeError, ValueError) as e:
                    raise TypeError(f'Invalid param value given for param "{name}". {e}')
            self._paramMap[p] = value
        return self

    def set(self, param: Union[str, Param], value: Any) -> "Params":
        param = self._resolveParam(param)
        return self._set(**{param.name: value})

    def _setDefault(self, **kwargs: Any) -> "Params":
        for name, value in kwargs.items():
            p = self.getParam(name)
            self._defaultParamMap[p] = value
        return self

    def clear(self, param: Union[str, Param]) -> None:
        param = self._resolveParam(param)
        self._paramMap.pop(param, None)

    def extractParamMap(self, extra: Optional[Dict[Param, Any]] = None) -> Dict[Param, Any]:
        pm = dict(self._defaultParamMap)
        pm.update(self._paramMap)
        if extra:
            pm.update(extra)
        return pm

    def explainParam(self, param: Union[str, Param]) -> str:
        param = self._resolveParam(param)
        default = (
            f"default: {self._defaultParamMap[param]}" if self.hasDefault(param) else "undefined"
        )
        cur = f", current: {self._paramMap[param]}" if self.isSet(param) else ""
        return f"{param.name}: {param.doc} ({default}{cur})"

    def explainParams(self) -> str:
        return "\n".join(self.explainParam(p) for p in self.params)

    def copy(self: P, extra: Optional[Dict[Param, Any]] = None) -> P:
        that = copy.copy(self)
        that._paramMap = dict(self._paramMap)
        that._defaultParamMap = dict(self._defaultParamMap)
        that._params = None
        if hasattr(self, "_tpu_params"):
            that._tpu_params = dict(self._tpu_params)  # type: ignore[attr-defined]
        if hasattr(self, "_fallback_params"):
            that._fallback_params = dict(self._fallback_params)  # type: ignore[attr-defined]
        if extra:
            for p, v in extra.items():
                if hasattr(that, "_set_params"):
                    # keeps Spark + backend sides in sync; raises (or arms CPU
                    # fallback) on TPU-unsupported params, like the reference
                    # auto-generated setters (params.py:287-328)
                    that._set_params(**{p.name: v})  # type: ignore[attr-defined]
                else:
                    that.set(p, v)
        return that

    def _copyValues(self, to: "Params", extra: Optional[Dict[Param, Any]] = None) -> "Params":
        paramMap = dict(self._paramMap)
        if extra:
            paramMap.update(extra)
        for p, v in self._defaultParamMap.items():
            if to.hasParam(p.name):
                to._defaultParamMap[to.getParam(p.name)] = v
        for p, v in paramMap.items():
            if to.hasParam(p.name):
                to._paramMap[to.getParam(p.name)] = v
        return to


# ---------------------------------------------------------------------------
# Shared Param mixins (reference params.py:45-159 and pyspark.ml.param.shared)
# ---------------------------------------------------------------------------


class HasFeaturesCol(Params):
    featuresCol = Param(
        "_", "featuresCol", "features column name.", TypeConverters.toString
    )

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(featuresCol="features")

    def getFeaturesCol(self) -> str:
        return self.getOrDefault(self.featuresCol)


class HasFeaturesCols(Params):
    """Multi-numeric-column input, avoiding VectorAssembler (reference
    params.py:69-88)."""

    featuresCols = Param(
        "_",
        "featuresCols",
        "features column names for multi-column input.",
        TypeConverters.toListString,
    )

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(featuresCols=[])

    def getFeaturesCols(self) -> List[str]:
        return self.getOrDefault(self.featuresCols)


class HasLabelCol(Params):
    labelCol = Param("_", "labelCol", "label column name.", TypeConverters.toString)

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(labelCol="label")

    def getLabelCol(self) -> str:
        return self.getOrDefault(self.labelCol)


class HasPredictionCol(Params):
    predictionCol = Param(
        "_", "predictionCol", "prediction column name.", TypeConverters.toString
    )

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(predictionCol="prediction")

    def getPredictionCol(self) -> str:
        return self.getOrDefault(self.predictionCol)


class HasProbabilityCol(Params):
    probabilityCol = Param(
        "_", "probabilityCol", "class conditional probabilities column name.",
        TypeConverters.toString,
    )

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(probabilityCol="probability")

    def getProbabilityCol(self) -> str:
        return self.getOrDefault(self.probabilityCol)


class HasRawPredictionCol(Params):
    rawPredictionCol = Param(
        "_", "rawPredictionCol", "raw prediction (confidence) column name.",
        TypeConverters.toString,
    )

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(rawPredictionCol="rawPrediction")

    def getRawPredictionCol(self) -> str:
        return self.getOrDefault(self.rawPredictionCol)


class HasOutputCol(Params):
    outputCol = Param("_", "outputCol", "output column name.", TypeConverters.toString)

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(outputCol=self.uid + "__output")

    def getOutputCol(self) -> str:
        return self.getOrDefault(self.outputCol)


class HasInputCol(Params):
    inputCol = Param("_", "inputCol", "input column name.", TypeConverters.toString)

    def getInputCol(self) -> str:
        return self.getOrDefault(self.inputCol)


class HasIDCol(Params):
    """Propagate a row id through shuffling ops (reference params.py:91-129)."""

    idCol = Param("_", "idCol", "id column name.", TypeConverters.toString)

    def setIdCol(self, value: str) -> "HasIDCol":
        self._set(idCol=value)
        return self

    def getIdCol(self) -> str:
        return self.getOrDefault(self.idCol)

    def _ensureIdCol(self, df: Any) -> Any:
        """Add a monotonically-increasing unique id column if idCol unset
        (reference params.py:112-129)."""
        import pandas as pd

        if not self.isSet("idCol"):
            id_col_name = "unique_id"
            while id_col_name in df.columns:
                id_col_name += "_0"
            df = df.copy()
            df[id_col_name] = range(len(df))
            self._set(idCol=id_col_name)
            return df
        return df


class HasVerboseParam(Params):
    verbose = Param(
        "_", "verbose", "Logging level 0-6 or bool for the backend.",
        TypeConverters.identity,
    )

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(verbose=False)


class HasEnableSparseDataOptim(Params):
    """Force sparse/dense training data layout (reference params.py:45-66)."""

    enable_sparse_data_optim = Param(
        "_",
        "enable_sparse_data_optim",
        "None (auto), True (force sparse), False (force dense).",
        TypeConverters.identity,
    )

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(enable_sparse_data_optim=None)


class HasSeed(Params):
    seed = Param("_", "seed", "random seed.", TypeConverters.toInt)

    def __init__(self) -> None:
        super().__init__()
        # Deterministic per-class default (Spark derives it from the class
        # name too; Python's hash() is salted per process, crc32 is not).
        import zlib

        self._setDefault(seed=zlib.crc32(type(self).__name__.encode()) & 0x7FFFFFFF)

    def getSeed(self) -> int:
        return self.getOrDefault(self.seed)

    def setSeed(self, value: int) -> "HasSeed":
        self._set(seed=value)
        return self


class HasTol(Params):
    tol = Param("_", "tol", "convergence tolerance for iterative algorithms.",
                TypeConverters.toFloat)

    def getTol(self) -> float:
        return self.getOrDefault(self.tol)


class HasMaxIter(Params):
    maxIter = Param("_", "maxIter", "max number of iterations (>= 0).",
                    TypeConverters.toInt)

    def getMaxIter(self) -> int:
        return self.getOrDefault(self.maxIter)


class HasRegParam(Params):
    regParam = Param("_", "regParam", "regularization parameter (>= 0).",
                     TypeConverters.toFloat)

    def getRegParam(self) -> float:
        return self.getOrDefault(self.regParam)


class HasElasticNetParam(Params):
    elasticNetParam = Param(
        "_", "elasticNetParam",
        "ElasticNet mixing: 0 = L2 penalty, 1 = L1 penalty.",
        TypeConverters.toFloat,
    )

    def getElasticNetParam(self) -> float:
        return self.getOrDefault(self.elasticNetParam)


class HasFitIntercept(Params):
    fitIntercept = Param("_", "fitIntercept", "whether to fit an intercept term.",
                         TypeConverters.toBoolean)

    def getFitIntercept(self) -> bool:
        return self.getOrDefault(self.fitIntercept)


class HasStandardization(Params):
    standardization = Param(
        "_", "standardization", "whether to standardize features before fitting.",
        TypeConverters.toBoolean,
    )

    def getStandardization(self) -> bool:
        return self.getOrDefault(self.standardization)


class HasWeightCol(Params):
    weightCol = Param("_", "weightCol", "instance weight column name.",
                      TypeConverters.toString)

    def getWeightCol(self) -> str:
        return self.getOrDefault(self.weightCol)


# ---------------------------------------------------------------------------
# Backend param mapping layer (reference _CumlClass params.py:162-257 and
# _CumlParams params.py:260-707)
# ---------------------------------------------------------------------------


class _TpuClass(ABC):
    """Declarative mapping between the Spark ML API param names and the TPU
    backend kernel param names (reference `_CumlClass`, params.py:162-257).

    `_param_mapping()` values:
      - str: backend param name
      - None: unsupported -> error or CPU fallback (reference params.py:186)
      - "": accepted but ignored
    """

    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {}

    @classmethod
    def _param_value_mapping(cls) -> Dict[str, Callable[[Any], Union[None, Any]]]:
        """Param-name -> value transformer for values needing translation
        (reference params.py:201-221)."""
        return {}

    @classmethod
    def _param_excludes(cls) -> List[str]:
        return []

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        """Backend kernel defaults (analog of `_get_cuml_params_default`,
        reference params.py:240-245; hardcoded, never imports the backend
        compute library at param-resolution time)."""
        return {}


class _TpuParams(_TpuClass, Params):
    """Mixin holding `_tpu_params` (the backend-side param dict, analog of
    `_cuml_params` reference params.py:260-707), `num_workers`, and the CPU
    (sklearn) fallback switches."""

    _float32_inputs: bool = True

    def __init__(self) -> None:
        super().__init__()
        self._tpu_params: Dict[str, Any] = {}
        self._num_workers: Optional[int] = None
        self._fallback_enabled: bool = bool(get_config("cpu_fallback_enabled"))
        self._fallback_params: Dict[str, Any] = {}
        self._float32_inputs = bool(get_config("float32_inputs"))

    def _init_tpu_params(self) -> None:
        self._tpu_params = dict(self._get_tpu_params_default())
        self._spark_defaults_synced = False

    def _sync_spark_defaults_to_tpu(self) -> None:
        """Overlay the Spark-side param *defaults* onto the backend dict so
        precedence is: backend defaults < Spark defaults < explicit sets.
        (The reference hardcodes cuML defaults that can disagree with Spark
        defaults, e.g. l1_ratio=0.15 vs elasticNetParam=0.0; Spark semantics
        must win for un-set params.)"""
        value_map = self._param_value_mapping()
        for sname, mapped in self._param_mapping().items():
            if not mapped:
                continue
            if self.hasParam(sname) and self.hasDefault(sname) and not self.isSet(sname):
                v = self._defaultParamMap[self.getParam(sname)]
                if sname in value_map:
                    v = value_map[sname](v)
                    if v is None:
                        continue
                self._tpu_params[mapped] = v

    @property
    def tpu_params(self) -> Dict[str, Any]:
        return self._tpu_params

    # alias for parity with the reference attribute name
    @property
    def cuml_params(self) -> Dict[str, Any]:
        return self._tpu_params

    @property
    def num_workers(self) -> int:
        """Number of TPU workers (mesh size) fitting the model.  Inferred
        from visible jax devices when unset (reference params.py:556-588
        infers from cluster GPUs)."""
        if self._num_workers is not None:
            return self._num_workers
        conf = get_config("num_workers")
        if conf:
            return int(conf)
        return self._infer_num_workers()

    @num_workers.setter
    def num_workers(self, value: int) -> None:
        self._num_workers = value

    def setNumWorkers(self, value: int) -> "_TpuParams":
        self._num_workers = value
        return self

    @staticmethod
    def _infer_num_workers() -> int:
        try:
            # active devices, not all visible ones: after an elastic mesh
            # recovery (resilience/elastic.py) the lost chips are excluded
            # from service and an inferred width must count the survivors
            from .parallel.mesh import active_devices

            return len(active_devices())
        except Exception:  # pragma: no cover
            return 1

    def _set_params(self, **kwargs: Any) -> "_TpuParams":
        """Set params on both the Spark-API side and the backend `_tpu_params`
        side, keeping the two in sync (reference `_set_params`,
        params.py:430-487)."""
        if not getattr(self, "_spark_defaults_synced", True):
            self._sync_spark_defaults_to_tpu()
            self._spark_defaults_synced = True
        mapping = self._param_mapping()
        value_map = self._param_value_mapping()
        for k, v in kwargs.items():
            if k == "num_workers":
                # None keeps the default (all visible devices), matching the
                # reference's inferred num_workers (params.py:556-588)
                self._num_workers = int(v) if v is not None else None
                continue
            if k == "float32_inputs":
                self._float32_inputs = bool(v)
                continue
            if self.hasParam(k):
                self._set(**{k: v})
                if k in mapping:
                    mapped = mapping[k]
                    if mapped is None:
                        # Unsupported on TPU: either arm CPU fallback or raise
                        # (reference params.py:287-328 auto-generated setters).
                        if self._fallback_enabled:
                            self._fallback_params[k] = v
                            get_logger(type(self)).warning(
                                f"Parameter {k} is not supported on TPU; "
                                f"will fall back to CPU (sklearn) fit."
                            )
                        else:
                            raise ValueError(
                                f"Parameter {k} is not supported on TPU. Set "
                                f"cpu_fallback_enabled config to fall back to sklearn."
                            )
                    elif mapped == "":
                        pass  # accepted and ignored
                    else:
                        val = v
                        if k in value_map:
                            val = value_map[k](v)
                            if val is None:
                                # unsupported *value* for a supported param
                                # (reference params.py:201-221)
                                raise ValueError(
                                    f"Value '{v}' for param '{k}' is not supported on TPU."
                                )
                        self._tpu_params[mapped] = val
            elif k in self._tpu_params or k in self._get_tpu_params_default():
                # backend-only kwarg passed straight through (reference
                # params.py:463-474)
                self._tpu_params[k] = v
            else:
                raise ValueError(f"Unsupported param '{k}'.")
        return self

    def _use_cpu_fallback(self, params: Optional[Dict[Param, Any]] = None) -> bool:
        """True when fallback is enabled and an unsupported param was set
        (reference `_use_cpu_fallback`, params.py:690-707)."""
        if not self._fallback_enabled:
            return False
        if self._fallback_params:
            return True
        if params:
            mapping = self._param_mapping()
            for p in params:
                if mapping.get(p.name, "absent") is None:
                    return True
        return False

    def _get_tpu_param(self, spark_name: str) -> Any:
        mapped = self._param_mapping().get(spark_name, spark_name)
        return self._tpu_params.get(mapped)  # type: ignore[arg-type]
