#
# Feature transformers: PCA — the analog of reference feature.py (468 LoC).
# The cuML PCAMG distributed fit (feature.py:240-261) is replaced by
# ops/pca.py: one sharded Gram matmul + replicated eigh.
#
from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

import numpy as np

from ..core import FitInput, _TpuEstimator, _TpuModel
from ..params import (
    HasFeaturesCol,
    HasFeaturesCols,
    HasInputCol,
    HasOutputCol,
    Param,
    TypeConverters,
    _TpuParams,
)
from ..utils import _ArrayBatch


class PCAClass:
    """Param mapping (reference PCAClass feature.py:60-75)."""

    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {"k": "n_components"}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "n_components": None,
            "svd_solver": "auto",
            "verbose": False,
            "whiten": False,
        }


class _PCATpuParams(_TpuParams, HasInputCol, HasOutputCol, HasFeaturesCol, HasFeaturesCols):
    """Shared params for PCA / PCAModel (reference _PCACumlParams
    feature.py:77-130)."""

    k = Param("_", "k", "the number of principal components.", TypeConverters.toInt)
    inputCols = Param(
        "_", "inputCols", "input column names for multi-column features.",
        TypeConverters.toListString,
    )

    def setInputCol(self, value: Union[str, List[str]]) -> "_PCATpuParams":
        if isinstance(value, str):
            self._set_params(inputCol=value)
        else:
            self._set_params(inputCols=value)
        return self

    def setInputCols(self, value: List[str]) -> "_PCATpuParams":
        return self._set_params(inputCols=value)

    def setOutputCol(self, value: str) -> "_PCATpuParams":
        return self._set_params(outputCol=value)

    def getInputCol(self) -> Union[str, List[str]]:
        if self.isSet(self.inputCols):
            return self.getOrDefault(self.inputCols)
        if self.isDefined(self.inputCol):
            return self.getOrDefault(self.inputCol)
        raise RuntimeError("inputCol is not set")

    def setK(self, value: int) -> "_PCATpuParams":
        return self._set_params(k=value)

    def getK(self) -> int:
        return self.getOrDefault("k")


class PCA(PCAClass, _TpuEstimator, _PCATpuParams):
    """Distributed PCA on TPU (API parity: reference PCA feature.py:117-297).

    Learns the top-k principal components of row-sharded data with a single
    psum'd Gram matrix per fit.  Spark semantics: `transform` projects the
    raw (uncentered) input onto the components.

    Examples
    --------
    >>> import pandas as pd
    >>> from spark_rapids_ml_tpu.feature import PCA
    >>> df = pd.DataFrame({"features": [[-1.0, -1.0], [0.0, 0.0], [1.0, 1.0]]})
    >>> model = PCA(k=1).setInputCol("features").setOutputCol("pca_features").fit(df)
    >>> model.transform(df)["pca_features"].tolist()  # doctest: +SKIP
    [[-1.414...], [0.0], [1.414...]]
    """

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(k=None)
        self._set_params(**kwargs)

    def _fit_array(self, fit_input: FitInput) -> Dict[str, Any]:
        from ..ops.pca import pca_fit, pca_fit_randomized, resolve_pca_solver

        k = fit_input.params.get("n_components") or fit_input.pdesc.n
        if k > fit_input.pdesc.n:
            raise ValueError(f"k={k} exceeds the number of features {fit_input.pdesc.n}")
        k = int(k)
        # solver dispatch (conf pca_solver=auto|full|randomized): the
        # randomized range-finder scales the Gram work O(n d l) instead
        # of O(n d^2) when k << d — the same tradeoff the reference's
        # cuML MG path makes (ops/pca.py resolve_pca_solver)
        solver, l, power_iters, _reason = resolve_pca_solver(
            fit_input.pdesc.n, k
        )
        if solver == "randomized":
            mean, components, ev, evr, sv = pca_fit_randomized(
                fit_input.X, fit_input.w, k, int(l), int(power_iters)
            )
        else:
            mean, components, ev, evr, sv = pca_fit(
                fit_input.X, fit_input.w, k
            )
        return {
            "mean_": np.asarray(mean),
            "components_": np.asarray(components),
            "explained_variance_": np.asarray(ev),
            "explained_variance_ratio_": np.asarray(evr),
            "singular_values_": np.asarray(sv),
            "n_cols": fit_input.pdesc.n,
            "dtype": str(np.dtype(fit_input.dtype).name),
        }

    def _supports_streaming_stats(self) -> bool:
        return True

    def _supports_fused_stats(self) -> bool:
        # one-pass second moments: the chunk order of arrival is
        # irrelevant, so accumulating while staging is exact
        return True

    def _resolved_k(self, d: int) -> int:
        k = int(self._tpu_params.get("n_components") or d)
        if k > d:
            raise ValueError(f"k={k} exceeds the number of features {d}")
        return k

    def _fit_fused(self, batch: _ArrayBatch) -> Dict[str, Any]:
        """Fused stage-and-solve over an in-memory host batch: the
        moment (or randomized-projected) accumulators fold each chunk in
        as it lands on the mesh (fused.py)."""
        from ..fused import fused_chunk_rows, fused_pca_stats, iter_host_chunks

        X = batch.X
        dtype = self._out_dtype(X)
        d = int(X.shape[1])

        def producer(n_dev: int):
            rows = fused_chunk_rows(
                int(X.shape[0]), d, np.dtype(dtype).itemsize, n_dev
            )
            return iter_host_chunks(X, None, batch.weight, rows, dtype)

        st = fused_pca_stats(producer, d, self._resolved_k(d), dtype)
        return self._attrs_from_fused(st, dtype)

    def _fit_fused_parquet(self, path: str) -> Dict[str, Any]:
        """Fused stage-and-solve straight from parquet: the chunk decode
        (the dominant host cost of the refconfig fits) runs on the
        producer thread, overlapped with the on-mesh accumulation."""
        from ..fused import (
            fused_chunk_rows,
            fused_pca_stats,
            iter_parquet_chunks,
        )
        from ..streaming import parquet_row_count, probe_num_features

        fcol, fcols, _, weight_col, dtype = self._streaming_io_params()
        d = probe_num_features(path, fcol, fcols)
        n = parquet_row_count(path)

        def producer(n_dev: int):
            rows = fused_chunk_rows(n, d, np.dtype(dtype).itemsize, n_dev)
            prep = {"s": 0.0, "iv": []}  # readers self-time their decode
            return (
                iter_parquet_chunks(
                    path, fcol, fcols, None, weight_col, rows, dtype,
                    prep=prep,
                ),
                prep,
            )

        st = fused_pca_stats(producer, d, self._resolved_k(d), dtype)
        return self._attrs_from_fused(st, dtype)

    def _attrs_from_fused(self, st: Dict[str, Any], dtype) -> Dict[str, Any]:
        if st.get("kind") == "projected":
            return self._attrs_from_projected(st, dtype)
        return self._attrs_from_moments(st, dtype)

    def _attrs_from_projected(self, st: Dict[str, Any], dtype) -> Dict[str, Any]:
        """Finalize the stage-overlapped RANDOMIZED fit: the small
        Q-projected eigenproblem from the accumulated tall-skinny
        moments (ops/pca.py `pca_attrs_from_projected`)."""
        from ..ops.pca import pca_attrs_from_projected

        mean, components, ev, evr, sv = pca_attrs_from_projected(
            st["Q"], st["SQ"], st["s1"], st["ssq"], float(st["sw"]),
            int(st["k"]),
        )
        dtype = np.dtype(dtype)
        return {
            "mean_": mean.astype(dtype),
            "components_": components.astype(dtype),
            "explained_variance_": ev.astype(dtype),
            "explained_variance_ratio_": evr.astype(dtype),
            "singular_values_": sv.astype(dtype),
            "n_cols": int(components.shape[1]),
            "dtype": str(dtype.name),
        }

    def _supports_fold_weights(self) -> bool:
        # weighted mean/covariance + deterministic eigh (ops/pca.py
        # SUPPORTS_ZERO_WEIGHT_ROWS): fold masks are plain zero weights
        from ..ops import pca as _pca_ops

        return bool(_pca_ops.SUPPORTS_ZERO_WEIGHT_ROWS)

    def _fit_streaming(self, path: str) -> Dict[str, Any]:
        """Beyond-HBM fit from multi-pass streamed second moments
        (streaming.py `pca_streaming_stats`): the dataset never resides in
        host RAM or HBM, only the (d,d) accumulator does.  The host
        finalization replicates `ops/pca.py pca_fit` in float64."""
        from ..streaming import pca_streaming_stats

        fcol, fcols, _, weight_col, dtype = self._streaming_io_params()
        st = pca_streaming_stats(path, fcol, fcols, weight_col, dtype=dtype)
        return self._attrs_from_moments(st, dtype)

    def _fit_streaming_csr(self, batch) -> Dict[str, Any]:
        """Sparse fit from blocked-densify second moments
        (streaming.py `pca_stats_from_csr`): exact, with one dense row
        block of host memory — the TPU analog of the reference's CSR PCA
        staging (core.py:220-265)."""
        from ..streaming import pca_stats_from_csr

        dtype = self._out_dtype(batch.X)
        st = pca_stats_from_csr(
            batch.X.tocsr(), batch.weight, dtype=dtype
        )
        return self._attrs_from_moments(st, dtype)

    def _attrs_from_moments(self, st: Dict[str, Any], dtype) -> Dict[str, Any]:
        S, s1, sw = np.asarray(st["S"]), np.asarray(st["s1"]), float(st["sw"])
        d = S.shape[0]
        k = int(self._tpu_params.get("n_components") or d)
        if k > d:
            raise ValueError(f"k={k} exceeds the number of features {d}")
        mean = s1 / sw
        cov = (S - sw * np.outer(mean, mean)) / (sw - 1.0)
        evals, evecs = np.linalg.eigh(cov)
        evals = evals[::-1]
        evecs = evecs[:, ::-1]
        components = evecs[:, :k].T
        flip_idx = np.argmax(np.abs(components), axis=1)
        signs = np.sign(components[np.arange(k), flip_idx])
        signs[signs == 0] = 1.0
        components = components * signs[:, None]
        ev = np.clip(evals[:k], 0.0, None)
        evr = ev / np.clip(evals, 0.0, None).sum()
        sv = np.sqrt(ev * (sw - 1.0))
        dtype = np.dtype(dtype)
        return {
            "mean_": mean.astype(dtype),
            "components_": components.astype(dtype),
            "explained_variance_": ev.astype(dtype),
            "explained_variance_ratio_": evr.astype(dtype),
            "singular_values_": sv.astype(dtype),
            "n_cols": d,
            "dtype": str(dtype.name),
        }

    def _create_model(self, attrs: Dict[str, Any]) -> "PCAModel":
        return PCAModel(**attrs)

    def _cpu_fit(self, batch: _ArrayBatch) -> "PCAModel":
        from sklearn.decomposition import PCA as SkPCA

        k = self.getOrDefault("k") or batch.X.shape[1]
        sk = SkPCA(n_components=k, svd_solver="full").fit(batch.X)
        model = PCAModel(
            mean_=sk.mean_.astype(batch.X.dtype),
            components_=sk.components_.astype(batch.X.dtype),
            explained_variance_=sk.explained_variance_.astype(batch.X.dtype),
            explained_variance_ratio_=sk.explained_variance_ratio_.astype(batch.X.dtype),
            singular_values_=sk.singular_values_.astype(batch.X.dtype),
            n_cols=int(batch.X.shape[1]),
            dtype=str(batch.X.dtype),
        )
        return model


class PCAModel(PCAClass, _TpuModel, _PCATpuParams):
    """PCA projection model (reference PCAModel feature.py:299-468).

    Note: like Spark, `transform` does NOT remove the mean — cuML does, and
    the reference adds `mean @ components^T` back (feature.py:447-459); here
    the projection is simply `X @ components^T`.
    """

    def __init__(self, **attrs: Any) -> None:
        super().__init__(**attrs)
        self.mean_: np.ndarray = np.asarray(attrs["mean_"])
        self.components_: np.ndarray = np.asarray(attrs["components_"])
        self.explained_variance_: np.ndarray = np.asarray(attrs["explained_variance_"])
        self.explained_variance_ratio_: np.ndarray = np.asarray(
            attrs["explained_variance_ratio_"]
        )
        self.singular_values_: np.ndarray = np.asarray(attrs["singular_values_"])
        self.n_cols: int = int(attrs["n_cols"])
        self.dtype: str = str(attrs.get("dtype", "float32"))
        self._set_params(k=int(self.components_.shape[0]))

    @property
    def pc(self) -> np.ndarray:
        """Principal components as a (n_features, k) matrix, matching
        pyspark.ml PCAModel.pc (column-major components)."""
        return self.components_.T

    @property
    def explainedVariance(self) -> np.ndarray:
        """Ratio of variance explained per component (pyspark parity)."""
        return self.explained_variance_ratio_

    def _output_columns(self) -> List[str]:
        return [self.getOrDefault("outputCol")]

    def _transform_device(self, Xs) -> Dict[str, Any]:
        import jax.numpy as jnp

        from ..ops.pca import pca_transform

        return {
            self.getOrDefault("outputCol"): pca_transform(
                Xs, jnp.asarray(self.components_.astype(Xs.dtype))
            )
        }

    def cpu(self):
        from sklearn.decomposition import PCA as SkPCA

        sk = SkPCA(n_components=self.components_.shape[0])
        sk.components_ = self.components_
        sk.mean_ = self.mean_
        sk.explained_variance_ = self.explained_variance_
        sk.explained_variance_ratio_ = self.explained_variance_ratio_
        sk.singular_values_ = self.singular_values_
        sk.n_components_ = self.components_.shape[0]
        sk.n_features_in_ = self.n_cols
        return sk
