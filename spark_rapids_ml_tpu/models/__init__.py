# API facade — pyspark.ml-compatible estimators/models (reference
# python/src/spark_rapids_ml/{feature,clustering,classification,regression,
# knn,umap}.py), backed by the ops/ TPU kernels.
