#
# UMAP — the analog of reference umap.py (1730 LoC).  The single-GPU
# `cuml.manifold.UMAP` fit (umap.py:1016-1063) becomes ops/umap.py jit
# kernels; the reference's fit strategy is kept exactly: fit on ONE worker
# (optionally on a sample_fraction of rows, umap.py:926-948), then the
# model (embedding + raw data) serves a distributed transform
# (umap.py:1407-1450 broadcasts the model; here the query kNN against the
# raw data runs on the sharded mesh via the ops/knn.py ring kernel).
#
from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

import numpy as np

from ..core import FitInput, _TpuEstimator, _TpuModel
from ..data import DatasetLike, _ensure_dense
from ..params import (
    HasFeaturesCol,
    HasFeaturesCols,
    HasLabelCol,
    HasOutputCol,
    Param,
    TypeConverters,
    _TpuParams,
)


class UMAPClass:
    """Param surface (reference UMAPClass umap.py:110-143: cuML-native
    names — there is no Spark UMAP, identity mapping)."""

    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {
            n: n
            for n in (
                "n_neighbors", "n_components", "metric", "metric_kwds",
                "n_epochs",
                "learning_rate", "init", "min_dist", "spread",
                "set_op_mix_ratio", "local_connectivity",
                "repulsion_strength", "negative_sample_rate", "a", "b",
                "random_state", "sample_fraction", "target_metric",
                "target_weight", "build_algo", "build_kwds",
            )
        }

    @classmethod
    def _param_value_mapping(cls):
        from ..ops.distances import SUPPORTED_METRICS

        return {
            # the full cuML metric zoo incl. jaccard (which the reference
            # limits to sparse inputs, umap.py:1145-1146 — the tiled
            # elementwise kernel here serves dense inputs too);
            # ops/distances.py implements the kernels
            "metric": lambda x: x if x in SUPPORTED_METRICS else None,
            "init": lambda x: x if x in ("spectral", "random") else None,
            "build_algo": lambda x: x
            if x in ("auto", "brute_force_knn", "nn_descent")
            else None,
        }

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "n_neighbors": 15,
            "n_components": 2,
            "metric": "euclidean",
            "metric_kwds": None,
            "n_epochs": None,
            "learning_rate": 1.0,
            "init": "spectral",
            "min_dist": 0.1,
            "spread": 1.0,
            "set_op_mix_ratio": 1.0,
            "local_connectivity": 1.0,
            "repulsion_strength": 1.0,
            "negative_sample_rate": 5,
            "transform_queue_size": 4.0,
            "a": None,
            "b": None,
            "precomputed_knn": None,
            "random_state": None,
            "sample_fraction": 1.0,
            "target_metric": "categorical",
            "target_weight": 0.5,
            "build_algo": "auto",
            "build_kwds": None,
            "verbose": False,
        }


class _UMAPParams(
    _TpuParams, HasFeaturesCol, HasFeaturesCols, HasLabelCol, HasOutputCol
):
    n_neighbors = Param("_", "n_neighbors", "Neighborhood size.",
                        TypeConverters.toFloat)
    n_components = Param("_", "n_components", "Embedding dimension.",
                         TypeConverters.toInt)
    metric = Param("_", "metric", "Distance metric.", TypeConverters.toString)
    metric_kwds = Param("_", "metric_kwds",
                        "Metric arguments (e.g. {'p': 3} for minkowski).",
                        TypeConverters.identity)
    n_epochs = Param("_", "n_epochs", "Training epochs (None = auto).",
                     TypeConverters.identity)
    learning_rate = Param("_", "learning_rate", "Initial learning rate.",
                          TypeConverters.toFloat)
    init = Param("_", "init", "Embedding init: spectral or random.",
                 TypeConverters.toString)
    min_dist = Param("_", "min_dist", "Minimum embedded distance.",
                     TypeConverters.toFloat)
    spread = Param("_", "spread", "Embedded scale.", TypeConverters.toFloat)
    set_op_mix_ratio = Param("_", "set_op_mix_ratio",
                             "Fuzzy union/intersection mix in [0,1].",
                             TypeConverters.toFloat)
    local_connectivity = Param("_", "local_connectivity",
                               "Assumed local connectivity.",
                               TypeConverters.toFloat)
    repulsion_strength = Param("_", "repulsion_strength",
                               "Negative-sample weighting.",
                               TypeConverters.toFloat)
    negative_sample_rate = Param("_", "negative_sample_rate",
                                 "Negative samples per positive edge.",
                                 TypeConverters.toInt)
    sample_fraction = Param("_", "sample_fraction",
                            "Fraction of rows used for the one-worker fit "
                            "(reference umap.py:926-948).",
                            TypeConverters.toFloat)
    random_state = Param("_", "random_state", "Random seed.",
                         TypeConverters.identity)
    build_algo = Param(
        "_", "build_algo",
        "kNN graph build: 'auto' (brute force <= 50k rows, else "
        "nn_descent), 'brute_force_knn', or 'nn_descent' (reference "
        "umap.py:362-370).",
        TypeConverters.toString)
    build_kwds = Param(
        "_", "build_kwds",
        "nn_descent arguments: nnd_graph_degree, nnd_max_iterations "
        "(reference umap.py:372-380).",
        TypeConverters.identity)

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(
            n_neighbors=15.0,
            n_components=2,
            metric="euclidean",
            n_epochs=None,
            learning_rate=1.0,
            init="spectral",
            min_dist=0.1,
            spread=1.0,
            set_op_mix_ratio=1.0,
            local_connectivity=1.0,
            repulsion_strength=1.0,
            negative_sample_rate=5,
            sample_fraction=1.0,
            random_state=None,
            build_algo="auto",
            outputCol="embedding",
        )

    def setFeaturesCol(self, value: Union[str, List[str]]):
        if isinstance(value, str):
            self._set_params(featuresCol=value)
        else:
            self._set_params(featuresCols=value)
        return self

    def setFeaturesCols(self, value: List[str]):
        return self._set_params(featuresCols=value)

    def setLabelCol(self, value: str):
        self._set(labelCol=value)
        return self

    def setOutputCol(self, value: str):
        self._set(outputCol=value)
        return self


# spectral(PCA) init on sparse input builds a d x d Gram on the host; past
# this feature count the eigh dominates fit time, so fall back to random
_SPARSE_SPECTRAL_MAX_D = 4096


def _sparse_pca_basis_project(X, n_comp: int, dtype) -> np.ndarray:
    """Chunked-Gram PCA projection of a CSR matrix — the sparse stand-in
    for the dense-SVD spectral-init basis.  Accumulates the d x d Gram over
    dense row chunks on the device (donated in-place adds), eigh's the
    covariance on the host, then projects chunks.  Host peak memory is one
    `host_batch_bytes` chunk plus the d x d Gram."""
    import jax
    import jax.numpy as jnp

    from ..native import densify_csr
    from ..streaming import chunk_rows_for

    n, d = X.shape
    # f64 projection chunks: size by 8-byte items so the dense chunk stays
    # within the host_batch_bytes budget
    chunk = max(1, int(chunk_rows_for(d, 8)))
    mean = np.asarray(X.mean(axis=0)).ravel().astype(np.float64)
    G = jnp.zeros((d, d), jnp.float32)
    acc = jax.jit(lambda g, c: g + c.T @ c, donate_argnums=0)
    for lo in range(0, n, chunk):
        hi = min(lo + chunk, n)
        G = acc(G, jnp.asarray(densify_csr(X[lo:hi], hi - lo, np.float32)))
    cov = np.asarray(jax.device_get(G), np.float64) / n - np.outer(mean, mean)
    _, v = np.linalg.eigh(cov)
    V = v[:, ::-1][:, :n_comp]  # top components, descending eigenvalue
    pc = np.empty((n, n_comp), np.float64)
    for lo in range(0, n, chunk):
        hi = min(lo + chunk, n)
        pc[lo:hi] = (densify_csr(X[lo:hi], hi - lo, np.float64) - mean) @ V
    return pc.astype(dtype)


class UMAP(UMAPClass, _TpuEstimator, _UMAPParams):
    """Uniform Manifold Approximation and Projection on TPU (API parity:
    reference UMAP umap.py:681-1348).

    Fit runs on one worker like the reference (umap.py:926-948), as three
    jit kernels: exact kNN graph (ops/knn.py), fuzzy simplicial set with
    smooth-knn bisection, and the umap-learn SGD recast as one compiled
    epoch loop over all edges (ops/umap.py).  `init="spectral"` uses a
    scaled PCA basis (the practical stand-in for graph-spectral init; cuML
    defaults to spectral, umap.py:120).

    Examples
    --------
    >>> import numpy as np
    >>> from spark_rapids_ml_tpu.umap import UMAP
    >>> X = np.random.default_rng(0).normal(size=(200, 8)).astype("float32")
    >>> m = UMAP(n_neighbors=10, random_state=1, n_epochs=50).fit(X)
    >>> m.embedding_.shape
    (200, 2)
    """

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._set_params(**kwargs)

    def _is_supervised(self) -> bool:
        # supervised UMAP: labels flow into the fuzzy-set intersection when
        # the user sets labelCol (reference umap.py:812-813)
        return self.hasParam("labelCol") and self.isSet("labelCol")

    def _fit(self, dataset: DatasetLike) -> "UMAPModel":
        import time

        import jax
        import jax.numpy as jnp

        from ..ops import umap as umap_ops

        t0 = time.time()
        batch = self._extract(dataset)
        from ..data import _is_sparse

        sparse_in = _is_sparse(batch.X)
        if sparse_in:
            # CSR fit (the analog of reference `_sparse_fit` umap.py:904-969,
            # which concatenates CSR chunks on the GPU): rows stay CSR on the
            # host end-to-end; the dense device matrix the TPU kernels need
            # is assembled chunk-by-chunk (densify_to_device), so host peak
            # memory is one `host_batch_bytes` chunk, never the full matrix
            X = batch.X.tocsr()
            dtype = self._out_dtype(X)
        else:
            X = _ensure_dense(batch.X)
            dtype = self._out_dtype(X)
            X = np.ascontiguousarray(X, dtype=dtype)
        p = self._tpu_params
        rs = p.get("random_state")
        seed = int(rs) if rs is not None else 42

        from ..parallel.mesh import allgather_host_csr, allgather_host_rows

        # single-worker fit strategy (the reference forces UMAP fit onto one
        # worker, umap.py:926-948): in multi-process mode every process
        # gathers the full sample and computes the identical model
        X = allgather_host_csr(X) if sparse_in else allgather_host_rows(X)
        y_all: Optional[np.ndarray] = None
        if batch.y is not None:
            y_all = allgather_host_rows(np.asarray(batch.y, np.float64))
        frac = float(p.get("sample_fraction", 1.0))
        if frac < 1.0:
            rng = np.random.default_rng(seed)
            keep = rng.random(X.shape[0]) < frac
            X_fit = X[keep]
            y_fit = y_all[keep] if y_all is not None else None
        else:
            X_fit = X
            y_fit = y_all
        n, d = X_fit.shape
        k = int(float(p["n_neighbors"]))
        if k >= n:
            raise ValueError(f"n_neighbors={k} must be < n_samples={n}")

        from ..ops.distances import metric_kind, preprocess_rows, umap_knn_graph

        metric = str(p.get("metric", "euclidean"))
        pw = float(dict(p.get("metric_kwds") or {}).get("p", 2.0))
        X_graph = X_fit
        row_tf = None
        if metric_kind(metric) == "matmul":
            # row transform folds cosine/correlation/hellinger onto the
            # MXU euclidean kernel (ops/distances.py); asarray keeps the
            # identity metrics (euclidean/l2/sqeuclidean) copy-free.  The
            # transform is row-local, so the sparse path applies it per
            # dense chunk during device assembly instead
            if sparse_in:
                row_tf = lambda c: preprocess_rows(c, metric)  # noqa: E731
            else:
                X_graph = np.asarray(
                    preprocess_rows(X_fit, metric), dtype=dtype
                )

        # 1. kNN graph (self excluded).  build_algo mirrors cuML UMAP
        # (reference umap.py:362-370): brute force for small n, NN-descent
        # (ops/cagra.py) past 50k rows — O(n·deg·rounds) instead of O(n²).
        build_algo = str(p.get("build_algo") or "auto")
        bk = dict(p.get("build_kwds") or {})
        use_nnd = build_algo == "nn_descent" or (
            build_algo == "auto" and n > 50_000
        )
        if use_nnd and metric_kind(metric) != "matmul":
            # the NN-descent kernel scores candidates with the euclidean
            # MXU identity; elementwise metrics keep the brute path
            self.logger.warning(
                f"build_algo={build_algo!r} resolved to nn_descent, which "
                f"does not support metric={metric!r}; using "
                "brute_force_knn (O(n\u00b2) at this row count)"
            )
            use_nnd = False
        if sparse_in:
            from ..data import densify_to_device

            Xd = densify_to_device(X_graph, dtype, row_transform=row_tf)
        else:
            Xd = jnp.asarray(X_graph)
        if use_nnd:
            from ..ops.cagra import knn_graph_nn_descent
            from ..ops.distances import finalize_sqdist

            seed_p = p.get("random_state")
            d2k, knn_i = knn_graph_nn_descent(
                Xd,
                k=k,
                deg=(int(bk["nnd_graph_degree"])
                     if "nnd_graph_degree" in bk else None),
                rounds=int(bk.get("nnd_max_iterations", 8)),
                seed=0 if seed_p is None else int(seed_p),
            )
            knn_d = finalize_sqdist(d2k, metric)
            knn_i = jnp.asarray(knn_i)
        else:
            ones = jnp.ones((n,), Xd.dtype)
            ids = jnp.arange(n, dtype=jnp.int32)
            dists, inds = umap_knn_graph(
                Xd, ones, ids, Xd, k=k + 1, metric=metric, p=pw
            )
            knn_d = dists[:, 1:]
            knn_i = inds[:, 1:]

        # 2. fuzzy simplicial set
        lc = max(1, int(float(p["local_connectivity"])))
        rho, sigma = umap_ops.smooth_knn_dist(knn_d, local_connectivity=lc)
        heads, tails, weights = umap_ops.fuzzy_simplicial_set(
            knn_i, knn_d, rho, sigma,
            set_op_mix_ratio=float(p["set_op_mix_ratio"]),
        )

        # 2b. supervised intersection (reference umap.py:812-813, 901:
        # labelCol -> cuML supervised fit; categorical target metric)
        if y_fit is not None:
            tmetric = str(p.get("target_metric") or "categorical")
            if tmetric != "categorical":
                raise ValueError(
                    f"target_metric='{tmetric}' is not supported; only "
                    "'categorical' supervised UMAP is implemented"
                )
            tw = float(p.get("target_weight", 0.5))
            # umap-learn: far_dist from target_weight; 1.0 -> effectively inf
            far_dist = 2.5 * (1.0 / (1.0 - tw)) if tw < 1.0 else 1.0e12
            known = np.isfinite(y_fit)
            codes = np.full(y_fit.shape[0], -1, np.int32)
            if known.any():
                _, inv = np.unique(y_fit[known], return_inverse=True)
                codes[known] = inv.astype(np.int32)
            weights = umap_ops.categorical_intersection(
                knn_i, heads, tails, weights,
                jnp.asarray(codes), far_dist=far_dist,
            )

        # 3. a/b curve parameters (host scipy, once)
        a, b = p.get("a"), p.get("b")
        if a is None or b is None:
            a, b = umap_ops.find_ab_params(
                float(p["spread"]), float(p["min_dist"])
            )

        # 4. init
        dim = int(p["n_components"])
        rng = np.random.default_rng(seed)
        init = str(p["init"])
        if init != "random" and sparse_in and d > _SPARSE_SPECTRAL_MAX_D:
            self.logger.warning(
                f"init='spectral' on sparse input needs a {d}x{d} Gram "
                f"(> {_SPARSE_SPECTRAL_MAX_D} feature cap); using random "
                "init"
            )
            init = "random"
        if init == "random":
            emb0 = rng.uniform(-10.0, 10.0, (n, dim)).astype(dtype)
        else:  # "spectral" -> scaled PCA basis + jitter
            if sparse_in:
                pc = _sparse_pca_basis_project(X_fit, min(dim, d), dtype)
            else:
                Xc = X_fit - X_fit.mean(axis=0)
                _, _, vt = np.linalg.svd(Xc, full_matrices=False)
                pc = Xc @ vt[: min(dim, d)].T
            pc = pc / max(np.abs(pc).max(), 1e-12) * 10.0
            if dim > pc.shape[1]:  # fewer features than components: pad
                pad = rng.uniform(-10.0, 10.0, (n, dim - pc.shape[1]))
                pc = np.concatenate([pc, pad], axis=1)
            emb0 = (pc + rng.normal(scale=1e-4, size=pc.shape)).astype(dtype)

        # 5. SGD epochs (umap-learn auto rule; explicit 0 = init only)
        n_epochs = p.get("n_epochs")
        n_epochs = (
            int(n_epochs) if n_epochs is not None
            else (500 if n <= 10000 else 200)
        )
        if n_epochs > 0:
            emb = umap_ops.optimize_embedding(
                jnp.asarray(emb0),
                heads,
                tails,
                weights,
                seed,
                n_epochs=n_epochs,
                a=a,
                b=b,
                initial_alpha=float(p["learning_rate"]),
                negative_sample_rate=int(p["negative_sample_rate"]),
                repulsion_strength=float(p["repulsion_strength"]),
                # an explicit random_state opts into reproducible fits:
                # the umap_kernel=auto choice then follows the platform
                # prior instead of the (noise-susceptible) measured probe
                deterministic=rs is not None,
            )
        else:
            emb = jnp.asarray(emb0)
        rho_h, sigma_h, emb_h = jax.device_get((rho, sigma, emb))

        model = UMAPModel(
            embedding_=np.asarray(emb_h),
            raw_data_=X_fit,
            rho_=np.asarray(rho_h),
            sigma_=np.asarray(sigma_h),
            a_=float(a),
            b_=float(b),
            n_cols=d,
            dtype=str(np.dtype(dtype).name),
        )
        self._copyValues(model)
        model._tpu_params = dict(self._tpu_params)
        model._num_workers = self._num_workers
        model._float32_inputs = self._float32_inputs
        self.logger.info(f"Finished UMAP fit in {time.time() - t0:.3f}s")
        return model

    def _fit_array(self, fit_input: FitInput) -> Dict[str, Any]:  # pragma: no cover
        raise NotImplementedError("fit is overridden (single-worker strategy)")

    def _create_model(self, attrs: Dict[str, Any]) -> "UMAPModel":  # pragma: no cover
        return UMAPModel(**attrs)


class UMAPModel(UMAPClass, _TpuModel, _UMAPParams):
    """Fitted UMAP model (reference UMAPModel umap.py:1349-1729): holds the
    embedding AND the raw training data (needed to embed new points);
    transform shards query rows over the mesh for the kNN against the raw
    data, then initializes each query point at the membership-weighted
    average of its neighbors' embeddings (umap-learn transform init)."""

    # core._transform hands CSR queries straight through (chunk-bounded
    # densify happens in the staging below, never whole on the host)
    _accepts_sparse_transform = True

    def __init__(self, **attrs: Any) -> None:
        super().__init__(**attrs)
        from ..data import _is_sparse

        self.embedding_: np.ndarray = np.asarray(attrs["embedding_"])
        raw = attrs["raw_data_"]
        # sparse fits keep the raw training data CSR (persisted as CSR
        # component arrays, core.py _Writer.save)
        self.raw_data_ = raw.tocsr() if _is_sparse(raw) else np.asarray(raw)
        self.rho_: np.ndarray = np.asarray(attrs["rho_"])
        self.sigma_: np.ndarray = np.asarray(attrs["sigma_"])
        self.a_: float = float(attrs["a_"])
        self.b_: float = float(attrs["b_"])
        self.n_cols: int = int(attrs["n_cols"])
        self.dtype: str = str(attrs.get("dtype", "float32"))

    @property
    def embedding(self) -> np.ndarray:
        """pyspark-style accessor (reference umap.py:1380-1392)."""
        return self.embedding_

    @property
    def rawData(self) -> np.ndarray:
        return self.raw_data_

    def _output_columns(self) -> List[str]:
        return [self.getOrDefault("outputCol")]

    def _transform_array(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        import jax.numpy as jnp

        from ..ops.distances import metric_kind, preprocess_rows, umap_knn_graph
        from ..ops.umap import transform_init
        from ..parallel import TpuContext

        k = int(float(self._tpu_params["n_neighbors"]))
        if k > self.raw_data_.shape[0]:
            # beyond the valid items the ring kernel emits id -1, which JAX's
            # clamped gathers would silently turn into wrong embeddings —
            # raise like NearestNeighborsModel._search does
            raise ValueError(
                f"n_neighbors={k} exceeds the {self.raw_data_.shape[0]} "
                f"training rows in the model"
            )
        from ..data import _is_sparse

        sparse_q = _is_sparse(X)
        Xq = (
            X.tocsr()
            if sparse_q
            else np.ascontiguousarray(X, dtype=self._out_dtype(X))
        )
        items = self.raw_data_
        sparse_items = _is_sparse(items)
        dtype = np.dtype(self._out_dtype(Xq))
        metric = str(self._tpu_params.get("metric", "euclidean"))
        pw = float(
            dict(self._tpu_params.get("metric_kwds") or {}).get("p", 2.0)
        )
        row_tf = None
        if metric_kind(metric) == "matmul":
            # the same row transform the fit applied, so the distances
            # match the fit's rho/sigma scales (NOTE: since round 3 the
            # cosine/correlation convention is 1-cos, not the chord
            # distance older saved models were fitted with).  Sparse
            # operands apply it per dense chunk inside stage_sparse
            row_tf = lambda c: preprocess_rows(c, metric)  # noqa: E731
            if not sparse_items:
                items = np.asarray(preprocess_rows(items, metric), dtype)
            if not sparse_q:
                Xq = np.asarray(preprocess_rows(Xq, metric), dtype)

        with TpuContext(self.num_workers, require_p2p=True) as ctx:
            mesh = ctx.mesh
        from ..parallel.mesh import RowStager

        # contiguous staging (interleave=False) for items AND queries:
        # same tie-determinism contract as exact kNN (models/knn.py
        # _staged_items) — the interleaved layout would resolve tied
        # neighbor distances differently for sparse vs dense input or
        # across device counts, changing embeddings
        ist = RowStager.for_replicated(
            items.shape[0], mesh, interleave=False
        )
        Xi = (
            ist.stage_sparse(items, dtype, row_transform=row_tf)
            if sparse_items
            else ist.stage(items, dtype)
        )
        validd = ist.mask(dtype)
        idsd = ist.row_ids()
        qst = RowStager.for_replicated(
            Xq.shape[0], mesh, interleave=False
        )
        Qs = (
            qst.stage_sparse(Xq, dtype, row_transform=row_tf)
            if sparse_q
            else qst.stage(Xq, dtype)
        )
        knn_d, inds = umap_knn_graph(
            Xi, validd, idsd, Qs, k=k, metric=metric, p=pw, mesh=mesh
        )
        emb = transform_init(
            inds,
            knn_d,
            jnp.asarray(self.rho_.astype(dtype)),
            jnp.asarray(self.sigma_.astype(dtype)),
            jnp.asarray(self.embedding_.astype(dtype)),
        )
        return {self.getOrDefault("outputCol"): qst.fetch(emb)}

    def _get_model_attributes(self) -> Dict[str, Any]:
        return {
            "embedding_": self.embedding_,
            "raw_data_": self.raw_data_,
            "rho_": self.rho_,
            "sigma_": self.sigma_,
            "a_": self.a_,
            "b_": self.b_,
            "n_cols": self.n_cols,
            "dtype": self.dtype,
        }

    def cpu(self):
        raise NotImplementedError(
            "umap-learn is not bundled; the model arrays (embedding_, "
            "raw_data_) are directly consumable"
        )


__all__ = ["UMAP", "UMAPModel"]
