#
# Regression: LinearRegression + RandomForestRegressor — the analog
# of reference regression.py (1148 LoC).  The three cuML distributed solvers
# (LinearRegressionMG eig / RidgeMG / CDMG coordinate descent, dispatched at
# regression.py:544-627) are replaced by ops/linear.py: one fused
# sufficient-statistics pass + replicated closed-form / FISTA solve.
#
from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

import numpy as np

from ..core import FitInput, _TpuEstimator, _TpuEstimatorSupervised, _TpuModel
from ..params import (
    HasElasticNetParam,
    HasFeaturesCol,
    HasFeaturesCols,
    HasFitIntercept,
    HasLabelCol,
    HasMaxIter,
    HasPredictionCol,
    HasRegParam,
    HasStandardization,
    HasTol,
    HasWeightCol,
    Param,
    TypeConverters,
    _TpuParams,
)
from ..utils import _ArrayBatch


class LinearRegressionClass:
    """Param mapping (reference LinearRegressionClass regression.py:181-232)."""

    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {
            "aggregationDepth": "",
            "elasticNetParam": "l1_ratio",
            "epsilon": "",
            "fitIntercept": "fit_intercept",
            "loss": "loss",
            "maxBlockSizeInMB": "",
            "maxIter": "max_iter",
            "regParam": "alpha",
            "solver": "solver",
            "standardization": "standardization",
            "tol": "tol",
            # improvement over the reference (weightCol -> None): the fused
            # stats kernel supports sample weights natively
            "weightCol": "",
        }

    @classmethod
    def _param_value_mapping(cls):
        return {
            "loss": lambda x: {
                "squaredError": "squared_loss",
                "huber": None,
                "squared_loss": "squared_loss",
            }.get(x, None),
            "solver": lambda x: {
                "auto": "auto",
                "normal": "eig",
                "l-bfgs": None,
                "eig": "eig",
            }.get(x, None),
        }

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "algorithm": "auto",
            "fit_intercept": True,
            "verbose": False,
            "alpha": 0.0001,
            "solver": "auto",
            "loss": "squared_loss",
            "l1_ratio": 0.15,
            "max_iter": 1000,
            "tol": 0.001,
            "standardization": True,
            "shuffle": True,
        }


class _LinearRegressionTpuParams(
    _TpuParams,
    HasFeaturesCol,
    HasFeaturesCols,
    HasLabelCol,
    HasPredictionCol,
    HasRegParam,
    HasElasticNetParam,
    HasFitIntercept,
    HasStandardization,
    HasMaxIter,
    HasTol,
    HasWeightCol,
):
    """Shared params (reference _LinearRegressionCumlParams)."""

    solver = Param("_", "solver", "The solver algorithm: auto, normal or eig.",
                   TypeConverters.toString)
    loss = Param("_", "loss", "The loss function: squaredError.",
                 TypeConverters.toString)
    aggregationDepth = Param("_", "aggregationDepth", "treeAggregate depth (ignored).",
                             TypeConverters.toInt)
    maxBlockSizeInMB = Param("_", "maxBlockSizeInMB", "block size (ignored).",
                             TypeConverters.toFloat)

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(
            regParam=0.0,
            elasticNetParam=0.0,
            fitIntercept=True,
            standardization=True,
            maxIter=100,
            tol=1e-6,
            solver="auto",
            loss="squaredError",
            aggregationDepth=2,
        )

    def setFeaturesCol(self, value: Union[str, List[str]]):
        if isinstance(value, str):
            self._set_params(featuresCol=value)
        else:
            self._set_params(featuresCols=value)
        return self

    def setFeaturesCols(self, value: List[str]):
        return self._set_params(featuresCols=value)

    def setLabelCol(self, value: str):
        self._set(labelCol=value)
        return self

    def setPredictionCol(self, value: str):
        self._set(predictionCol=value)
        return self

    def setRegParam(self, value: float):
        return self._set_params(regParam=value)

    def setElasticNetParam(self, value: float):
        return self._set_params(elasticNetParam=value)

    def setFitIntercept(self, value: bool):
        return self._set_params(fitIntercept=value)

    def setStandardization(self, value: bool):
        return self._set_params(standardization=value)

    def setMaxIter(self, value: int):
        return self._set_params(maxIter=value)

    def setTol(self, value: float):
        return self._set_params(tol=value)

    def setWeightCol(self, value: str):
        return self._set_params(weightCol=value)


class LinearRegression(
    LinearRegressionClass, _TpuEstimatorSupervised, _LinearRegressionTpuParams
):
    """Distributed linear regression on TPU (API parity: reference
    LinearRegression regression.py:282-694).

    Solver dispatch mirrors the reference (regression.py:544-627): regParam=0
    -> OLS normal equations; elasticNetParam=0 -> ridge closed form; else
    FISTA (same optimum as cuML's CD for the convex elastic-net objective).
    All variants consume one fused sufficient-statistics pass.

    Examples
    --------
    >>> import pandas as pd
    >>> from spark_rapids_ml_tpu.regression import LinearRegression
    >>> df = pd.DataFrame({"features": [[1.0, 2.0], [2.0, 3.0], [3.0, 4.0]],
    ...                    "label": [3.0, 5.0, 7.0]})
    >>> model = LinearRegression().setFeaturesCol("features").fit(df)
    >>> round(float(model.transform(df)["prediction"][0]), 2)
    3.0
    """

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._set_params(**kwargs)

    def _fista_checkpoint(self, gram: np.ndarray, sxy: np.ndarray, sw: float):
        """(checkpoint_path, tag) for the FISTA elastic-net loop when the
        `checkpoint_dir` conf is set (the estimator-wide resume contract,
        resilience/checkpoint.py).  The tag binds the problem CONTENT —
        Gram/cross-moment checksums, not just shapes — so a same-shaped
        fit on different data can never resume this one's state."""
        from ..resilience.checkpoint import (
            checkpoint_file_for,
            resolve_checkpoint_dir,
        )

        ckpt_dir = resolve_checkpoint_dir()
        if not ckpt_dir:
            return None, ""
        p = self._tpu_params
        tag = (
            f"linreg-fista|d={int(gram.shape[0])}|sw={sw}"
            f"|gs={float(np.float64(gram).sum()):.12g}"
            f"|xs={float(np.float64(sxy).sum()):.12g}"
            f"|a={p['alpha']}|l1r={p['l1_ratio']}|int={p['fit_intercept']}"
            f"|std={p.get('standardization', True)}|mi={p['max_iter']}"
        )
        return checkpoint_file_for(ckpt_dir, tag), tag

    def _fit_array(self, fit_input: FitInput) -> Dict[str, Any]:
        from ..ops.linear import linreg_sufficient_stats, solve_linear_host

        p = fit_input.params
        gram, sxy, s1, sw, sy, syy = linreg_sufficient_stats(
            fit_input.X, fit_input.w, fit_input.y
        )
        gram_h, sxy_h = np.asarray(gram), np.asarray(sxy)
        ckpt_path, ckpt_tag = self._fista_checkpoint(gram_h, sxy_h, float(sw))
        coef, intercept, diag = solve_linear_host(
            gram_h,
            sxy_h,
            np.asarray(s1),
            float(sw),
            float(sy),
            float(syy),
            reg_param=float(p["alpha"]),
            elasticnet_param=float(p["l1_ratio"]),
            fit_intercept=bool(p["fit_intercept"]),
            standardization=bool(p.get("standardization", True)),
            tol=float(p["tol"]),
            max_iter=int(p["max_iter"]),
            checkpoint_path=ckpt_path,
            checkpoint_tag=ckpt_tag,
        )
        # summary metrics via a cancellation-free residual pass over the
        # still-staged data (the one-pass SSE expansion loses ~eps·Σwy²)
        import jax
        import jax.numpy as jnp

        from ..ops.linear import _summary_from_sse, linreg_residual_sse

        sse = float(
            jax.device_get(
                linreg_residual_sse(
                    fit_input.X,
                    fit_input.w,
                    fit_input.y,
                    jnp.asarray(coef, fit_input.X.dtype),
                    fit_input.X.dtype.type(intercept),
                )
            )
        )
        diag.update(
            _summary_from_sse(
                sse, float(sw), float(sy), float(syy),
                bool(p["fit_intercept"]),
            )
        )
        dtype = np.dtype(fit_input.dtype)
        return {
            "coef_": coef.astype(dtype),
            "intercept_": float(intercept),
            "n_iter_": int(diag["n_iter"]),
            "rmse_": float(diag["rmse"]),
            "mse_": float(diag["mse"]),
            "r2_": float(diag["r2"]),
            "n_cols": fit_input.pdesc.n,
            "dtype": str(dtype.name),
        }

    def _supports_streaming_stats(self) -> bool:
        return True

    def _supports_fused_stats(self) -> bool:
        # the Gram/moment/cross sums are chunk-order invariant, so
        # accumulating while staging is exact (fused.py)
        return True

    def _fit_fused(self, batch: _ArrayBatch) -> Dict[str, Any]:
        """Fused stage-and-solve over an in-memory host batch: the
        weighted Gram/moment/cross statistics accumulate on the mesh as
        each chunk lands (fused.py), then the same host solve as the
        streamed-statistics path.  Summary rmse/mse/r2 come from the
        one-pass SSE expansion (as on every streamed path — no staged
        array exists for a residual pass)."""
        from ..fused import fused_chunk_rows, fused_linreg_stats, iter_host_chunks

        X = batch.X
        dtype = self._out_dtype(X)
        d = int(X.shape[1])
        ldt = self._fit_label_dtype() or np.dtype(dtype)

        def producer(n_dev: int):
            rows = fused_chunk_rows(
                int(X.shape[0]), d, np.dtype(dtype).itemsize, n_dev
            )
            return iter_host_chunks(
                X, batch.y, batch.weight, rows, dtype, label_dtype=ldt
            )

        st = fused_linreg_stats(producer, d, dtype)
        return self._attrs_from_stats(st, dtype)

    def _fit_fused_parquet(self, path: str) -> Dict[str, Any]:
        """Fused stage-and-solve straight from parquet (decode on the
        producer thread, accumulate on the mesh)."""
        from ..fused import (
            fused_chunk_rows,
            fused_linreg_stats,
            iter_parquet_chunks,
        )
        from ..streaming import parquet_row_count, probe_num_features

        fcol, fcols, label_col, weight_col, dtype = self._streaming_io_params()
        if label_col is None:
            raise ValueError("labelCol must be set for LinearRegression")
        d = probe_num_features(path, fcol, fcols)
        n = parquet_row_count(path)
        ldt = self._fit_label_dtype() or np.dtype(dtype)

        def producer(n_dev: int):
            rows = fused_chunk_rows(n, d, np.dtype(dtype).itemsize, n_dev)
            prep = {"s": 0.0, "iv": []}  # readers self-time their decode
            return (
                iter_parquet_chunks(
                    path, fcol, fcols, label_col, weight_col, rows, dtype,
                    label_dtype=ldt, prep=prep,
                ),
                prep,
            )

        st = fused_linreg_stats(producer, d, dtype)
        return self._attrs_from_stats(st, dtype)

    def _supports_fold_weights(self) -> bool:
        # closed-form/FISTA solve over w-weighted sufficient statistics
        # (ops/linear.py SUPPORTS_ZERO_WEIGHT_ROWS): a CV fold mask is
        # exactly a zero weight, and the solution is row-count free
        from ..ops import linear as _linear_ops

        return bool(_linear_ops.SUPPORTS_ZERO_WEIGHT_ROWS)

    def _fit_streaming(self, path: str) -> Dict[str, Any]:
        """Beyond-HBM fit from multi-pass streamed sufficient statistics
        (streaming.py `linreg_streaming_stats`); the host solve is the same
        `solve_linear_host` the in-memory path uses."""
        from ..streaming import linreg_streaming_stats

        fcol, fcols, label_col, weight_col, dtype = self._streaming_io_params()
        if label_col is None:
            raise ValueError("labelCol must be set for LinearRegression")
        st = linreg_streaming_stats(
            path, fcol, fcols, label_col, weight_col, dtype=dtype
        )
        return self._attrs_from_stats(st, dtype)

    def _fit_streaming_csr(self, batch) -> Dict[str, Any]:
        """Sparse fit from blocked-densify sufficient statistics
        (streaming.py `linreg_stats_from_csr`): exact, with one dense row
        block of host memory — the analog of the reference's CSR path
        (classification.py:960-966 applied to the normal equations)."""
        from ..streaming import linreg_stats_from_csr

        dtype = self._out_dtype(batch.X)
        st = linreg_stats_from_csr(
            batch.X.tocsr(), np.asarray(batch.y), batch.weight, dtype=dtype
        )
        return self._attrs_from_stats(st, dtype)

    def _attrs_from_stats(self, st: Dict[str, Any], dtype) -> Dict[str, Any]:
        from ..ops.linear import solve_linear_host

        p = self._tpu_params
        ckpt_path, ckpt_tag = self._fista_checkpoint(
            np.asarray(st["gram"]), np.asarray(st["sxy"]), float(st["sw"])
        )
        coef, intercept, diag = solve_linear_host(
            np.asarray(st["gram"]),
            np.asarray(st["sxy"]),
            np.asarray(st["s1"]),
            float(st["sw"]),
            float(st["sy"]),
            float(st["syy"]),
            reg_param=float(p["alpha"]),
            elasticnet_param=float(p["l1_ratio"]),
            fit_intercept=bool(p["fit_intercept"]),
            standardization=bool(p.get("standardization", True)),
            tol=float(p["tol"]),
            max_iter=int(p["max_iter"]),
            checkpoint_path=ckpt_path,
            checkpoint_tag=ckpt_tag,
        )
        dtype = np.dtype(dtype)
        return {
            "coef_": coef.astype(dtype),
            "intercept_": float(intercept),
            "n_iter_": int(diag["n_iter"]),
            "rmse_": float(diag["rmse"]),
            "mse_": float(diag["mse"]),
            "r2_": float(diag["r2"]),
            "n_cols": int(np.asarray(st["gram"]).shape[0]),
            "dtype": str(dtype.name),
        }

    def _create_model(self, attrs: Dict[str, Any]) -> "LinearRegressionModel":
        return LinearRegressionModel(**attrs)

    def _cpu_fit(self, batch: _ArrayBatch) -> "LinearRegressionModel":
        from sklearn.linear_model import ElasticNet, LinearRegression as SkLR, Ridge

        reg = self.getOrDefault("regParam")
        l1r = self.getOrDefault("elasticNetParam")
        n = batch.X.shape[0]
        if reg == 0.0:
            sk = SkLR(fit_intercept=self.getOrDefault("fitIntercept"))
        elif l1r == 0.0:
            sk = Ridge(alpha=reg * n, fit_intercept=self.getOrDefault("fitIntercept"))
        else:
            sk = ElasticNet(
                alpha=reg, l1_ratio=l1r,
                fit_intercept=self.getOrDefault("fitIntercept"),
            )
        sk.fit(batch.X, batch.y, sample_weight=batch.weight)
        # summary metrics so the fallback path matches the TPU surface
        w = (
            np.ones(batch.X.shape[0])
            if batch.weight is None
            else np.asarray(batch.weight, np.float64)
        )
        y = np.asarray(batch.y, np.float64)
        resid = y - sk.predict(batch.X)
        sse = float((w * resid * resid).sum())
        from ..ops.linear import _summary_from_sse

        stats = _summary_from_sse(
            sse, float(w.sum()), float((w * y).sum()),
            float((w * y * y).sum()), self.getOrDefault("fitIntercept"),
        )
        return LinearRegressionModel(
            coef_=np.asarray(sk.coef_, batch.X.dtype),
            intercept_=float(sk.intercept_),
            n_iter_=int(np.max(getattr(sk, "n_iter_", 0)) or 0),
            rmse_=stats["rmse"],
            mse_=stats["mse"],
            r2_=stats["r2"],
            n_cols=int(batch.X.shape[1]),
            dtype=str(batch.X.dtype),
        )


class LinearRegressionTrainingSummary:
    """Spark LinearRegressionTrainingSummary analog (exact-from-stats)."""

    def __init__(self, rootMeanSquaredError: float, meanSquaredError: float,
                 r2: float, totalIterations: int) -> None:
        self.rootMeanSquaredError = float(rootMeanSquaredError)
        self.meanSquaredError = float(meanSquaredError)
        self.r2 = float(r2)
        self.totalIterations = int(totalIterations)


class LinearRegressionSummary:
    """Evaluation summary on a given dataset (pyspark
    LinearRegressionSummary surface over the metrics subsystem)."""

    def __init__(self, predictions, metrics, fit_intercept: bool = True) -> None:
        self.predictions = predictions
        self._m = metrics
        self._fit_intercept = bool(fit_intercept)

    @property
    def rootMeanSquaredError(self) -> float:
        return float(self._m.root_mean_squared_error)

    @property
    def meanSquaredError(self) -> float:
        return float(self._m.mean_squared_error)

    @property
    def meanAbsoluteError(self) -> float:
        return float(self._m.mean_absolute_error)

    @property
    def r2(self) -> float:
        # Spark passes throughOrigin=!fitIntercept (RegressionMetrics),
        # matching the training summary's through-origin SStot
        return float(self._m.r2(through_origin=not self._fit_intercept))

    @property
    def explainedVariance(self) -> float:
        return float(self._m.explained_variance)


class LinearRegressionModel(
    LinearRegressionClass, _TpuModel, _LinearRegressionTpuParams
):
    """Linear regression model (reference LinearRegressionModel
    regression.py:696-900)."""

    def __init__(self, **attrs: Any) -> None:
        super().__init__(**attrs)
        self.coef_: np.ndarray = np.asarray(attrs["coef_"])
        self.intercept_: float = float(attrs["intercept_"])
        self.n_iter_: int = int(attrs.get("n_iter_", 0))
        self.rmse_: float = float(attrs.get("rmse_", float("nan")))
        self.mse_: float = float(attrs.get("mse_", float("nan")))
        self.r2_: float = float(attrs.get("r2_", float("nan")))
        self.n_cols: int = int(attrs["n_cols"])
        self.dtype: str = str(attrs.get("dtype", "float32"))

    @property
    def coefficients(self) -> np.ndarray:
        """pyspark.ml parity."""
        return self.coef_

    @property
    def intercept(self) -> float:
        return self.intercept_

    @property
    def hasSummary(self) -> bool:
        return np.isfinite(self.rmse_)

    @property
    def summary(self) -> "LinearRegressionTrainingSummary":
        """Training summary (pyspark parity): weighted training rmse/mse/r2
        computed EXACTLY from the fit's sufficient statistics — no second
        data pass (Spark's summary re-reads the training data)."""
        if not self.hasSummary:
            raise RuntimeError("No training summary available on this model")
        return LinearRegressionTrainingSummary(
            rootMeanSquaredError=self.rmse_,
            meanSquaredError=self.mse_,
            r2=self.r2_,
            totalIterations=self.n_iter_,
        )

    def evaluate(self, dataset) -> "LinearRegressionSummary":
        """Metrics of this model on `dataset` (pyspark
        LinearRegressionModel.evaluate; the reference delegates to the
        pyspark CPU model, regression.py:770 — here the TPU transform +
        the metrics subsystem compute them natively)."""
        from ..core import _evaluate_frame
        from ..metrics import RegressionMetrics

        out_df, y, preds, weights = _evaluate_frame(self, dataset)
        # the SPARK param is what _copyValues propagates onto the model
        # (the backend _tpu_params dict stays at defaults here)
        fit_intercept = bool(self.getOrDefault("fitIntercept"))
        return LinearRegressionSummary(
            predictions=out_df,
            metrics=RegressionMetrics.from_predictions(y, preds, weights),
            fit_intercept=fit_intercept,
        )

    def predict(self, value) -> float:
        """Prediction for ONE sample (pyspark LinearRegressionModel.predict;
        the reference falls back to the pyspark CPU model,
        regression.py:764)."""
        v = np.asarray(value, np.float64).reshape(-1)
        coef = np.asarray(self.coef_, np.float64).reshape(-1)
        if v.shape[0] != coef.shape[0]:
            raise ValueError(
                f"feature vector has {v.shape[0]} entries; model expects "
                f"{coef.shape[0]}"
            )
        return float(coef @ v + float(self.intercept_))

    def _transform_device(self, Xs) -> Dict[str, Any]:
        import jax.numpy as jnp

        from ..ops.linear import linreg_predict

        return {
            self.getOrDefault("predictionCol"): linreg_predict(
                Xs,
                jnp.asarray(self.coef_.astype(Xs.dtype)),
                Xs.dtype.type(self.intercept_),
            )
        }

    def cpu(self):
        from sklearn.linear_model import LinearRegression as SkLR

        sk = SkLR()
        sk.coef_ = self.coef_.astype(np.float64)
        sk.intercept_ = float(self.intercept_)
        sk.n_features_in_ = self.n_cols
        return sk


# ---------------------------------------------------------------------------
# RandomForestRegressor (reference regression.py RandomForestRegressor +
# tree.py shared layer)
# ---------------------------------------------------------------------------


from ..models.tree import (  # noqa: E402
    _RandomForestEstimator,
    _RandomForestModel,
)


class RandomForestRegressor(_RandomForestEstimator):
    """Distributed random forest regressor on TPU (API parity: reference
    RandomForestRegressor in regression.py:860-1000 + tree.py:314-528).
    Variance-split histogram trees; ensemble parallelism over the mesh
    (each device fits numTrees/num_workers trees on its local rows,
    reference tree.py:330-341, docstring regression.py:895-899).

    Examples
    --------
    >>> import numpy as np, pandas as pd
    >>> from spark_rapids_ml_tpu.regression import RandomForestRegressor
    >>> df = pd.DataFrame({"features": [[0.0], [0.1], [0.9], [1.0]],
    ...                    "label": [0.0, 0.0, 10.0, 10.0]})
    >>> rf = RandomForestRegressor(numTrees=5, seed=3, num_workers=1)
    >>> model = rf.setFeaturesCol("features").setLabelCol("label").fit(df)
    >>> [round(v, 1) for v in model.transform(df)["prediction"]]
    [0.0, 0.0, 10.0, 10.0]
    """

    def _is_classification(self) -> bool:
        return False

    def _create_model(self, attrs: Dict[str, Any]) -> "RandomForestRegressionModel":
        return RandomForestRegressionModel(**attrs)

    def _cpu_fit(self, batch: _ArrayBatch) -> "RandomForestRegressionModel":
        raise NotImplementedError(
            "RandomForestRegressor has no CPU fallback; unset unsupported params"
        )


class RandomForestRegressionModel(_RandomForestModel):
    """Random forest regression model (reference
    RandomForestRegressionModel in regression.py)."""

    def _transform_device(self, Xs) -> Dict[str, Any]:
        import jax.numpy as jnp

        from ..ops.forest import forest_apply

        leaves = forest_apply(
            Xs,
            jnp.asarray(self.feature),
            jnp.asarray(self.threshold.astype(Xs.dtype)),
            jnp.asarray(self.left_child),
            max_depth=self.max_depth,
        )  # (T, n)
        stats = jnp.take_along_axis(
            jnp.asarray(self.leaf_stats.astype(Xs.dtype)),
            leaves[:, :, None], axis=1,
        )  # (T, n, 3): (weight, sum y, sum y^2)
        w = jnp.maximum(stats[:, :, 0], 1e-12)
        preds = (stats[:, :, 1] / w).mean(axis=0)
        return {self.getOrDefault("predictionCol"): preds.astype(Xs.dtype)}

    def cpu(self):
        from .classification import _NumpyForestPredictor

        return _NumpyForestPredictor(self, classification=False)

    def predict(self, value) -> float:
        """Single-sample forest mean (the reference falls back to the
        pyspark CPU model; the node-table forest is host-resident)."""
        v = np.asarray(value, np.float64).reshape(1, -1)
        if v.shape[1] != self.n_cols:
            raise ValueError(
                f"feature vector has {v.shape[1]} entries; model expects "
                f"{self.n_cols}"
            )
        return float(self.cpu().predict(v)[0])
