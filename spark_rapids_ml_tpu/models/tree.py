#
# Random forest shared layer — the analog of reference tree.py (745 LoC):
# `_RandomForestClass` param mapping (tree.py:91-153),
# `_RandomForestEstimator` (tree.py:314) and `_RandomForestModel`
# (tree.py:530), with the cuML single-GPU forest + treelite gather replaced
# by the ops/forest.py histogram builder (ensemble parallelism over the
# mesh, no collectives) and a portable JSON tree format.
#
from __future__ import annotations

import json
import math
from typing import Any, Dict, List, Optional, Union

import numpy as np

from ..core import FitInput, _TpuEstimator, _TpuModel
from ..params import (
    HasFeaturesCol,
    HasFeaturesCols,
    HasLabelCol,
    HasPredictionCol,
    HasSeed,
    HasWeightCol,
    Param,
    TypeConverters,
    _TpuParams,
)


class _RandomForestClass:
    """Param mapping (reference _RandomForestClass tree.py:91-153)."""

    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {
            "maxBins": "n_bins",
            "maxDepth": "max_depth",
            "numTrees": "n_estimators",
            "impurity": "split_criterion",
            "featureSubsetStrategy": "max_features",
            "bootstrap": "bootstrap",
            "seed": "random_state",
            "subsamplingRate": "max_samples",
            "minInstancesPerNode": "min_samples_leaf",
            "minInfoGain": "min_impurity_decrease",
            # accepted-and-ignored Spark params (reference tree.py:141-148)
            "maxMemoryInMB": "",
            "cacheNodeIds": "",
            "checkpointInterval": "",
            "minWeightFractionPerNode": "",
        }

    @classmethod
    def _param_value_mapping(cls):
        def subset_mapper(x):
            # reference featureSubsetStrategy mapping tree.py:113-135
            if x in ("auto", "all", "sqrt", "log2", "onethird"):
                return x
            try:
                xf = float(x)
                if xf == int(xf) and xf >= 1:
                    return int(xf)
                if 0.0 < xf <= 1.0:
                    return xf
            except ValueError:
                pass
            return None

        return {"featureSubsetStrategy": subset_mapper}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "n_estimators": 100,
            "max_depth": 16,
            "n_bins": 128,
            "max_features": "auto",
            "bootstrap": True,
            "random_state": None,
            "max_samples": 1.0,
            "min_samples_leaf": 1,
            "min_impurity_decrease": 0.0,
            "split_criterion": None,  # set per subclass (gini/variance)
            # width budget of the active-node frontier per level (ops/forest
            # builds exactly level-wise while 2^level <= max_active_nodes,
            # then best-first under this width); program size and compile
            # memory scale with it, not with 2^max_depth
            "max_active_nodes": 256,
            "verbose": False,
        }


class _RandomForestParams(
    _TpuParams,
    HasFeaturesCol,
    HasFeaturesCols,
    HasLabelCol,
    HasPredictionCol,
    HasSeed,
    HasWeightCol,
):
    maxDepth = Param("_", "maxDepth", "Maximum depth of the tree.",
                     TypeConverters.toInt)
    maxBins = Param("_", "maxBins",
                    "Max number of bins for discretizing continuous features.",
                    TypeConverters.toInt)
    impurity = Param("_", "impurity", "Criterion for information gain.",
                     TypeConverters.toString)
    featureSubsetStrategy = Param(
        "_", "featureSubsetStrategy",
        "The number of features to consider for splits at each tree node: "
        "auto, all, onethird, sqrt, log2, n (int or fraction).",
        TypeConverters.toString)
    subsamplingRate = Param(
        "_", "subsamplingRate",
        "Fraction of the training data used for learning each tree.",
        TypeConverters.toFloat)
    minInstancesPerNode = Param(
        "_", "minInstancesPerNode",
        "Minimum number of instances each child must have after a split.",
        TypeConverters.toInt)
    minInfoGain = Param(
        "_", "minInfoGain",
        "Minimum information gain for a split to be considered.",
        TypeConverters.toFloat)
    bootstrap = Param("_", "bootstrap", "Whether bootstrap samples are used.",
                      TypeConverters.toBoolean)
    maxMemoryInMB = Param("_", "maxMemoryInMB", "ignored.", TypeConverters.toInt)
    cacheNodeIds = Param("_", "cacheNodeIds", "ignored.", TypeConverters.toBoolean)
    checkpointInterval = Param("_", "checkpointInterval", "ignored.",
                               TypeConverters.toInt)
    minWeightFractionPerNode = Param("_", "minWeightFractionPerNode", "ignored.",
                                     TypeConverters.toFloat)

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(
            maxDepth=5,
            maxBins=32,
            featureSubsetStrategy="auto",
            subsamplingRate=1.0,
            minInstancesPerNode=1,
            minInfoGain=0.0,
            bootstrap=True,
        )

    def setFeaturesCol(self, value: Union[str, List[str]]):
        if isinstance(value, str):
            self._set_params(featuresCol=value)
        else:
            self._set_params(featuresCols=value)
        return self

    def setFeaturesCols(self, value: List[str]):
        return self._set_params(featuresCols=value)

    def setLabelCol(self, value: str):
        self._set(labelCol=value)
        return self

    def setPredictionCol(self, value: str):
        self._set(predictionCol=value)
        return self

    def setMaxDepth(self, value: int):
        return self._set_params(maxDepth=value)

    def setMaxBins(self, value: int):
        return self._set_params(maxBins=value)

    def setImpurity(self, value: str):
        return self._set_params(impurity=value)

    def setFeatureSubsetStrategy(self, value: str):
        return self._set_params(featureSubsetStrategy=value)

    def setSubsamplingRate(self, value: float):
        return self._set_params(subsamplingRate=value)

    def setMinInstancesPerNode(self, value: int):
        return self._set_params(minInstancesPerNode=value)

    def setMinInfoGain(self, value: float):
        return self._set_params(minInfoGain=value)

    def setBootstrap(self, value: bool):
        return self._set_params(bootstrap=value)

    def setSeed(self, value: int):
        return self._set_params(seed=value)

    def setWeightCol(self, value: str):
        return self._set_params(weightCol=value)


def _resolve_max_features(strategy, d: int, is_classification: bool) -> int:
    """featureSubsetStrategy -> #features per node (Spark semantics,
    reference tree.py:113-135)."""
    if strategy in (None, "auto"):
        return (
            max(1, int(math.sqrt(d)))
            if is_classification
            else max(1, d // 3)
        )
    if strategy == "all":
        return d
    if strategy == "sqrt":
        return max(1, int(math.sqrt(d)))
    if strategy == "log2":
        return max(1, int(math.log2(d)))
    if strategy == "onethird":
        return max(1, d // 3)
    if isinstance(strategy, int):
        return max(1, min(strategy, d))
    if isinstance(strategy, float):
        return max(1, min(int(strategy * d), d))
    raise ValueError(f"Unsupported featureSubsetStrategy: {strategy}")


class _RandomForestEstimatorParams(_RandomForestParams):
    """numTrees lives only on the estimator: the fitted model exposes it as
    a property (pyspark _TreeEnsembleModel.numTrees), which cannot coexist
    with a Param descriptor of the same name."""

    numTrees = Param("_", "numTrees", "Number of trees to train.",
                     TypeConverters.toInt)

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(numTrees=20)

    def setNumTrees(self, value: int):
        return self._set_params(numTrees=value)

    def getNumTrees(self) -> int:
        return self.getOrDefault("numTrees")


class _RandomForestEstimator(
    _RandomForestClass, _TpuEstimator, _RandomForestEstimatorParams
):
    """Shared fit logic (reference _RandomForestEstimator tree.py:314-528)."""

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._set_params(**kwargs)

    def _is_classification(self) -> bool:
        raise NotImplementedError

    def _is_supervised(self) -> bool:
        return True

    def _num_stat_classes(self, fit_input: FitInput) -> int:
        """Classes for the histogram channels (0 = regression)."""
        return 0

    def _criterion(self) -> int:
        from ..ops.forest import ENTROPY, GINI, VARIANCE

        imp = self._tpu_params.get("split_criterion")
        if imp is None:
            imp = "gini" if self._is_classification() else "variance"
        allowed = (
            {"gini": GINI, "entropy": ENTROPY}
            if self._is_classification()
            else {"variance": VARIANCE}
        )
        if imp not in allowed:
            raise ValueError(
                f"impurity '{imp}' is not supported for this task; "
                f"choose from {sorted(allowed)}"
            )
        return allowed[imp]

    def _fit_array(self, fit_input: FitInput) -> Dict[str, Any]:
        import jax

        from ..ops.forest import forest_fit

        p = fit_input.params
        mesh = fit_input.mesh
        n_dev = mesh.devices.size
        n_trees = int(p["n_estimators"])
        trees_per_worker = -(-n_trees // n_dev)  # ceil; extras trimmed below
        max_depth = int(p["max_depth"])
        seed = p.get("random_state")
        seed = int(seed) if seed is not None else int(self.getOrDefault("seed"))
        d = fit_input.pdesc.n
        max_features = _resolve_max_features(
            p.get("max_features", "auto"), d, self._is_classification()
        )
        trees = forest_fit(
            fit_input.X,
            fit_input.y,
            fit_input.w,
            seed,
            trees_per_worker=trees_per_worker,
            max_depth=max_depth,
            n_bins=int(p["n_bins"]),
            criterion=self._criterion(),
            n_classes=self._num_stat_classes(fit_input),
            max_features=max_features,
            min_instances=float(p["min_samples_leaf"]),
            min_info_gain=float(p["min_impurity_decrease"]),
            bootstrap=bool(p["bootstrap"]),
            subsample=float(p["max_samples"]),
            max_active=int(p.get("max_active_nodes", 256)),
            mesh=mesh,
        )
        # forest_fit dispatches tree chunks from the host and returns
        # host-side TreeArrays (fetching per chunk is the tunnel-safe sync)
        host = trees
        return {
            "feature": np.asarray(host.feature)[:n_trees],
            "threshold": np.asarray(host.threshold)[:n_trees],
            "leaf_stats": np.asarray(host.leaf_stats)[:n_trees],
            "gain": np.asarray(host.gain)[:n_trees],
            "count": np.asarray(host.count)[:n_trees],
            "left_child": np.asarray(host.left_child)[:n_trees],
            "max_depth": max_depth,
            "n_cols": d,
            "dtype": str(np.dtype(fit_input.dtype).name),
        }


class _RandomForestModel(_RandomForestClass, _TpuModel, _RandomForestParams):
    """Shared model logic (reference _RandomForestModel tree.py:530-745)."""

    def __init__(self, **attrs: Any) -> None:
        super().__init__(**attrs)
        self.feature: np.ndarray = np.asarray(attrs["feature"])
        self.threshold: np.ndarray = np.asarray(attrs["threshold"])
        self.leaf_stats: np.ndarray = np.asarray(attrs["leaf_stats"])
        self.gain: np.ndarray = np.asarray(attrs.get(
            "gain", np.zeros(self.feature.shape, np.float32)))
        self.count: np.ndarray = np.asarray(attrs.get(
            "count", np.zeros(self.feature.shape, np.float32)))
        if "left_child" in attrs:
            self.left_child: np.ndarray = np.asarray(attrs["left_child"])
        else:
            # models saved by the pre-node-table release used the implicit
            # heap layout: children of i at 2i+1 / 2i+2
            idx = np.arange(self.feature.shape[1], dtype=np.int32)
            heap = np.where(self.feature >= 0, 2 * idx + 1, -1)
            self.left_child = heap.astype(np.int32)
        self.max_depth: int = int(attrs["max_depth"])
        self.n_cols: int = int(attrs["n_cols"])
        self.dtype: str = str(attrs.get("dtype", "float32"))

    @property
    def numTrees(self) -> int:
        return int(self.feature.shape[0])

    @property
    def totalNumNodes(self) -> int:
        """Reachable (real) nodes across all trees."""
        return int(self._reachable_mask().sum())

    def _reachable_mask(self) -> np.ndarray:
        """(T, n_nodes) bool: nodes actually part of each tree.  Child
        table ids are always greater than the parent's (children are
        allocated level by level), so one ascending pass suffices."""
        T, n_nodes = self.feature.shape
        reach = np.zeros((T, n_nodes), bool)
        reach[:, 0] = True
        rows = np.arange(T)
        for i in range(n_nodes):
            split = reach[:, i] & (self.feature[:, i] >= 0)
            if not split.any():
                continue
            li = self.left_child[:, i]
            sel = rows[split]
            reach[sel, li[split]] = True
            reach[sel, li[split] + 1] = True
        return reach

    @property
    def treeWeights(self) -> List[float]:
        return [1.0] * self.numTrees

    @property
    def featureImportances(self) -> np.ndarray:
        """Gain-weighted importances, normalized per tree then averaged and
        re-normalized (Spark RandomForest.featureImportances semantics)."""
        T, max_nodes = self.feature.shape
        total = np.zeros((self.n_cols,), np.float64)
        for t in range(T):
            imp = np.zeros((self.n_cols,), np.float64)
            split = self.feature[t] >= 0
            np.add.at(
                imp,
                self.feature[t][split],
                (self.gain[t] * self.count[t])[split],
            )
            s = imp.sum()
            if s > 0:
                total += imp / s
        s = total.sum()
        return total / s if s > 0 else total

    def _apply_trees(self, X: np.ndarray) -> np.ndarray:
        """Leaf heap index per (tree, row) on device."""
        import jax
        import jax.numpy as jnp

        from ..ops.forest import forest_apply

        leaves = forest_apply(
            jnp.asarray(X),
            jnp.asarray(self.feature),
            jnp.asarray(self.threshold),
            jnp.asarray(self.left_child),
            max_depth=self.max_depth,
        )
        return np.asarray(jax.device_get(leaves))  # (T, n)

    def toDebugString(self) -> str:
        """Text dump of the forest (Spark model.toDebugString parity)."""
        lines = [f"RandomForestModel with {self.numTrees} trees"]
        for t in range(self.numTrees):
            lines.append(f"  Tree {t}:")
            stack = [(0, 2)]
            while stack:
                node, indent = stack.pop()
                pad = " " * indent
                f = int(self.feature[t, node])
                if f < 0:
                    val = self.leaf_stats[t, node]
                    lines.append(f"{pad}Predict: {val.tolist()}")
                else:
                    thr = float(self.threshold[t, node])
                    lc = int(self.left_child[t, node])
                    lines.append(f"{pad}If (feature {f} <= {thr:.6g})")
                    stack.append((lc + 1, indent + 1))
                    stack.append((lc, indent + 1))
        return "\n".join(lines)

    def to_json(self) -> str:
        """Portable treelite-JSON-style export (the analog of the
        reference's treelite serialization, tree.py:424-447)."""

        def node_dict(t: int, i: int) -> Dict[str, Any]:
            f = int(self.feature[t, i])
            if f < 0:
                return {"leaf_value": self.leaf_stats[t, i].tolist()}
            lc = int(self.left_child[t, i])
            return {
                "split_feature": f,
                "threshold": float(self.threshold[t, i]),
                "default_left": True,
                "left_child": node_dict(t, lc),
                "right_child": node_dict(t, lc + 1),
            }

        return json.dumps(
            {
                "num_trees": self.numTrees,
                "num_feature": self.n_cols,
                "trees": [node_dict(t, 0) for t in range(self.numTrees)],
            }
        )


__all__ = [
    "_RandomForestClass",
    "_RandomForestParams",
    "_RandomForestEstimator",
    "_RandomForestModel",
    "_resolve_max_features",
]
