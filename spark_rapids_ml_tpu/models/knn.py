#
# k-NN: exact NearestNeighbors + ApproximateNearestNeighbors — the analog of
# reference knn.py (1729 LoC).  The cuML NearestNeighborsMG.kneighbors call
# (knn.py:688-779, UCX p2p block exchange) becomes the ops/knn.py ppermute
# ring; the cuVS ivf_flat/ivf_pq local-index-per-partition strategy
# (knn.py:1516-1657) becomes ops/ivf.py bucketed-gather search.
#
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from ..core import _TpuEstimator, _TpuModel, _resolve_feature_params, FitInput
from ..data import DatasetLike, _ensure_dense, extract_arrays
from ..params import (
    HasFeaturesCol,
    HasFeaturesCols,
    HasIDCol,
    Param,
    TypeConverters,
    _TpuParams,
)


class _NNClass:
    """Param mapping (reference _NearestNeighborsClass knn.py:76-90)."""

    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {"k": "n_neighbors"}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {"n_neighbors": 5, "verbose": False}


class _KNNParams(_TpuParams, HasFeaturesCol, HasFeaturesCols, HasIDCol):
    k = Param("_", "k", "The number of nearest neighbors to retrieve.",
              TypeConverters.toInt)

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(k=5)

    def setK(self, value: int):
        return self._set_params(k=value)

    def getK(self) -> int:
        return self.getOrDefault("k")

    def setFeaturesCol(self, value: Union[str, List[str]]):
        if isinstance(value, str):
            self._set_params(featuresCol=value)
        else:
            self._set_params(featuresCols=value)
        return self

    def setFeaturesCols(self, value: List[str]):
        return self._set_params(featuresCols=value)

    def setIdCol(self, value: str):
        return self._set_params(idCol=value)


def _extract_with_ids(
    inst, dataset: DatasetLike, keep_sparse: bool = False
) -> Tuple[np.ndarray, np.ndarray, Any, bool]:
    """Extract (X, ids, source_frame).  The analog of `_ensureIdCol`
    (reference params.py:91-129): when the user names an idCol it is read
    from the dataset, otherwise monotonically-increasing row ids are
    generated.  With `keep_sparse` a CSR input stays CSR — the exact-kNN
    paths stage it dense chunk-by-chunk (RowStager.stage_sparse), the
    analog of the reference keeping CSR end-to-end through fit staging
    (core.py:183-265)."""
    import pandas as pd

    from ..data import _is_sparse

    features_col, features_cols = _resolve_feature_params(inst)
    id_col = (
        inst.getOrDefault("idCol")
        if inst.hasParam("idCol") and inst.isSet("idCol")
        else None
    )
    batch = extract_arrays(
        dataset,
        features_col=features_col,
        features_cols=features_cols,
        id_col=id_col,
        dtype=None,
        supervised=False,
    )
    if keep_sparse and _is_sparse(batch.X):
        X = batch.X.tocsr()
    else:
        X = _ensure_dense(batch.X)
    if batch.row_id is not None:
        ids = np.asarray(batch.row_id)
        auto_ids = False
    else:
        ids = np.arange(X.shape[0], dtype=np.int64)
        auto_ids = True
    df = dataset if isinstance(dataset, pd.DataFrame) else None
    return X, ids, df, auto_ids


def _gather_items(X: np.ndarray, ids: np.ndarray, auto_ids: bool):
    """Multi-process item gather for the replicated-model contract.  Auto-
    generated ids are LOCAL positions per process; regenerate them as global
    positions after the gather so they match single-process numbering
    (user-provided idCol values pass through untouched)."""
    from ..data import _is_sparse
    from ..parallel.mesh import allgather_host_csr, allgather_host_rows

    X = allgather_host_csr(X) if _is_sparse(X) else allgather_host_rows(X)
    if auto_ids:
        ids = np.arange(X.shape[0], dtype=np.int64)
    else:
        ids = allgather_host_rows(ids)
    return X, ids


def _item_layout_for(X: np.ndarray, ids: np.ndarray, auto_ids: bool):
    """Decide the item layout for an exact-kNN fit: replicate the full set
    on every host (small data — the simple contract), or keep FEATURES
    process-local past `knn_replicate_max_bytes` and replicate only the
    cheap global id vector (the analog of the reference's distributed
    block exchange, knn.py:688-779, where no worker holds the full item
    matrix).  Returns (X, ids_global, distributed, n_items_global)."""
    import jax

    from ..config import get_config
    from ..parallel.mesh import allgather_host_rows

    if jax.process_count() == 1:
        X, ids = _gather_items(X, ids, auto_ids)
        return X, ids, False, X.shape[0]
    from jax.experimental import multihost_utils

    counts = np.asarray(
        multihost_utils.process_allgather(
            np.asarray(X.shape[0], np.int64)
        )
    ).reshape(-1)
    n_global = int(counts.sum())
    total_bytes = n_global * int(X.shape[1]) * X.dtype.itemsize
    if total_bytes <= int(get_config("knn_replicate_max_bytes")):
        X, ids = _gather_items(X, ids, auto_ids)
        return X, ids, False, n_global
    if auto_ids:
        ids_global = np.arange(n_global, dtype=np.int64)
    else:
        ids_global = allgather_host_rows(ids)
    return X, ids_global, True, n_global


def _assemble_knn_df(q_ids, indices, dist, sort_by_query_id: bool):
    import pandas as pd

    knn_df = pd.DataFrame(
        {
            "query_id": q_ids,
            "indices": list(indices),
            "distances": list(dist.astype(np.float32)),
        }
    )
    if sort_by_query_id:
        knn_df = knn_df.sort_values("query_id", ignore_index=True)
    return knn_df


def _flatten_join(knn_df, distCol: str, drop_invalid: bool):
    """Vectorized (item_id, query_id, dist) flattening of a knn_df."""
    import pandas as pd

    idx = np.stack(knn_df["indices"].to_numpy())
    dist = np.stack(knn_df["distances"].to_numpy())
    k = idx.shape[1]
    out = pd.DataFrame(
        {
            "item_id": idx.reshape(-1),
            "query_id": np.repeat(knn_df["query_id"].to_numpy(), k),
            distCol: dist.reshape(-1).astype(np.float64),
        }
    )
    if drop_invalid:
        out = out[(out["item_id"] >= 0) & np.isfinite(out[distCol])]
        out = out.reset_index(drop=True)
    return out


class _NNModelBase(_TpuModel):
    """Shared kneighbors/join surface for the exact and approximate models."""

    item_features: np.ndarray
    item_ids: np.ndarray
    _item_df: Any
    # exact search stages CSR queries chunk-bounded; the ANN index probes
    # take dense host queries
    _sparse_query_ok = False

    def _search(self, Q: np.ndarray, k: int) -> Tuple[np.ndarray, np.ndarray]:
        raise NotImplementedError

    def _metric(self) -> str:
        if self.hasParam("metric"):
            return str(self._tpu_params.get("metric",
                                            self.getOrDefault("metric")))
        return "euclidean"

    def _apply_metric(self, d2: np.ndarray) -> np.ndarray:
        """Map squared-euclidean kernel output to the requested metric.
        Cosine search runs on unit vectors, where cosine distance
        1 - cos = ||u-v||^2 / 2 (the cuVS cosine convention)."""
        metric = self._metric()
        if metric == "sqeuclidean":
            return d2
        if metric == "euclidean":
            return np.sqrt(d2)
        if metric == "cosine":
            return d2 / 2.0
        raise ValueError(
            f"metric '{metric}' is not supported; use euclidean, "
            "sqeuclidean, or cosine"
        )

    def kneighbors(
        self, query_df: DatasetLike, sort_knn_df_by_query_id: bool = True
    ) -> Tuple[Any, Any, Any]:
        """Return (item_df, query_df, knn_df) where knn_df holds one row per
        query: `query_id`, `indices` (item ids), `distances` — reference
        knn.py:579-657 (exact) / knn.py:1256-1470 (approximate; unreachable
        slots are id -1 at distance inf)."""
        import pandas as pd

        from ..data import _is_sparse

        Q, q_ids, q_df, _ = _extract_with_ids(
            self, query_df, keep_sparse=self._sparse_query_ok
        )
        k = int(self._tpu_params.get("n_neighbors", self.getOrDefault("k")))
        dist, pos = self._search(Q if _is_sparse(Q) else np.asarray(Q), k)
        indices = np.where(pos >= 0, self.item_ids[np.maximum(pos, 0)], -1)
        knn_df = _assemble_knn_df(q_ids, indices, dist, sort_knn_df_by_query_id)
        item_df = self._item_df
        if item_df is None:
            item_df = pd.DataFrame({"id": self.item_ids})
        return item_df, q_df, knn_df

    def _transform(self, dataset: DatasetLike):
        raise NotImplementedError(
            f"{type(self).__name__} does not support transform(); use "
            "kneighbors() or the join method (reference knn.py:560-577)."
        )

    def cpu(self):
        from sklearn.neighbors import NearestNeighbors as SkNN

        sk = SkNN(n_neighbors=int(self.getOrDefault("k")), algorithm="brute")
        sk.fit(self.item_features)
        return sk


def _finalize_nn_fit(est, model, df):
    model._item_df = df
    est._copyValues(model)
    model._tpu_params = dict(est._tpu_params)
    model._num_workers = est._num_workers
    model._float32_inputs = est._float32_inputs
    return model


class NearestNeighbors(_NNClass, _TpuEstimator, _KNNParams):
    """Exact brute-force k nearest neighbors (API parity: reference
    NearestNeighbors knn.py:208-513).

    `fit` only captures the item set (the reference's fit tags the item
    DataFrame, knn.py:352-372 — no training happens); the distributed work
    runs in `kneighbors`, where item and query rows are sharded over the
    mesh and item blocks rotate through a `ppermute` ring (the ICI-native
    analog of the reference's UCX p2p block exchange, knn.py:688-779).

    Examples
    --------
    >>> import pandas as pd
    >>> from spark_rapids_ml_tpu.knn import NearestNeighbors
    >>> items = pd.DataFrame({"features": [[0.0, 0.0], [1.0, 1.0], [5.0, 5.0]]})
    >>> queries = pd.DataFrame({"features": [[0.2, 0.2], [4.9, 5.1]]})
    >>> model = NearestNeighbors(k=1).setFeaturesCol("features").fit(items)
    >>> _, _, knn_df = model.kneighbors(queries)
    >>> [int(i[0]) for i in knn_df["indices"]]
    [0, 2]
    """

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._set_params(**kwargs)

    def _fit(self, dataset: DatasetLike) -> "NearestNeighborsModel":
        from ..data import _is_sparse

        X, ids, df, auto_ids = _extract_with_ids(self, dataset,
                                                 keep_sparse=True)
        # multi-process: each process fit() sees its local items.  Small
        # item sets replicate on every host (simple model contract); past
        # `knn_replicate_max_bytes` features stay PROCESS-LOCAL and only
        # the id vector replicates — kneighbors stages each process's
        # block into the global sharded layout, so no host or device ever
        # holds the full N x d matrix.  CSR items stay CSR on the host;
        # kneighbors stages them dense chunk-by-chunk.
        X, ids, distributed, n_global = _item_layout_for(
            X if _is_sparse(X) else np.asarray(X), np.asarray(ids), auto_ids
        )
        model = NearestNeighborsModel(
            item_features=X if _is_sparse(X) else np.asarray(X),
            item_ids=ids,
            n_cols=int(X.shape[1]),
            dtype=str(X.dtype),
            distributed_items=distributed,
            n_items_global=n_global,
        )
        return _finalize_nn_fit(self, model, df)

    def _fit_array(self, fit_input: FitInput) -> Dict[str, Any]:  # pragma: no cover
        raise NotImplementedError("fit is overridden; no kernel at fit time")

    def _create_model(self, attrs: Dict[str, Any]):  # pragma: no cover
        return NearestNeighborsModel(**attrs)


class NearestNeighborsModel(_NNClass, _NNModelBase, _KNNParams):
    """Fitted exact k-NN model (reference NearestNeighborsModel knn.py:516-940)."""

    _sparse_query_ok = True

    def __init__(self, **attrs: Any) -> None:
        super().__init__(**attrs)
        from ..data import _is_sparse

        feats = attrs["item_features"]
        # sparse fits keep the item set CSR on the host (persisted as CSR
        # component arrays, core.py _Writer.save); search stages it dense
        # chunk-by-chunk (stage_sparse), bounding host peak memory
        self.item_features = (
            feats.tocsr() if _is_sparse(feats) else np.asarray(feats)
        )
        self.item_ids: np.ndarray = np.asarray(attrs["item_ids"])
        self.n_cols = int(attrs.get("n_cols", self.item_features.shape[1]))
        self.dtype = str(attrs.get("dtype", self.item_features.dtype))
        # distributed-item layout: `item_features` holds only THIS
        # process's rows; `item_ids` is the (cheap) global id vector
        self.distributed_items = bool(attrs.get("distributed_items", False))
        self.n_items_global = int(
            attrs.get("n_items_global", self.item_features.shape[0])
        )
        self._item_df = None
        self._device_items = None  # lazily cached device-resident item shards

    def _staged_items(self, mesh, dtype):
        """Item rows + validity + positional ids staged onto the mesh once
        and reused across kneighbors calls.  Replicated item arrays shard
        via `RowStager.for_replicated` (each process stages its even block
        of the global rows); distributed item arrays stage each process's
        LOCAL block directly — either way positional ids come from the
        same layout in global process-major order and are remapped to user
        ids on the host afterwards (as the reference remaps cuml row ids,
        knn.py:787-801)."""
        from ..data import _is_sparse
        from ..parallel.mesh import RowStager

        key = (id(mesh), str(dtype))
        if self._device_items is not None and self._device_items[0] == key:
            return self._device_items[1]
        # items ALWAYS stage contiguous (interleave=False): the
        # interleaved layout breaks distance ties by device-layout
        # position, so a sparse fit (contiguous-only staging) or a
        # different device count would return different neighbors among
        # tied candidates.  Contiguous staging ties break by original
        # item position — identical for dense/sparse and for any n_dev —
        # while bucketed padding still shares compiles.
        sparse_items = _is_sparse(self.item_features)
        if self.distributed_items:
            st = RowStager(
                self.item_features.shape[0], mesh, interleave=False,
            )
        else:
            st = RowStager.for_replicated(
                self.item_features.shape[0], mesh, interleave=False,
            )
        staged = (
            st.stage_sparse(self.item_features, dtype)
            if sparse_items
            else st.stage(self.item_features, dtype),
            st.mask(dtype),
            st.row_ids(),
        )
        self._device_items = (key, staged)
        return staged

    def save(self, path: str) -> None:
        if self.distributed_items:
            raise NotImplementedError(
                "A distributed-item NearestNeighborsModel holds only this "
                "process's feature rows; persist the source dataset (or "
                "lower knn_replicate_max_bytes to refit replicated) "
                "instead of saving the model."
            )
        super().save(path)

    def cpu(self):
        if self.distributed_items:
            # sklearn on the local block would silently search a fraction
            # of the items with positions that don't match the global ids
            raise NotImplementedError(
                "cpu() needs the full item set; this distributed-item "
                "model holds only this process's rows"
            )
        return super().cpu()

    def _search(self, Q: np.ndarray, k: int) -> Tuple[np.ndarray, np.ndarray]:
        """Distributed ring brute force; (metric distances, positional
        indices) trimmed of padding."""
        from ..ops.knn import knn_ring_topk, knn_topk_single
        from ..parallel import TpuContext
        from ..parallel.mesh import RowStager

        from ..data import _is_sparse

        n_items = self.n_items_global
        if k > n_items:
            raise ValueError(f"k={k} exceeds the number of items ({n_items})")
        with TpuContext(self.num_workers, require_p2p=True) as ctx:
            mesh = ctx.mesh
        dtype = self._out_dtype(self.item_features)
        items, valid, ids = self._staged_items(mesh, dtype)
        # queries stage contiguous like the items: the query's device
        # decides its ring start offset, so an interleaved dense layout
        # vs the contiguous sparse layout would merge item blocks in
        # different orders and resolve distance TIES differently
        if _is_sparse(Q):
            qst = RowStager.for_replicated(
                Q.shape[0], mesh, interleave=False
            )
            queries = qst.stage_sparse(Q, dtype)
        else:
            qst = RowStager.for_replicated(
                np.asarray(Q).shape[0], mesh, interleave=False
            )
            queries = qst.stage(np.asarray(Q), dtype)
        if mesh.devices.size == 1:
            d2, idx = knn_topk_single(items, valid, ids, queries, k=k)
        else:
            d2, idx = knn_ring_topk(items, valid, ids, queries, k=k, mesh=mesh)
        return self._apply_metric(qst.fetch(d2)), qst.fetch(idx)

    def exactNearestNeighborsJoin(self, query_df: DatasetLike, distCol: str = "distCol"):
        """Flattened (item_id, query_id, distance) join — reference
        knn.py:803-940."""
        _, _, knn_df = self.kneighbors(query_df)
        return _flatten_join(knn_df, distCol, drop_invalid=False)

    def _get_model_attributes(self) -> Dict[str, Any]:
        return {
            "item_features": self.item_features,
            "item_ids": self.item_ids,
            "n_cols": self.n_cols,
            "dtype": self.dtype,
        }


class _ANNClass:
    """Param mapping (reference _ApproximateNearestNeighborsClass
    knn.py:843-865)."""

    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {"k": "n_neighbors", "algorithm": "algorithm",
                "algoParams": "algo_params", "metric": "metric"}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "n_neighbors": 5,
            "algorithm": "ivfflat",
            "algo_params": None,
            "metric": "euclidean",
            "verbose": False,
        }


class _ANNParams(_KNNParams):
    algorithm = Param("_", "algorithm",
                      "ANN algorithm: ivfflat, ivfpq, or cagra.",
                      TypeConverters.toString)
    algoParams = Param("_", "algoParams",
                       "algorithm-specific parameters (nlist/nprobe/M/n_bits/"
                       "refine_ratio).", TypeConverters.identity)
    metric = Param("_", "metric", "distance metric (euclidean/sqeuclidean/cosine).",
                   TypeConverters.toString)

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(algorithm="ivfflat", metric="euclidean")

    def setAlgorithm(self, value: str):
        return self._set_params(algorithm=value)

    def getAlgorithm(self) -> str:
        return self.getOrDefault("algorithm")

    def setAlgoParams(self, value: Dict[str, Any]):
        return self._set_params(algoParams=value)

    def setMetric(self, value: str):
        return self._set_params(metric=value)


_SUPPORTED_ANN_ALGOS = ("ivfflat", "ivfpq", "cagra")


class ApproximateNearestNeighbors(_ANNClass, _TpuEstimator, _ANNParams):
    """Approximate k nearest neighbors (API parity: reference
    ApproximateNearestNeighbors knn.py:941-1222, backed by cuVS
    ivf_flat/ivf_pq/cagra).

    `fit` trains the index: an ops/kmeans.py coarse quantizer plus (for
    `ivfpq`) per-subspace residual codebooks — the analog of the cuVS index
    build (reference knn.py:1516-1530) — or, for `cagra`, an NN-descent
    kNN graph searched by fixed-iteration beam traversal (ops/cagra.py; the
    analog of cuVS CAGRA, reference knn.py:1581-1657).  `kneighbors`
    shards queries over the mesh and probes the replicated index (the
    single-controller inverse of the reference's shard-index/
    broadcast-queries layout, knn.py:1448-1470).

    algoParams (reference knn.py:860-865 passthrough dict):
      - nlist: number of inverted lists (default ~sqrt(n))
      - nprobe: lists probed per query (default 20, clamped to nlist)
      - M / n_bits: ivfpq subspaces / code bits (defaults 8 / 8)
      - refine_ratio: ivfpq exact re-rank multiplier (default 2)
      - graph_degree / nn_descent_niter: cagra graph degree (default 32)
        and NN-descent build rounds (default 8)
      - nn_descent_sample: cagra local-join width per round (default
        graph_degree; pass 2*graph_degree for the exhaustive join)
      - itopk_size / max_iterations: cagra search beam width (default 64)
        and traversal iterations (default 12) — cuVS search param names

    Examples
    --------
    >>> import numpy as np
    >>> from spark_rapids_ml_tpu.knn import ApproximateNearestNeighbors
    >>> X = np.random.default_rng(0).normal(size=(256, 16)).astype("float32")
    >>> ann = ApproximateNearestNeighbors(k=4, algoParams={"nlist": 8, "nprobe": 8})
    >>> _, _, knn_df = ann.fit(X).kneighbors(X[:10])
    >>> [int(i[0]) for i in knn_df["indices"]] == list(range(10))
    True
    """

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._set_params(**kwargs)

    def _fit(self, dataset: DatasetLike) -> "ApproximateNearestNeighborsModel":
        from ..ops import ivf as ivf_ops

        X, ids, df, auto_ids = _extract_with_ids(self, dataset)
        # replicated-model contract in multi-process mode (see
        # NearestNeighbors._fit); each process builds the identical index
        X, ids = _gather_items(np.asarray(X), np.asarray(ids), auto_ids)
        X = np.ascontiguousarray(X, dtype=np.float32)
        algo = str(self._tpu_params.get("algorithm", "ivfflat")).lower()
        if algo not in _SUPPORTED_ANN_ALGOS:
            raise ValueError(
                f"algorithm '{algo}' is not supported; choose from "
                f"{_SUPPORTED_ANN_ALGOS}"
            )
        metric = str(self._tpu_params.get("metric", "euclidean"))
        if metric not in ("euclidean", "sqeuclidean", "cosine"):
            raise ValueError(
                f"metric '{metric}' is not supported; use euclidean, "
                "sqeuclidean, or cosine"
            )
        if metric == "cosine":
            # cuVS cosine == euclidean on unit vectors / 2: build the index
            # over normalized items (queries normalize at search)
            X = X / np.maximum(
                np.linalg.norm(X, axis=1, keepdims=True), 1e-12
            ).astype(np.float32)
        ap = dict(self._tpu_params.get("algo_params") or {})
        n = X.shape[0]
        nlist = int(ap.get("nlist", max(1, min(int(np.sqrt(n)), n))))
        nlist = max(1, min(nlist, n))
        attrs: Dict[str, Any] = {
            "item_features": X,
            "item_ids": ids,
            "n_cols": int(X.shape[1]),
            "dtype": str(X.dtype),
            "algorithm": algo,
            "nlist": nlist,
        }
        if algo == "cagra":
            from ..ops.cagra import build_cagra_graph
            from ..parallel.mesh import (
                _chunked_device_get,
                _chunked_device_put,
            )

            deg = int(ap.get("graph_degree", 32))
            deg = max(1, min(deg, n - 1))
            rounds = int(ap.get("nn_descent_niter", 8))
            sample = ap.get("nn_descent_sample")
            # bounded-piece upload: a one-shot put of a BASELINE-scale
            # item matrix (10M x 128 = 5 GB) exceeds the tunnel
            # transfer-RPC ceiling (mesh._chunked_device_put rationale)
            graph = build_cagra_graph(
                _chunked_device_put(np.ascontiguousarray(X)),
                seed=0,
                deg=deg,
                rounds=max(rounds, 1),
                sample=None if sample is None else int(sample),
            )
            # bounded-slice fetch: a one-shot 1.28 GB graph download
            # crashed the worker after a fully successful 10M build
            attrs.update(cagra_graph=_chunked_device_get(graph))
        elif algo == "ivfflat":
            index = ivf_ops.build_ivfflat(X, nlist=nlist)
            attrs.update(
                ivf_centers=index.centers,
                ivf_buckets=index.buckets,
                ivf_bucket_ids=index.bucket_ids,
                ivf_bucket_valid=index.bucket_valid,
                ivf_sub_table=index.sub_table,
            )
        else:  # ivfpq
            M = int(ap.get("M", 8))
            d = X.shape[1]
            if d % M != 0:  # shrink M to a divisor (cuVS requires divisibility)
                M = next(m for m in range(min(M, d), 0, -1) if d % m == 0)
            n_bits = int(ap.get("n_bits", 8))
            if not 1 <= n_bits <= 8:
                # codes are stored uint8; >8 bits would silently wrap
                raise ValueError(f"ivfpq n_bits must be in [1, 8], got {n_bits}")
            index = ivf_ops.build_ivfpq(X, nlist=nlist, M=M, n_bits=n_bits)
            attrs.update(
                ivf_centers=index.centers,
                pq_codebooks=index.codebooks,
                pq_codes=index.codes,
                ivf_bucket_ids=index.bucket_ids,
                ivf_bucket_valid=index.bucket_valid,
                ivf_sub_table=index.sub_table,
                pq_M=M,
            )
        model = ApproximateNearestNeighborsModel(**attrs)
        return _finalize_nn_fit(self, model, df)

    def _fit_array(self, fit_input: FitInput) -> Dict[str, Any]:  # pragma: no cover
        raise NotImplementedError("fit is overridden; index build is host-orchestrated")

    def _create_model(self, attrs: Dict[str, Any]):  # pragma: no cover
        return ApproximateNearestNeighborsModel(**attrs)


class ApproximateNearestNeighborsModel(_ANNClass, _NNModelBase, _ANNParams):
    """Fitted ANN model (reference ApproximateNearestNeighborsModel
    knn.py:1223-1729)."""

    def __init__(self, **attrs: Any) -> None:
        super().__init__(**attrs)
        self.item_features: np.ndarray = np.asarray(attrs["item_features"])
        self.item_ids: np.ndarray = np.asarray(attrs["item_ids"])
        self.n_cols = int(attrs.get("n_cols", self.item_features.shape[1]))
        self.dtype = str(attrs.get("dtype", self.item_features.dtype))
        self.algorithm_: str = str(attrs.get("algorithm", "ivfflat"))
        self.nlist_: int = int(attrs.get("nlist", 1))
        if (
            self.algorithm_ in ("ivfflat", "ivfpq")
            and "ivf_sub_table" not in attrs
            and "ivf_centers" in attrs
        ):
            # models persisted before sub-list splitting: every list is
            # its own (only) sub-list — the identity table
            attrs["ivf_sub_table"] = np.arange(
                np.asarray(attrs["ivf_centers"]).shape[0], dtype=np.int32
            )[:, None]
        self._attrs = attrs
        self._item_df = None
        self._device_index = None  # lazily cached device-resident index

    def _staged_index(self, names):
        """The inverted file staged into HBM once and reused across
        kneighbors calls (replicated; queries are what gets sharded).
        Large arrays (a 10M-item inverted file is ~5+ GB) upload in
        bounded pieces — a one-shot put of that size can never finish
        inside the tunnel transfer-RPC deadline (mesh._chunked_device_put
        rationale)."""
        from jax.sharding import NamedSharding, PartitionSpec

        from ..parallel.mesh import _chunked_device_put

        if self._device_index is None or self._device_index[0] != names:
            from ..parallel import TpuContext

            with TpuContext(self.num_workers) as ctx:
                repl = NamedSharding(ctx.mesh, PartitionSpec())
            # every attribute gets the same replicated placement; the
            # helper one-shot-puts anything under the transfer ceiling
            staged = tuple(
                _chunked_device_put(
                    np.ascontiguousarray(np.asarray(self._attrs[n])), repl
                )
                for n in names
            )
            self._device_index = (names, staged)
        return self._device_index[1]

    def _search(self, Q: np.ndarray, k: int) -> Tuple[np.ndarray, np.ndarray]:
        """Chunked search: bounds the per-dispatch candidate working set
        (IVF gathers nprobe·bucket·d floats per query, CAGRA beam·deg·d —
        at 10k+ queries one dispatch would materialize tens of GB)."""
        from ..parallel import TpuContext

        n_items = int(self.item_features.shape[0])
        if k > n_items:
            # search_cagra's top_k(beam) and the IVF shortlists all require
            # k <= n; fail with a clear message instead of an XLA error
            raise ValueError(
                f"k={k} exceeds the number of indexed items ({n_items})"
            )
        Q = np.ascontiguousarray(Q, dtype=np.float32)
        if self._metric() == "cosine":
            # normalize once for all chunks (index is built on unit vectors)
            Q = Q / np.maximum(
                np.linalg.norm(Q, axis=1, keepdims=True), 1e-12
            ).astype(np.float32)
        with TpuContext(self.num_workers) as ctx:
            mesh = ctx.mesh
        nq = int(Q.shape[0])
        per_q = self._per_query_candidate_bytes(k)
        from ..config import get_config

        budget = int(get_config("hbm_bytes")) // 8
        # floor 1, not a fixed batch: a 64-query floor at BASELINE-scale
        # bucket sizes forced a working set far past HBM (10M ANN run)
        chunk = max(1, min(nq, budget // max(per_q, 1)))
        if nq <= chunk:
            return self._search_chunk(Q, k, mesh)
        outs = [
            self._search_chunk(Q[lo : lo + chunk], k, mesh)
            for lo in range(0, nq, chunk)
        ]
        return (
            np.concatenate([d for d, _ in outs]),
            np.concatenate([p for _, p in outs]),
        )

    def _per_query_candidate_bytes(self, k: int) -> int:
        ap = dict(self._tpu_params.get("algo_params") or {})
        d = int(self.n_cols)
        if self.algorithm_ == "cagra":
            deg = int(self._attrs["cagra_graph"].shape[1])
            beam = max(int(ap.get("itopk_size", 64)), k)
            width = beam * (1 + deg) + deg
        elif self.algorithm_ == "ivfflat":
            # the probe-rank fold visits ONE list per step: per-query
            # peak is a single (mb, d) gather + distances, not nprobe x
            mb = int(self._attrs["ivf_buckets"].shape[1])
            width = mb
        else:  # ivfpq: one (mb, M) code gather per step + the per-parent
            # ADC LUT block (nprobe, M, ksub) precomputed up front and
            # live across the whole fold loop (ops/ivf.py search_ivfpq)
            mb = int(self._attrs["pq_codes"].shape[1])
            M = int(self._attrs.get("pq_M", 8))
            ksub = int(self._attrs["pq_codebooks"].shape[1])
            nprobe = max(1, min(int(ap.get("nprobe", 20)), self.nlist_))
            return (mb * (M * 4 + 8) + nprobe * M * ksub) * 4
        # distances + gathered vectors + dedup/sort keys, ~2x slack
        return width * (d + 4) * 4 * 2

    def _search_chunk(
        self, Q: np.ndarray, k: int, mesh
    ) -> Tuple[np.ndarray, np.ndarray]:
        from ..ops import ivf as ivf_ops
        from ..parallel.mesh import RowStager

        qst = RowStager.for_replicated(Q.shape[0], mesh)
        Qs = qst.stage(Q, np.float32)
        ap = dict(self._tpu_params.get("algo_params") or {})
        nprobe = int(ap.get("nprobe", 20))
        # nprobe means DISTINCT coarse parent cells — sub-list splitting
        # (ops/ivf.py) is expanded inside the search via sub_table
        nprobe = max(1, min(nprobe, self.nlist_))
        if self.algorithm_ == "cagra":
            from ..ops.cagra import search_cagra

            items, graph = self._staged_index(("item_features", "cagra_graph"))
            beam = int(ap.get("itopk_size", 64))
            beam = max(beam, k)
            iters = int(ap.get("max_iterations", 12))
            d2, pos = search_cagra(
                Qs, items, graph, k=k, beam=beam, iters=max(iters, 1)
            )
        elif self.algorithm_ == "ivfflat":
            centers, buckets, bids, bvalid, stab = self._staged_index(
                ("ivf_centers", "ivf_buckets", "ivf_bucket_ids",
                 "ivf_bucket_valid", "ivf_sub_table")
            )
            d2, pos = ivf_ops.search_ivfflat(
                Qs, centers, buckets, bids, bvalid, stab,
                nprobe=nprobe, k=k,
            )
        else:
            centers, codebooks, codes, bids, bvalid, stab = (
                self._staged_index(
                    ("ivf_centers", "pq_codebooks", "pq_codes",
                     "ivf_bucket_ids", "ivf_bucket_valid", "ivf_sub_table")
                )
            )
            refine = int(ap.get("refine_ratio", 2))
            k2 = min(max(k * refine, k), self.item_features.shape[0])
            d2, pos = ivf_ops.search_ivfpq(
                Qs, centers, codebooks, codes, bids, bvalid, stab,
                nprobe=nprobe, k=k2,
            )
            return self._exact_rerank(Q, qst.fetch(pos), k)
        # CAGRA / IVF-Flat: the kernels rank by matmul-identity distances
        # (x2 + c2 - 2xc), whose f32 cancellation leaves ~1e-4 absolute
        # error (a point's own distance comes back ~0.008, not 0).  The
        # final top-k is re-scored in the cancellation-free diff form —
        # the same exact pass cuVS `refine` runs (reference
        # knn.py:1627-1657) — so reported distances are exact and
        # near-ties order correctly.
        return self._exact_rerank(Q, qst.fetch(pos), k)

    def _exact_rerank(
        self, Q: np.ndarray, pos: np.ndarray, k: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Exact diff-form re-score + re-rank of a (q, >=k) candidate id
        block; invalid slots (pos < 0) sort last and stay -1."""
        safe = np.maximum(pos, 0)
        cand = self.item_features[safe]  # (q, k2, d)
        diff = cand - Q[:, None, :]
        exact = (diff * diff).sum(axis=2).astype(np.float32)
        exact = np.where(pos >= 0, exact, np.inf)
        order = np.argsort(exact, axis=1, kind="stable")[:, :k]
        d2 = np.take_along_axis(exact, order, axis=1)
        out_pos = np.take_along_axis(pos, order, axis=1)
        return self._apply_metric(d2), out_pos

    def approxSimilarityJoin(self, query_df: DatasetLike, distCol: str = "distCol"):
        """Flattened approximate join (reference knn.py:1671-1729); slots
        with no reachable candidate are dropped."""
        _, _, knn_df = self.kneighbors(query_df)
        return _flatten_join(knn_df, distCol, drop_invalid=True)

    def _get_model_attributes(self) -> Dict[str, Any]:
        return dict(self._attrs)


__all__ = [
    "NearestNeighbors",
    "NearestNeighborsModel",
    "ApproximateNearestNeighbors",
    "ApproximateNearestNeighborsModel",
]
