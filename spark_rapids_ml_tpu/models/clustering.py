#
# Clustering: KMeans (+ DBSCAN below) — the analog of reference
# clustering.py (1182 LoC).  The cuML KMeansMG distributed fit
# (clustering.py:377-411) is replaced by ops/kmeans.py: Gumbel-max
# k-means++ seeding + a single compiled Lloyd while_loop with psum'd
# centroid updates.  The reference's >1GB model-chunking machinery
# (clustering.py:433-498) has no analog: there is no Spark row-size limit
# in this runtime, model arrays go straight to the host.
#
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core import FitInput, _TpuEstimator, _TpuModel
from ..params import (
    HasFeaturesCol,
    HasFeaturesCols,
    HasPredictionCol,
    HasSeed,
    HasTol,
    HasMaxIter,
    HasWeightCol,
    Param,
    TypeConverters,
    _TpuParams,
)
from ..utils import _ArrayBatch, get_logger


class KMeansClass:
    """Param mapping (reference KMeansClass clustering.py:84-137)."""

    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {
            "distanceMeasure": None,  # only euclidean on TPU (as in cuML)
            "initMode": "init",
            "k": "n_clusters",
            "initSteps": "init_steps",
            "maxIter": "max_iter",
            "seed": "random_state",
            "tol": "tol",
            # improvement over the reference (maps weightCol -> None): the
            # TPU kernel supports sample weights natively
            "weightCol": "",
            "solver": "",
            "maxBlockSizeInMB": "",
        }

    @classmethod
    def _param_value_mapping(cls):
        def tol_mapper(x: float) -> float:
            if x == 0.0:
                get_logger(cls).warning(
                    "tol=0 mapped to the smallest positive float32 "
                    "(reference clustering.py:108-120)."
                )
                return float(np.finfo("float32").tiny)
            return x

        def init_mapper(x: str):
            return {
                "k-means||": "scalable-k-means++",
                "scalable-k-means++": "scalable-k-means++",
                "k-means++": "k-means++",
                "random": "random",
            }.get(x)

        return {"tol": tol_mapper, "initMode": init_mapper}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "n_clusters": 8,
            "max_iter": 300,
            "tol": 0.0001,
            "verbose": False,
            "random_state": None,
            "init": "scalable-k-means++",
            "n_init": "auto",
            "init_steps": 2,
            "oversampling_factor": 2.0,
            "max_samples_per_batch": 32768,
        }


class _KMeansTpuParams(
    _TpuParams,
    HasFeaturesCol,
    HasFeaturesCols,
    HasPredictionCol,
    HasSeed,
    HasTol,
    HasMaxIter,
    HasWeightCol,
):
    """Shared params for KMeans / KMeansModel (reference _KMeansCumlParams
    clustering.py:140-183)."""

    k = Param("_", "k", "The number of clusters to create.", TypeConverters.toInt)
    initMode = Param(
        "_", "initMode", 'The initialization algorithm: "k-means||" or "random".',
        TypeConverters.toString,
    )
    initSteps = Param("_", "initSteps", "The number of steps for k-means|| init.",
                      TypeConverters.toInt)
    distanceMeasure = Param("_", "distanceMeasure", "The distance measure.",
                            TypeConverters.toString)

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(
            k=2, initMode="k-means||", initSteps=2, maxIter=20, tol=1e-4
        )

    def setFeaturesCol(self, value):
        if isinstance(value, str):
            self._set_params(featuresCol=value)
        else:
            self._set_params(featuresCols=value)
        return self

    def setFeaturesCols(self, value: List[str]):
        return self._set_params(featuresCols=value)

    def setPredictionCol(self, value: str):
        self._set(predictionCol=value)
        return self

    def setK(self, value: int):
        return self._set_params(k=value)

    def getK(self) -> int:
        return self.getOrDefault("k")

    def setInitMode(self, value: str):
        return self._set_params(initMode=value)

    def setMaxIter(self, value: int):
        return self._set_params(maxIter=value)

    def setTol(self, value: float):
        return self._set_params(tol=value)

    def setWeightCol(self, value: str):
        return self._set_params(weightCol=value)


class KMeans(KMeansClass, _TpuEstimator, _KMeansTpuParams):
    """Distributed KMeans on TPU (API parity: reference KMeans
    clustering.py:185-498).

    Seeding runs on-device (Gumbel-max k-means++, the quality analog of
    cuML's scalable-k-means++); Lloyd iterations are one compiled
    while_loop whose centroid partial sums psum over the mesh.

    Examples
    --------
    >>> import pandas as pd
    >>> from spark_rapids_ml_tpu.clustering import KMeans
    >>> df = pd.DataFrame({"features": [[0.0, 0.0], [1.0, 1.0], [9.0, 8.0], [8.0, 9.0]]})
    >>> model = KMeans(k=2, seed=1).setFeaturesCol("features").fit(df)
    >>> sorted(model.transform(df)["prediction"].tolist())
    [0, 0, 1, 1]
    """

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._set_params(**kwargs)

    def _supports_streaming_stats(self) -> bool:
        # beyond-HBM epoch-streaming Lloyd (streaming.py
        # `kmeans_streaming_fit`): no sufficient statistics exist, so every
        # iteration re-streams the parquet chunks
        return True

    def _fit_streaming(self, path: str) -> Dict[str, Any]:
        """Beyond-HBM fit: centers seeded from a strided subsample, each
        Lloyd iteration a streamed assign+accumulate pass — dataset size
        bounded by disk, not HBM x chips (the TPU analog of the
        reference's cluster-memory-scaled ingest, utils.py:403-522)."""
        from ..streaming import kmeans_streaming_fit

        fcol, fcols, _, weight_col, dtype = self._streaming_io_params()
        from ..resilience.checkpoint import resolve_checkpoint_dir

        p = self._tpu_params
        seed = p.get("random_state")
        seed = int(seed) if seed is not None else int(self.getOrDefault("seed"))
        ckpt_dir = resolve_checkpoint_dir(streaming=True)
        res = kmeans_streaming_fit(
            path, fcol, fcols, weight_col,
            k=int(p["n_clusters"]),
            seed=seed,
            max_iter=int(p["max_iter"]),
            tol=float(p["tol"]),
            init=str(p["init"]),
            init_steps=int(p.get("init_steps") or 2),
            oversample=float(p.get("oversampling_factor") or 2.0),
            dtype=dtype,
            checkpoint_dir=ckpt_dir or None,
        )
        dtype = np.dtype(dtype)
        return {
            "cluster_centers_": np.asarray(res["centers"]).astype(dtype),
            "inertia_": float(res["cost"]),
            "n_iter_": int(res["n_iter"]),
            "n_cols": int(res["d"]),
            "dtype": str(dtype.name),
        }

    def _fit_array(self, fit_input: FitInput) -> Dict[str, Any]:
        from ..ops.kmeans import kmeans_fit_auto

        p = fit_input.params
        k = int(p["n_clusters"])
        seed = p.get("random_state")
        seed = int(seed) if seed is not None else int(self.getOrDefault("seed"))
        max_iter = int(p["max_iter"])
        # fused single-program Lloyd until the whole solve (init
        # included) could exceed the per-program device-time budget
        # (45 s dispatch rule); then host-dispatched per-block
        # iterations.  The gate itself lives in ops/kmeans.py
        # kmeans_fit_auto, shared with the IVF quantizer training.
        # `checkpoint_dir` set -> the stepwise (checkpointable) solver
        # runs regardless of size and the fit resumes after a crash.
        from ..resilience.checkpoint import (
            checkpoint_file_for,
            resolve_checkpoint_dir,
        )

        ckpt_dir = resolve_checkpoint_dir()
        ckpt_path = None
        ckpt_tag = ""
        if ckpt_dir:
            from ..core import _fit_fingerprint

            # the tag binds n_valid, never the PADDED shape: padding is a
            # function of the device count, and an elastic resume on a
            # shrunken mesh (resilience/elastic.py) must derive the SAME
            # tag from its re-staged input to find the checkpoint
            ckpt_tag = (
                f"kmeans-mem|n={int(fit_input.n_valid)}"
                f"|d={fit_input.pdesc.n}|k={k}|seed={seed}"
                f"|mi={max_iter}|tol={p['tol']}|{_fit_fingerprint(fit_input)}"
            )
            ckpt_path = checkpoint_file_for(ckpt_dir, ckpt_tag)
        centers, cost, n_iter, stepwise = kmeans_fit_auto(
            fit_input.X,
            fit_input.w,
            k=k,
            seed=seed,
            max_iter=max_iter,
            tol=float(p["tol"]),
            init=str(p["init"]),
            init_steps=int(p.get("init_steps") or 2),
            oversample=float(p.get("oversampling_factor") or 2.0),
            checkpoint_path=ckpt_path,
            checkpoint_tag=ckpt_tag,
        )
        if stepwise:
            self.logger.info("KMeans: stepwise host-dispatched Lloyd")
        return {
            "cluster_centers_": np.asarray(centers),
            "inertia_": float(cost),
            "n_iter_": int(n_iter),
            "n_cols": fit_input.pdesc.n,
            "dtype": str(np.dtype(fit_input.dtype).name),
        }

    def _create_model(self, attrs: Dict[str, Any]) -> "KMeansModel":
        return KMeansModel(**attrs)

    def _cpu_fit(self, batch: _ArrayBatch) -> "KMeansModel":
        from sklearn.cluster import KMeans as SkKMeans

        sk = SkKMeans(
            n_clusters=self.getOrDefault("k"),
            max_iter=self.getOrDefault("maxIter"),
            tol=self.getOrDefault("tol"),
            random_state=self.getOrDefault("seed") & 0x7FFFFFFF,
            n_init=1,
        ).fit(batch.X, sample_weight=batch.weight)
        return KMeansModel(
            cluster_centers_=sk.cluster_centers_.astype(batch.X.dtype),
            inertia_=float(sk.inertia_),
            n_iter_=int(sk.n_iter_),
            n_cols=int(batch.X.shape[1]),
            dtype=str(batch.X.dtype),
        )


class KMeansSummary:
    """pyspark KMeansSummary analog: the training-cost surface."""

    def __init__(self, trainingCost: float, k: int, numIter: int) -> None:
        self.trainingCost = float(trainingCost)
        self.k = int(k)
        self.numIter = int(numIter)


class KMeansModel(KMeansClass, _TpuModel, _KMeansTpuParams):
    """KMeans model (reference KMeansModel clustering.py:501-600)."""

    def __init__(self, **attrs: Any) -> None:
        super().__init__(**attrs)
        self.cluster_centers_: np.ndarray = np.asarray(attrs["cluster_centers_"])
        self.inertia_: float = float(attrs.get("inertia_", 0.0))
        self.n_iter_: int = int(attrs.get("n_iter_", 0))
        self.n_cols: int = int(attrs["n_cols"])
        self.dtype: str = str(attrs.get("dtype", "float32"))
        self._set_params(k=int(self.cluster_centers_.shape[0]))

    def clusterCenters(self) -> List[np.ndarray]:
        """pyspark.ml parity: list of center vectors."""
        return list(self.cluster_centers_)

    @property
    def hasSummary(self) -> bool:
        return True

    @property
    def summary(self) -> "KMeansSummary":
        """pyspark parity: KMeansModel.summary.trainingCost (the weighted
        training inertia Spark's summary reports) + iteration count."""
        return KMeansSummary(
            trainingCost=self.inertia_,
            k=int(self.cluster_centers_.shape[0]),
            numIter=self.n_iter_,
        )

    def predict(self, value) -> int:
        """Nearest-center id for ONE sample (pyspark KMeansModel.predict;
        the reference falls back to the pyspark CPU model,
        clustering.py:551 — the centers are host-resident, so compute
        directly)."""
        v = np.asarray(value, np.float64).reshape(-1)
        C = self.cluster_centers_.astype(np.float64)
        if v.shape[0] != C.shape[1]:
            raise ValueError(
                f"feature vector has {v.shape[0]} entries; model expects "
                f"{C.shape[1]}"
            )
        return int(np.argmin(((C - v) ** 2).sum(axis=1)))

    def _transform_device(self, Xs) -> Dict[str, Any]:
        import jax.numpy as jnp

        from ..ops.kmeans import kmeans_predict

        return {
            self.getOrDefault("predictionCol"): kmeans_predict(
                Xs, jnp.asarray(self.cluster_centers_.astype(Xs.dtype))
            )
        }

    def cpu(self):
        from sklearn.cluster import KMeans as SkKMeans

        sk = SkKMeans(n_clusters=self.cluster_centers_.shape[0], n_init=1)
        sk.cluster_centers_ = self.cluster_centers_.astype(np.float64)
        sk.inertia_ = self.inertia_
        sk.n_iter_ = self.n_iter_
        sk._n_threads = 1
        sk.n_features_in_ = self.n_cols
        return sk


# ---------------------------------------------------------------------------
# DBSCAN (reference clustering.py:729-1182)
# ---------------------------------------------------------------------------


class DBSCANClass:
    """Param surface (reference DBSCANClass clustering.py:603-632: cuML-native
    names — Spark MLlib has no DBSCAN, so there is no Spark param mapping)."""

    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        # identity mapping: the API params ARE the backend params
        return {"eps": "eps", "min_samples": "min_samples", "metric": "metric"}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "eps": 0.5,
            "min_samples": 5,
            "metric": "euclidean",
            "max_mbytes_per_batch": None,
            "verbose": False,
            "calc_core_sample_indices": False,
        }


class _DBSCANTpuParams(
    _TpuParams, HasFeaturesCol, HasFeaturesCols, HasPredictionCol
):
    eps = Param("_", "eps",
                "The maximum distance between two samples for one to be "
                "considered in the neighborhood of the other.",
                TypeConverters.toFloat)
    min_samples = Param("_", "min_samples",
                        "The number of samples in a neighborhood (including "
                        "the point itself) for a point to be a core point.",
                        TypeConverters.toInt)
    metric = Param("_", "metric", "Distance metric: euclidean or cosine.",
                   TypeConverters.toString)

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(eps=0.5, min_samples=5, metric="euclidean")

    def setFeaturesCol(self, value):
        if isinstance(value, str):
            self._set_params(featuresCol=value)
        else:
            self._set_params(featuresCols=value)
        return self

    def setFeaturesCols(self, value: List[str]):
        return self._set_params(featuresCols=value)

    def setPredictionCol(self, value: str):
        self._set(predictionCol=value)
        return self

    def setEps(self, value: float):
        return self._set_params(eps=value)

    def getEps(self) -> float:
        return self.getOrDefault("eps")

    def setMinSamples(self, value: int):
        return self._set_params(min_samples=value)

    def getMinSamples(self) -> int:
        return self.getOrDefault("min_samples")

    def setMetric(self, value: str):
        return self._set_params(metric=value)

    def getMetric(self) -> str:
        return self.getOrDefault("metric")


class DBSCAN(DBSCANClass, _TpuEstimator, _DBSCANTpuParams):
    """Distributed DBSCAN on TPU (API parity: reference DBSCAN
    clustering.py:729-931).

    `fit` is deferred exactly like the reference (clustering.py:900-914
    returns a param-copied model): clustering is density-based, so there is
    no model to train — the work happens in `DBSCANModel.transform`, which
    labels the given dataset.  The reference broadcasts the whole dataset
    to every rank (clustering.py:1104-1155); here the dataset is replicated
    per device and responsibility for rows is sharded, with cluster
    expansion as min-label connected components (ops/dbscan.py).

    Examples
    --------
    >>> import pandas as pd
    >>> from spark_rapids_ml_tpu.clustering import DBSCAN
    >>> df = pd.DataFrame({"features": [[0.0], [0.1], [0.2], [9.0], [9.1], [50.0]]})
    >>> model = DBSCAN(eps=0.5, min_samples=2).setFeaturesCol("features").fit(df)
    >>> model.transform(df)["prediction"].tolist()
    [0, 0, 0, 1, 1, -1]
    """

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._set_params(**kwargs)

    def _fit(self, dataset) -> "DBSCANModel":
        if str(self._tpu_params.get("metric", "euclidean")) not in (
            "euclidean", "cosine"
        ):
            raise ValueError("DBSCAN metric must be euclidean or cosine")
        model = DBSCANModel(
            n_cols=0, dtype="float32"
        )  # deferred: no attributes until transform
        self._copyValues(model)
        model._tpu_params = dict(self._tpu_params)
        model._num_workers = self._num_workers
        model._float32_inputs = self._float32_inputs
        return model

    def _fit_array(self, fit_input: FitInput) -> Dict[str, Any]:  # pragma: no cover
        raise NotImplementedError("DBSCAN fit is deferred to transform")

    def _create_model(self, attrs: Dict[str, Any]) -> "DBSCANModel":  # pragma: no cover
        return DBSCANModel(**attrs)


class DBSCANModel(DBSCANClass, _TpuModel, _DBSCANTpuParams):
    """Deferred-fit DBSCAN model (reference DBSCANModel clustering.py:933-1182):
    `transform` runs the distributed fit_predict on the given dataset and
    appends the cluster label column (-1 = noise, clusters renumbered to
    consecutive ids by first occurrence, matching sklearn)."""

    def __init__(self, **attrs: Any) -> None:
        super().__init__(**attrs)
        self.n_cols = int(attrs.get("n_cols", 0))
        self.dtype = str(attrs.get("dtype", "float32"))

    def _transform_array(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        import jax
        import jax.numpy as jnp

        from ..ops.dbscan import dbscan_fit_predict
        from ..parallel import TpuContext

        eps = float(self._tpu_params["eps"])
        if str(self._tpu_params.get("metric", "euclidean")) == "cosine":
            # cosine_dist <= eps on unit vectors  <=>  ||u-v|| <= sqrt(2 eps)
            # (||u-v||^2 = 2 (1 - cos) = 2 cosine_dist)
            norms = np.linalg.norm(X, axis=1, keepdims=True)
            X = X / np.maximum(norms, 1e-12)
            eps = float(np.sqrt(2.0 * eps))
        with TpuContext(self.num_workers, require_p2p=True) as ctx:
            mesh = ctx.mesh
        dtype = self._out_dtype(X)
        from ..parallel.mesh import RowStager

        st = RowStager.for_replicated(X.shape[0], mesh)
        Xs = st.stage(X, dtype)
        valid = st.mask(dtype)
        kernel_kwargs: Dict[str, Any] = {}
        mb = self._tpu_params.get("max_mbytes_per_batch")
        if mb:
            # cuML's max_mbytes_per_batch (reference clustering.py:603-632):
            # a BYTE cap on the per-device distance working set — the
            # kernel bounds its per-sweep (m_local, block) f32 distance
            # tile to fit it (ops/dbscan.py dbscan_fit_predict).
            kernel_kwargs["adj_budget"] = max(int(float(mb) * 1024 * 1024), 1)
        labels, _core = dbscan_fit_predict(
            Xs, valid,
            jnp.asarray(eps, dtype),
            jnp.asarray(int(self._tpu_params["min_samples"]), jnp.int32),
            mesh=mesh,
            **kernel_kwargs,
        )
        labels = st.fetch(labels)
        # renumber representatives to consecutive ids by first occurrence,
        # vectorized (a Python loop here costs seconds at benchmark scale)
        out = np.full(labels.shape, -1, np.int64)
        clustered = labels >= 0
        if clustered.any():
            uniq, first_pos, inverse = np.unique(
                labels[clustered], return_index=True, return_inverse=True
            )
            # rank unique reps by first occurrence in the row order
            order = np.argsort(first_pos, kind="stable")
            rank = np.empty_like(order)
            rank[order] = np.arange(order.size)
            out[clustered] = rank[inverse]
        return {self.getOrDefault("predictionCol"): out}

    def cpu(self):
        from sklearn.cluster import DBSCAN as SkDBSCAN

        return SkDBSCAN(
            eps=float(self._tpu_params["eps"]),
            min_samples=int(self._tpu_params["min_samples"]),
            metric=str(self._tpu_params.get("metric", "euclidean")),
        )
