#
# Clustering: KMeans (+ DBSCAN below) — the analog of reference
# clustering.py (1182 LoC).  The cuML KMeansMG distributed fit
# (clustering.py:377-411) is replaced by ops/kmeans.py: Gumbel-max
# k-means++ seeding + a single compiled Lloyd while_loop with psum'd
# centroid updates.  The reference's >1GB model-chunking machinery
# (clustering.py:433-498) has no analog: there is no Spark row-size limit
# in this runtime, model arrays go straight to the host.
#
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core import FitInput, _TpuEstimator, _TpuModel
from ..params import (
    HasFeaturesCol,
    HasFeaturesCols,
    HasPredictionCol,
    HasSeed,
    HasTol,
    HasMaxIter,
    HasWeightCol,
    Param,
    TypeConverters,
    _TpuParams,
)
from ..utils import _ArrayBatch, get_logger


class KMeansClass:
    """Param mapping (reference KMeansClass clustering.py:84-137)."""

    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {
            "distanceMeasure": None,  # only euclidean on TPU (as in cuML)
            "initMode": "init",
            "k": "n_clusters",
            "initSteps": "",
            "maxIter": "max_iter",
            "seed": "random_state",
            "tol": "tol",
            # improvement over the reference (maps weightCol -> None): the
            # TPU kernel supports sample weights natively
            "weightCol": "",
            "solver": "",
            "maxBlockSizeInMB": "",
        }

    @classmethod
    def _param_value_mapping(cls):
        def tol_mapper(x: float) -> float:
            if x == 0.0:
                get_logger(cls).warning(
                    "tol=0 mapped to the smallest positive float32 "
                    "(reference clustering.py:108-120)."
                )
                return float(np.finfo("float32").tiny)
            return x

        def init_mapper(x: str):
            return {
                "k-means||": "k-means++",
                "scalable-k-means++": "k-means++",
                "k-means++": "k-means++",
                "random": "random",
            }.get(x)

        return {"tol": tol_mapper, "initMode": init_mapper}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "n_clusters": 8,
            "max_iter": 300,
            "tol": 0.0001,
            "verbose": False,
            "random_state": None,
            "init": "k-means++",
            "n_init": "auto",
            "oversampling_factor": 2.0,
            "max_samples_per_batch": 32768,
        }


class _KMeansTpuParams(
    _TpuParams,
    HasFeaturesCol,
    HasFeaturesCols,
    HasPredictionCol,
    HasSeed,
    HasTol,
    HasMaxIter,
    HasWeightCol,
):
    """Shared params for KMeans / KMeansModel (reference _KMeansCumlParams
    clustering.py:140-183)."""

    k = Param("_", "k", "The number of clusters to create.", TypeConverters.toInt)
    initMode = Param(
        "_", "initMode", 'The initialization algorithm: "k-means||" or "random".',
        TypeConverters.toString,
    )
    initSteps = Param("_", "initSteps", "The number of steps for k-means|| init.",
                      TypeConverters.toInt)
    distanceMeasure = Param("_", "distanceMeasure", "The distance measure.",
                            TypeConverters.toString)

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(
            k=2, initMode="k-means||", initSteps=2, maxIter=20, tol=1e-4
        )

    def setFeaturesCol(self, value):
        if isinstance(value, str):
            self._set_params(featuresCol=value)
        else:
            self._set_params(featuresCols=value)
        return self

    def setFeaturesCols(self, value: List[str]):
        return self._set_params(featuresCols=value)

    def setPredictionCol(self, value: str):
        self._set(predictionCol=value)
        return self

    def setK(self, value: int):
        return self._set_params(k=value)

    def getK(self) -> int:
        return self.getOrDefault("k")

    def setInitMode(self, value: str):
        return self._set_params(initMode=value)

    def setMaxIter(self, value: int):
        return self._set_params(maxIter=value)

    def setTol(self, value: float):
        return self._set_params(tol=value)

    def setWeightCol(self, value: str):
        return self._set_params(weightCol=value)


class KMeans(KMeansClass, _TpuEstimator, _KMeansTpuParams):
    """Distributed KMeans on TPU (API parity: reference KMeans
    clustering.py:185-498).

    Seeding runs on-device (Gumbel-max k-means++, the quality analog of
    cuML's scalable-k-means++); Lloyd iterations are one compiled
    while_loop whose centroid partial sums psum over the mesh.

    Examples
    --------
    >>> import pandas as pd
    >>> from spark_rapids_ml_tpu.clustering import KMeans
    >>> df = pd.DataFrame({"features": [[0.0, 0.0], [1.0, 1.0], [9.0, 8.0], [8.0, 9.0]]})
    >>> model = KMeans(k=2, seed=1).setFeaturesCol("features").fit(df)
    >>> sorted(model.transform(df)["prediction"].tolist())
    [0, 0, 1, 1]
    """

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._set_params(**kwargs)

    def _fit_array(self, fit_input: FitInput) -> Dict[str, Any]:
        from ..ops.kmeans import kmeans_fit

        p = fit_input.params
        k = int(p["n_clusters"])
        seed = p.get("random_state")
        seed = int(seed) if seed is not None else int(self.getOrDefault("seed"))
        centers, cost, n_iter = kmeans_fit(
            fit_input.X,
            fit_input.w,
            k=k,
            seed=seed,
            max_iter=int(p["max_iter"]),
            tol=float(p["tol"]),
            init=str(p["init"]),
        )
        return {
            "cluster_centers_": np.asarray(centers),
            "inertia_": float(cost),
            "n_iter_": int(n_iter),
            "n_cols": fit_input.pdesc.n,
            "dtype": str(np.dtype(fit_input.dtype).name),
        }

    def _create_model(self, attrs: Dict[str, Any]) -> "KMeansModel":
        return KMeansModel(**attrs)

    def _cpu_fit(self, batch: _ArrayBatch) -> "KMeansModel":
        from sklearn.cluster import KMeans as SkKMeans

        sk = SkKMeans(
            n_clusters=self.getOrDefault("k"),
            max_iter=self.getOrDefault("maxIter"),
            tol=self.getOrDefault("tol"),
            random_state=self.getOrDefault("seed") & 0x7FFFFFFF,
            n_init=1,
        ).fit(batch.X, sample_weight=batch.weight)
        return KMeansModel(
            cluster_centers_=sk.cluster_centers_.astype(batch.X.dtype),
            inertia_=float(sk.inertia_),
            n_iter_=int(sk.n_iter_),
            n_cols=int(batch.X.shape[1]),
            dtype=str(batch.X.dtype),
        )


class KMeansModel(KMeansClass, _TpuModel, _KMeansTpuParams):
    """KMeans model (reference KMeansModel clustering.py:501-600)."""

    def __init__(self, **attrs: Any) -> None:
        super().__init__(**attrs)
        self.cluster_centers_: np.ndarray = np.asarray(attrs["cluster_centers_"])
        self.inertia_: float = float(attrs.get("inertia_", 0.0))
        self.n_iter_: int = int(attrs.get("n_iter_", 0))
        self.n_cols: int = int(attrs["n_cols"])
        self.dtype: str = str(attrs.get("dtype", "float32"))
        self._set_params(k=int(self.cluster_centers_.shape[0]))

    def clusterCenters(self) -> List[np.ndarray]:
        """pyspark.ml parity: list of center vectors."""
        return list(self.cluster_centers_)

    @property
    def hasSummary(self) -> bool:
        return False

    def _transform_array(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        import jax.numpy as jnp

        from ..ops.kmeans import kmeans_predict

        preds = np.asarray(
            kmeans_predict(jnp.asarray(X), jnp.asarray(self.cluster_centers_.astype(X.dtype)))
        )
        return {self.getOrDefault("predictionCol"): preds}

    def cpu(self):
        from sklearn.cluster import KMeans as SkKMeans

        sk = SkKMeans(n_clusters=self.cluster_centers_.shape[0], n_init=1)
        sk.cluster_centers_ = self.cluster_centers_.astype(np.float64)
        sk.inertia_ = self.inertia_
        sk.n_iter_ = self.n_iter_
        sk._n_threads = 1
        sk.n_features_in_ = self.n_cols
        return sk
