#
# Classification: LogisticRegression + RandomForestClassifier — the
# analog of reference classification.py (1615 LoC).  The cuML
# `LogisticRegressionMG` L-BFGS/OWL-QN distributed solver
# (classification.py:1046-1081) is replaced by ops/logistic.py +
# ops/lbfgs.py: a fully-jitted L-BFGS whose gradient psums over the mesh.
#
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Union

import numpy as np

from ..core import FitInput, _TpuEstimatorSupervised, _TpuModel
from ..params import (
    HasElasticNetParam,
    HasEnableSparseDataOptim,
    HasFeaturesCol,
    HasFeaturesCols,
    HasFitIntercept,
    HasLabelCol,
    HasMaxIter,
    HasPredictionCol,
    HasProbabilityCol,
    HasRawPredictionCol,
    HasRegParam,
    HasStandardization,
    HasTol,
    HasWeightCol,
    Param,
    TypeConverters,
    _TpuParams,
)
from ..utils import _ArrayBatch


def _label_range_kernel(y, w):
    import jax.numpy as jnp

    valid = w > 0
    big = jnp.iinfo(jnp.int32).max
    return (
        jnp.where(valid, y, big).min(),
        jnp.where(valid, y, -1).max(),
    )


def _label_check_kernel(y, w):
    """(is_integral, min_label) among valid rows, for float label arrays."""
    import jax.numpy as jnp

    valid = w > 0
    yf = y.astype(jnp.float32)
    integral = jnp.all(jnp.where(valid, yf == jnp.round(yf), True))
    mn = jnp.where(valid, yf, jnp.inf).min()
    return integral, mn


def _label_range(y, w):
    """(min, max) label among valid (w>0) rows, computed on device."""
    import jax

    global _label_range_jit
    if _label_range_jit is None:
        _label_range_jit = jax.jit(_label_range_kernel)
    # one host round-trip for both scalars (device_get batches the fetch;
    # separate int() casts would each block on the tunnel)
    return jax.device_get(_label_range_jit(y, w))


_label_range_jit = None
_label_check_jit = None


class LogisticRegressionClass:
    """Param mapping (reference LogisticRegressionClass
    classification.py:679-747, incl. the regParam -> C inversion
    classification.py:701-705)."""

    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {
            "maxIter": "max_iter",
            "regParam": "C",
            "elasticNetParam": "l1_ratio",
            "tol": "tol",
            "fitIntercept": "fit_intercept",
            # improvements over the reference (-> None there): the TPU
            # predict path honors threshold; the kernel takes sample weights
            "threshold": "",
            "thresholds": None,
            "standardization": "standardization",
            "weightCol": "",
            "aggregationDepth": "",
            "family": "family",
            "lowerBoundsOnCoefficients": None,
            "upperBoundsOnCoefficients": None,
            "lowerBoundsOnIntercepts": None,
            "upperBoundsOnIntercepts": None,
            "maxBlockSizeInMB": "",
        }

    @classmethod
    def _param_value_mapping(cls):
        # Spark regParam -> sklearn/cuml-style inverse C (reference
        # classification.py:701-705): C = 1/regParam, 0 means unregularized.
        # NOTE: value maps here are keyed by the SPARK param name.
        return {"regParam": lambda x: 1.0 / x if x > 0.0 else (0.0 if x == 0.0 else None)}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "fit_intercept": True,
            "standardization": False,
            "verbose": False,
            "C": 1.0,
            "penalty": "l2",
            "l1_ratio": None,
            "max_iter": 1000,
            "tol": 0.0001,
            "family": "auto",
            "lbfgs_memory": 10,
            "linesearch_max_iter": 20,
        }


class _LogisticRegressionTpuParams(
    _TpuParams,
    HasFeaturesCol,
    HasFeaturesCols,
    HasLabelCol,
    HasPredictionCol,
    HasProbabilityCol,
    HasRawPredictionCol,
    HasEnableSparseDataOptim,
    HasRegParam,
    HasElasticNetParam,
    HasFitIntercept,
    HasStandardization,
    HasMaxIter,
    HasTol,
    HasWeightCol,
):
    """Shared params (reference _LogisticRegressionCumlParams
    classification.py:750-820)."""

    family = Param("_", "family", 'Label distribution: "auto", "binomial", '
                   '"multinomial".', TypeConverters.toString)
    threshold = Param("_", "threshold", "binary prediction threshold in [0,1].",
                      TypeConverters.toFloat)
    # declared for pyspark API parity; mapped to None (unsupported on TPU)
    thresholds = Param("_", "thresholds", "per-class thresholds (unsupported).",
                       TypeConverters.toListFloat)
    lowerBoundsOnCoefficients = Param("_", "lowerBoundsOnCoefficients",
                                      "box constraint (unsupported).",
                                      TypeConverters.identity)
    upperBoundsOnCoefficients = Param("_", "upperBoundsOnCoefficients",
                                      "box constraint (unsupported).",
                                      TypeConverters.identity)
    lowerBoundsOnIntercepts = Param("_", "lowerBoundsOnIntercepts",
                                    "box constraint (unsupported).",
                                    TypeConverters.identity)
    upperBoundsOnIntercepts = Param("_", "upperBoundsOnIntercepts",
                                    "box constraint (unsupported).",
                                    TypeConverters.identity)

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(
            regParam=0.0,
            elasticNetParam=0.0,
            tol=1e-6,
            maxIter=100,
            fitIntercept=True,
            standardization=True,
            family="auto",
            threshold=0.5,
        )

    def setFeaturesCol(self, value: Union[str, List[str]]):
        if isinstance(value, str):
            self._set_params(featuresCol=value)
        else:
            self._set_params(featuresCols=value)
        return self

    def setFeaturesCols(self, value: List[str]):
        return self._set_params(featuresCols=value)

    def setLabelCol(self, value: str):
        self._set(labelCol=value)
        return self

    def setPredictionCol(self, value: str):
        self._set(predictionCol=value)
        return self

    def setProbabilityCol(self, value: str):
        self._set(probabilityCol=value)
        return self

    def setRawPredictionCol(self, value: str):
        self._set(rawPredictionCol=value)
        return self

    def setRegParam(self, value: float):
        return self._set_params(regParam=value)

    def setElasticNetParam(self, value: float):
        return self._set_params(elasticNetParam=value)

    def setFitIntercept(self, value: bool):
        return self._set_params(fitIntercept=value)

    def setStandardization(self, value: bool):
        return self._set_params(standardization=value)

    def setMaxIter(self, value: int):
        return self._set_params(maxIter=value)

    def setTol(self, value: float):
        return self._set_params(tol=value)

    def setWeightCol(self, value: str):
        return self._set_params(weightCol=value)

    def setThreshold(self, value: float):
        return self._set_params(threshold=value)

    def setFamily(self, value: str):
        return self._set_params(family=value)


class LogisticRegression(
    LogisticRegressionClass, _TpuEstimatorSupervised, _LogisticRegressionTpuParams
):
    """Distributed logistic regression on TPU (API parity: reference
    LogisticRegression classification.py:822-1304).

    Binomial labels use Spark's single-coefficient-vector parameterization;
    multinomial uses softmax with the full coefficient matrix.  Both run the
    jitted L-BFGS (OWL-QN when elasticNetParam > 0) of ops/lbfgs.py with
    `lbfgs_memory=10`, `linesearch_max_iter=20` (cuML's settings, reference
    classification.py:1046-1052).  Standardization is applied on-device and
    coefficients are un-scaled after the solve (reference
    classification.py:1018-1028).

    Examples
    --------
    >>> import pandas as pd
    >>> from spark_rapids_ml_tpu.classification import LogisticRegression
    >>> df = pd.DataFrame({"features": [[1.0, 2.0], [1.0, 3.0], [2.0, 1.0], [3.0, 1.0]],
    ...                    "label": [1.0, 1.0, 0.0, 0.0]})
    >>> model = LogisticRegression(regParam=0.01).setFeaturesCol("features").fit(df)
    >>> model.transform(df)["prediction"].tolist()
    [1, 1, 0, 0]
    """

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._set_params(**kwargs)

    def _fit_label_dtype(self):
        return np.dtype(np.int32)

    def _use_sparse_kernel(self, batch: _ArrayBatch) -> bool:
        # None (auto) -> sparse inputs stay sparse; True forces the sparse
        # kernel even for dense inputs; False forces densify (reference
        # _use_sparse_in_cuml, core.py:183-216)
        opt = self.getOrDefault("enable_sparse_data_optim")
        if opt is True:
            return True
        if opt is False:
            return False
        from ..data import _is_sparse

        return _is_sparse(batch.X)

    def _validate_input(self, batch: _ArrayBatch) -> None:
        classes = np.unique(batch.y)
        if not np.all(classes == classes.astype(np.int64)):
            raise RuntimeError(f"Labels MUST be Integers, but got {classes}")
        if classes.min() < 0:
            raise RuntimeError(f"Labels MUST be non-negative, but got {classes}")

    def _validate_device_input(self, ds) -> None:
        """Same label contract as `_validate_input`, evaluated on device for
        DeviceDataset fits (before the int32 cast would mask violations)."""
        import jax

        global _label_check_jit
        if _label_check_jit is None:
            _label_check_jit = jax.jit(_label_check_kernel)
        integral, mn = jax.device_get(_label_check_jit(ds.y, ds.weight))
        if not bool(integral):
            raise RuntimeError("Labels MUST be Integers")
        if float(mn) < 0:
            raise RuntimeError(f"Labels MUST be non-negative, but got min {mn}")

    def _supports_streaming_stats(self) -> bool:
        # beyond-HBM epoch-streaming L-BFGS (streaming.py
        # `logreg_streaming_fit`): every solver evaluation re-streams the
        # parquet chunks through a donated loss+gradient accumulator
        return True

    def _supports_fold_weights(self) -> bool:
        # convex w-weighted objective, deterministic zero init
        # (ops/logistic.py SUPPORTS_ZERO_WEIGHT_ROWS): a CV fold mask is
        # exactly a zero weight and the optimum is row-count free
        from ..ops import logistic as _logistic_ops

        return bool(_logistic_ops.SUPPORTS_ZERO_WEIGHT_ROWS)

    def _fit_streaming(self, path: str) -> Dict[str, Any]:
        """Beyond-HBM fit: host-driven L-BFGS/OWL-QN whose oracle streams
        the dataset per evaluation — the reachability answer to the 1B-row
        BASELINE workload (dataset bounded by disk, not HBM x chips; the
        analog of the reference's reserved-memory ingest scaling,
        utils.py:403-522 + classification.py:1046-1081)."""
        from ..streaming import logreg_streaming_fit

        fcol, fcols, label_col, weight_col, dtype = self._streaming_io_params()
        if label_col is None:
            raise ValueError("labelCol must be set for LogisticRegression")
        p = self._tpu_params
        C = float(p["C"])
        reg_param = 1.0 / C if C > 0 else 0.0
        l1_ratio = p.get("l1_ratio")
        en = float(l1_ratio) if l1_ratio is not None else float(
            self.getOrDefault("elasticNetParam")
        )
        fit_intercept = bool(p["fit_intercept"])
        from ..resilience.checkpoint import resolve_checkpoint_dir

        ckpt_dir = resolve_checkpoint_dir(streaming=True)
        res = logreg_streaming_fit(
            path, fcol, fcols, label_col, weight_col,
            family=str(self.getOrDefault("family")),
            l2=reg_param * (1.0 - en),
            l1=reg_param * en,
            fit_intercept=fit_intercept,
            standardization=bool(p.get("standardization", True)),
            tol=float(p["tol"]),
            max_iter=int(p["max_iter"]),
            history=int(p.get("lbfgs_memory", 10)),
            ls_max=int(p.get("linesearch_max_iter", 20)),
            dtype=dtype,
            # filename derives from the fit's content tag inside the
            # solver: stable across process restarts (a uid-based name
            # made a preempted-and-restarted fit miss its checkpoint)
            checkpoint_dir=ckpt_dir or None,
        )
        dtype = np.dtype(dtype)
        if "degenerate_label" in res:
            cv = float(res["degenerate_label"])
            if cv not in (0.0, 1.0):
                raise RuntimeError(
                    "class value must be either 1. or 0. when dataset has one label"
                )
            return {
                "coef_": np.zeros((1, res["d"]), dtype),
                "intercept_": np.array(
                    [np.inf if cv == 1.0 else -np.inf], dtype
                ),
                "classes_": [cv],
                "n_cols": res["d"],
                "dtype": str(dtype.name),
                "num_iters": 0,
                "objective": 0.0,
            }
        coef = np.asarray(res["coef"], np.float64)
        intercept = np.asarray(res["intercept"], np.float64)
        if res["std"] is not None:
            std = np.asarray(res["std"], np.float64)
            coef = np.where(std > 0, coef / std, coef)
            if fit_intercept and res["mean"] is not None:
                intercept = intercept - coef @ np.asarray(res["mean"], np.float64)
        if fit_intercept and len(intercept) > 1:
            intercept = intercept - intercept.mean()
        hist = [float(v) for v in res["history"]]
        return {
            "coef_": coef.astype(dtype),
            "intercept_": intercept.astype(dtype),
            "classes_": [float(c) for c in range(res["n_classes"])],
            "n_cols": int(res["d"]),
            "dtype": str(dtype.name),
            "num_iters": int(res["n_iter"]),
            "objective": float(hist[-1]) if hist else 0.0,
            "objective_history": hist,
            "converged": bool(res.get("converged", False)),
            # true dataset passes incl. line-search backtracks (bench.py
            # computes rows/sec/epoch from this)
            "streaming_epochs": int(res.get("epochs", 0)),
        }

    def _fit_array(self, fit_input: FitInput) -> Dict[str, Any]:
        import jax.numpy as jnp

        from ..ops.logistic import logreg_fit, logreg_fit_binary
        from ..ops.stats import standardize, weighted_moments

        p = fit_input.params
        dtype = np.dtype(fit_input.dtype)
        # label range via two on-device scalar reductions — pulling the full
        # y/w arrays to host would cross HBM->host for the whole dataset;
        # integrality was validated host-side pre-staging (_validate_input)
        y_min, y_max = _label_range(fit_input.y, fit_input.w)
        y_min, y_max = int(y_min), int(y_max)

        # degenerate single-label dataset (Spark semantics: +/-inf intercept,
        # reference classification.py:1106-1121)
        if y_min == y_max:
            cv = float(y_min)
            if cv not in (0.0, 1.0):
                raise RuntimeError(
                    "class value must be either 1. or 0. when dataset has one label"
                )
            return {
                "coef_": np.zeros((1, fit_input.pdesc.n), dtype),
                "intercept_": np.array([np.inf if cv == 1.0 else -np.inf], dtype),
                "classes_": [cv],
                "n_cols": fit_input.pdesc.n,
                "dtype": str(dtype.name),
                "num_iters": 0,
                "objective": 0.0,
            }

        # Spark numClasses = max(label)+1 (can include empty classes;
        # cuML instead uses unique - see reference TODO classification.py:1106)
        n_classes = y_max + 1
        family = str(self.getOrDefault("family"))
        binomial = n_classes == 2 and family in ("auto", "binomial")

        C = float(p["C"])
        reg_param = 1.0 / C if C > 0 else 0.0
        l1_ratio = p.get("l1_ratio")
        en = float(l1_ratio) if l1_ratio is not None else float(
            self.getOrDefault("elasticNetParam")
        )
        l2 = reg_param * (1.0 - en)
        l1 = reg_param * en
        fit_intercept = bool(p["fit_intercept"])
        standardization = bool(p.get("standardization", True))
        tol = float(p["tol"])
        max_iter = int(p["max_iter"])

        import jax

        w = fit_input.w
        sparse = "ell_cols" in fit_input.extra
        # estimator-wide checkpoint/resume: `checkpoint_dir` set -> the
        # host-dispatched (checkpointable) solver runs regardless of the
        # FLOP gate — the fused while_loop is one opaque device program
        # with no iteration boundary to persist at
        from ..resilience.checkpoint import (
            checkpoint_file_for,
            resolve_checkpoint_dir,
        )

        ckpt_dir = resolve_checkpoint_dir()
        ckpt_path = None
        ckpt_tag = ""
        if ckpt_dir:
            from ..core import _fit_fingerprint

            # m (lbfgs_memory) is shape-critical: the checkpointed S/Y
            # history buffers are (m, n), so a resume under a different m
            # must tag-mismatch and start fresh, not broadcast-fail.
            # n binds n_valid, never the padded shape: padding depends on
            # the device count, and an elastic resume on a shrunken mesh
            # must derive the same tag (resilience/elastic.py)
            ckpt_tag = (
                f"logreg-mem|n={int(fit_input.n_valid)}"
                f"|d={fit_input.pdesc.n}|C={n_classes}|l2={l2}|l1={l1}"
                f"|int={fit_intercept}|std={standardization}|mi={max_iter}"
                f"|m={int(p.get('lbfgs_memory', 10))}"
                f"|ls={int(p.get('linesearch_max_iter', 20))}"
                f"|{_fit_fingerprint(fit_input)}"
            )
            ckpt_path = checkpoint_file_for(ckpt_dir, ckpt_tag)
        kwargs = dict(
            l2=l2,
            l1=l1,
            fit_intercept=fit_intercept,
            tol=tol,
            max_iter=max_iter,
            history=int(p.get("lbfgs_memory", 10)),
            ls_max=int(p.get("linesearch_max_iter", 20)),
        )
        mean = std = None
        if sparse:
            # ELL sparse path (the analog of the reference's CSR
            # LogisticRegressionMG, classification.py:1054-1055).
            # Standardization is std-scaling only — no centering, which
            # preserves sparsity and (with an intercept) the same optimum.
            from ..ops.logistic import logreg_fit_binary_ell, logreg_fit_ell
            from ..ops.sparse import ell_scale_columns, ell_weighted_moments

            vals, cols = fit_input.X, fit_input.extra["ell_cols"]
            d = fit_input.pdesc.n
            if standardization:
                _, std = ell_weighted_moments(vals, cols, w, d=d)
                vals = ell_scale_columns(vals, cols, 1.0 / std)
            # same per-program budget gate as the dense branch: a
            # reference-scale sparse fit must not compile the whole solve
            # into one program either (45 s dispatch rule)
            from ..config import get_config

            C_eff = 1 if binomial else n_classes
            per_eval = 4.0 * vals.shape[0] * vals.shape[1] * C_eff
            budget = float(get_config("dispatch_flops_limit"))
            if per_eval * max_iter * 2.0 > budget or ckpt_path:
                from ..ops.logistic import logreg_fit_host_dispatch
                from ..ops.sparse import ell_matmat, ell_matvec

                self.logger.info(
                    "LogisticRegression: host-dispatched L-BFGS (sparse; "
                    f"{per_eval * max_iter * 2.0:.2e} fused FLOPs vs "
                    f"budget {budget:.0e}, checkpointing "
                    f"{'on' if ckpt_path else 'off'})"
                )
                coef, b, loss, n_iter, hist = logreg_fit_host_dispatch(
                    vals, w, fit_input.y, n_classes=n_classes,
                    binomial=binomial, d=d,
                    data=(vals, cols),
                    margin_fn=lambda dat, beta: ell_matvec(*dat, beta),
                    logits_fn=lambda dat, Wm: ell_matmat(*dat, Wm),
                    checkpoint_path=ckpt_path,
                    checkpoint_tag=ckpt_tag,
                    **kwargs,
                )
            elif binomial:
                coef, b, loss, n_iter, hist = logreg_fit_binary_ell(
                    vals, cols, w, fit_input.y, d=d, **kwargs
                )
            else:
                coef, b, loss, n_iter, hist = logreg_fit_ell(
                    vals, cols, w, fit_input.y, n_classes=n_classes, d=d,
                    **kwargs
                )
        else:
            X = fit_input.X
            if standardization:
                mean, std, _ = weighted_moments(X, w)
                if fit_intercept:
                    X = standardize(X, w, mean, std)
                else:
                    # no intercept to absorb a centering shift: scale only
                    # (Spark's aggregators never center; this keeps the
                    # optimum identical to the sparse path as well)
                    X = standardize(
                        X, w, jnp.zeros_like(mean), std
                    )
                    mean = None
            from ..config import get_config

            if get_config("bf16_features") and X.dtype == jnp.float32:
                # bandwidth lever: the L-BFGS margin/gradient matvecs are
                # HBM-bound; bf16 feature STORAGE halves the bytes per
                # iteration while the solver state and accumulation stay
                # f32 (the MXU consumes bf16 natively).  Opt-in: costs ~3
                # decimal digits of feature precision.
                X = X.astype(jnp.bfloat16)
            # fused single-program L-BFGS until the whole solve could
            # exceed the per-program device-time budget (45 s dispatch
            # rule; the reference 1M x 3000 maxIter=200 config crosses
            # it) — then host-driven L-BFGS, one evaluation per program
            C_eff = 1 if binomial else n_classes
            per_eval = 4.0 * X.shape[0] * X.shape[1] * C_eff
            fused_flops = per_eval * max_iter * 2.0  # ~2 evals/iter
            budget = float(get_config("dispatch_flops_limit"))
            if fused_flops > budget or ckpt_path:
                from ..ops.logistic import logreg_fit_host_dispatch

                self.logger.info(
                    f"LogisticRegression: host-dispatched L-BFGS "
                    f"({fused_flops:.2e} fused FLOPs vs budget "
                    f"{budget:.0e}, checkpointing "
                    f"{'on' if ckpt_path else 'off'})"
                )
                coef, b, loss, n_iter, hist = logreg_fit_host_dispatch(
                    X, w, fit_input.y, n_classes=n_classes,
                    binomial=binomial, checkpoint_path=ckpt_path,
                    checkpoint_tag=ckpt_tag, **kwargs
                )
            elif binomial:
                coef, b, loss, n_iter, hist = logreg_fit_binary(
                    X, w, fit_input.y, **kwargs
                )
            else:
                coef, b, loss, n_iter, hist = logreg_fit(
                    X, w, fit_input.y, n_classes=n_classes, **kwargs
                )
        # ONE batched device->host fetch for every output (each separate
        # np.asarray/float() would pay a full host sync)
        fetch = {"coef": coef, "b": b, "loss": loss, "n_iter": n_iter,
                 "hist": hist}
        if standardization:
            fetch["std"] = std
            if mean is not None:
                fetch["mean"] = mean
        host = jax.device_get(fetch)
        loss, n_iter = host["loss"], host["n_iter"]
        if binomial:
            coef = np.asarray(host["coef"], np.float64).reshape(1, -1)
            intercept = np.array([float(host["b"])])
        else:
            coef = np.asarray(host["coef"], np.float64)
            intercept = np.asarray(host["b"], np.float64)

        if standardization:
            std = np.asarray(host["std"], np.float64)
            coef = np.where(std > 0, coef / std, coef)
            if fit_intercept and "mean" in host:
                # dense path centers features; undo the shift (the sparse
                # path never centers, so its intercept is already correct)
                mean = np.asarray(host["mean"], np.float64)
                intercept = intercept - coef @ mean
        # Spark centers multinomial intercepts (softmax shift-invariance;
        # reference classification.py:1135-1147)
        if fit_intercept and len(intercept) > 1:
            intercept = intercept - intercept.mean()

        # Spark's LogisticRegressionTrainingSummary.objectiveHistory:
        # FULL (penalty-inclusive) objective per iteration, entry 0 =
        # initial.  Entries 0..n_iter are all written; strip only a
        # defensive trailing-NaN tail so objectiveHistory[j] always means
        # iteration j (a mid-run non-finite objective is reported, not
        # hidden).
        hist = np.asarray(host["hist"], np.float64)[: int(n_iter) + 1]
        while len(hist) and np.isnan(hist[-1]):
            hist = hist[:-1]
        if len(hist):
            # `objective` matches the history definition (incl. the L1
            # term under OWL-QN) so summary.objectiveHistory[-1] ==
            # model.objective always holds
            loss = hist[-1]
        return {
            "coef_": coef.astype(dtype),
            "intercept_": intercept.astype(dtype),
            "classes_": [float(c) for c in range(n_classes)],
            "n_cols": fit_input.pdesc.n,
            "dtype": str(dtype.name),
            "num_iters": int(n_iter),
            "objective": float(loss),
            "objective_history": [float(v) for v in hist],
        }

    def _create_model(self, attrs: Dict[str, Any]) -> "LogisticRegressionModel":
        return LogisticRegressionModel(**attrs)

    def _cpu_fit(self, batch: _ArrayBatch) -> "LogisticRegressionModel":
        from sklearn.linear_model import LogisticRegression as SkLR

        reg = self.getOrDefault("regParam")
        en = self.getOrDefault("elasticNetParam")
        n = batch.X.shape[0]
        if reg == 0.0:
            sk = SkLR(penalty=None, fit_intercept=self.getOrDefault("fitIntercept"),
                      max_iter=1000)
        elif en == 0.0:
            sk = SkLR(C=1.0 / (reg * n), penalty="l2", max_iter=1000,
                      fit_intercept=self.getOrDefault("fitIntercept"))
        else:
            sk = SkLR(C=1.0 / (reg * n), penalty="elasticnet", l1_ratio=en,
                      solver="saga", max_iter=5000,
                      fit_intercept=self.getOrDefault("fitIntercept"))
        sk.fit(batch.X, batch.y.astype(np.int32), sample_weight=batch.weight)
        return LogisticRegressionModel(
            coef_=np.asarray(sk.coef_, batch.X.dtype),
            intercept_=np.asarray(sk.intercept_, batch.X.dtype),
            classes_=[float(c) for c in sk.classes_],
            n_cols=int(batch.X.shape[1]),
            dtype=str(batch.X.dtype),
            num_iters=int(np.max(sk.n_iter_)),
            objective=0.0,
        )


class LogisticRegressionTrainingSummary:
    """Spark LogisticRegressionTrainingSummary analog (the surface
    tests_large reads: `model.summary.objectiveHistory`,
    reference tests_large/test_large_logistic_regression.py:39-60)."""

    def __init__(self, objectiveHistory: List[float], totalIterations: int):
        self.objectiveHistory = list(objectiveHistory)
        self.totalIterations = int(totalIterations)


class LogisticRegressionModel(
    LogisticRegressionClass, _TpuModel, _LogisticRegressionTpuParams
):
    """Logistic regression model (reference LogisticRegressionModel
    classification.py:1306-1615)."""

    def __init__(self, **attrs: Any) -> None:
        super().__init__(**attrs)
        self.coef_: np.ndarray = np.atleast_2d(np.asarray(attrs["coef_"]))
        self.intercept_: np.ndarray = np.atleast_1d(np.asarray(attrs["intercept_"]))
        self.classes_: List[float] = [float(c) for c in attrs["classes_"]]
        self.n_cols: int = int(attrs["n_cols"])
        self.dtype: str = str(attrs.get("dtype", "float32"))
        self.num_iters: int = int(attrs.get("num_iters", 0))
        self.objective: float = float(attrs.get("objective", 0.0))
        self.objective_history: List[float] = [
            float(v) for v in attrs.get("objective_history", [])
        ]

    @property
    def numClasses(self) -> int:
        return len(self.classes_)

    @property
    def hasSummary(self) -> bool:
        # always available after fit (pyspark parity); paths without a
        # solver trace (degenerate single-label, CPU fallback) report the
        # single final objective
        return True

    @property
    def summary(self) -> "LogisticRegressionTrainingSummary":
        """Training summary (pyspark parity: objectiveHistory records the
        full objective per L-BFGS iteration — Spark's
        LogisticRegressionTrainingSummary surface)."""
        return LogisticRegressionTrainingSummary(
            objectiveHistory=self.objective_history or [self.objective],
            totalIterations=self.num_iters,
        )

    @property
    def coefficients(self) -> np.ndarray:
        """Binary models: the single coefficient vector (pyspark parity)."""
        if self.coef_.shape[0] == 1:
            return self.coef_[0]
        raise RuntimeError("Multinomial model: use coefficientMatrix")

    @property
    def coefficientMatrix(self) -> np.ndarray:
        return self.coef_

    @property
    def intercept(self) -> float:
        if len(self.intercept_) == 1:
            return float(self.intercept_[0])
        raise RuntimeError("Multinomial model: use interceptVector")

    @property
    def interceptVector(self) -> np.ndarray:
        return self.intercept_

    def _is_binomial(self) -> bool:
        return self.coef_.shape[0] == 1

    def _output_columns(self) -> List[str]:
        return [
            self.getOrDefault("predictionCol"),
            self.getOrDefault("probabilityCol"),
            self.getOrDefault("rawPredictionCol"),
        ]

    def _transform_array(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        # +/-inf intercepts (single-label degenerate model) can't go
        # through XLA math cleanly; handle on host
        if self._is_binomial() and not np.isfinite(self.intercept_[0]):
            n = X.shape[0]
            p1 = 1.0 if self.intercept_[0] > 0 else 0.0
            dt = X.dtype if hasattr(X, "dtype") else np.float32
            preds = np.full(n, p1, np.int32)
            probs = np.tile([1.0 - p1, p1], (n, 1)).astype(dt)
            raw = np.tile(
                [-self.intercept_[0], self.intercept_[0]], (n, 1)
            ).astype(dt)
            return {
                self.getOrDefault("predictionCol"): preds,
                self.getOrDefault("probabilityCol"): probs,
                self.getOrDefault("rawPredictionCol"): raw,
            }
        return super()._transform_array(X)

    def _transform_device(self, Xs) -> Dict[str, Any]:
        import jax.numpy as jnp

        from ..ops.logistic import binary_predict, logreg_predict

        if self._is_binomial():
            preds, probs, raw = binary_predict(
                Xs,
                jnp.asarray(self.coef_[0].astype(Xs.dtype)),
                Xs.dtype.type(self.intercept_[0]),
            )
            threshold = float(self.getOrDefault("threshold"))
            if threshold != 0.5:
                preds = (probs[:, 1] > threshold).astype(jnp.int32)
        else:
            preds, probs, raw = logreg_predict(
                Xs,
                jnp.asarray(self.coef_.astype(Xs.dtype)),
                jnp.asarray(self.intercept_.astype(Xs.dtype)),
            )
        return {
            self.getOrDefault("predictionCol"): preds.astype(jnp.int32),
            self.getOrDefault("probabilityCol"): probs,
            self.getOrDefault("rawPredictionCol"): raw,
        }

    # -- single-sample API (pyspark Model surface).  The reference falls
    # back to the pyspark CPU model here (classification.py:1593-1615);
    # the coefficient math is host-resident, so compute directly. --------

    def _margins(self, value) -> np.ndarray:
        v = np.asarray(value, np.float64).reshape(-1)
        if v.shape[0] != self.n_cols:
            raise ValueError(
                f"feature vector has {v.shape[0]} entries; model expects "
                f"{self.n_cols}"
            )
        return self.coef_.astype(np.float64) @ v + self.intercept_.astype(
            np.float64
        )

    def predictRaw(self, value) -> np.ndarray:
        """Raw margin vector for one sample (Spark: [-m, m] for binomial)."""
        m = self._margins(value)
        if self._is_binomial():
            return np.array([-m[0], m[0]])
        return m

    def predictProbability(self, value) -> np.ndarray:
        m = self._margins(value)
        if self._is_binomial():
            p1 = 1.0 / (1.0 + np.exp(-m[0]))
            return np.array([1.0 - p1, p1])
        e = np.exp(m - m.max())
        return e / e.sum()

    def predict(self, value) -> float:
        probs = self.predictProbability(value)
        if self._is_binomial():
            threshold = float(self.getOrDefault("threshold"))
            return float(probs[1] > threshold)
        return float(np.argmax(probs))

    def evaluate(self, dataset) -> "LogisticRegressionSummary":
        """Metrics of this model on `dataset` (pyspark
        LogisticRegressionModel.evaluate; the reference delegates to the
        pyspark CPU model — here the TPU transform + the metrics
        subsystem compute them natively).  Goes through the standard
        `_transform`, so featuresCol/featuresCols resolution, chunked
        distributed inference, and the full predictions frame (original
        columns + prediction/probability/rawPrediction) all apply."""
        return _evaluate_classification(self, dataset, LogisticRegressionSummary)

    def cpu(self):
        from sklearn.linear_model import LogisticRegression as SkLR

        sk = SkLR()
        if self._is_binomial():
            sk.coef_ = self.coef_.astype(np.float64)
            sk.intercept_ = self.intercept_.astype(np.float64)
            sk.classes_ = np.array([0.0, 1.0])
        else:
            sk.coef_ = self.coef_.astype(np.float64)
            sk.intercept_ = self.intercept_.astype(np.float64)
            sk.classes_ = np.array(self.classes_)
        sk.n_features_in_ = self.n_cols
        return sk


class _ClassificationSummary:
    """Shared evaluation summary (the pyspark classification summary
    surface over the metrics subsystem)."""

    def __init__(self, predictions, metrics) -> None:
        self.predictions = predictions
        self._m = metrics

    @property
    def accuracy(self) -> float:
        return float(self._m.accuracy)

    @property
    def weightedPrecision(self) -> float:
        return float(self._m.weighted_precision)

    @property
    def weightedRecall(self) -> float:
        return float(self._m.weighted_recall)

    def weightedFMeasure(self, beta: float = 1.0) -> float:
        # a METHOD, matching pyspark's summary surface
        return float(self._m.weighted_f_measure(beta))


class LogisticRegressionSummary(_ClassificationSummary):
    pass


class RandomForestClassificationSummary(_ClassificationSummary):
    pass


def _evaluate_classification(model, dataset, summary_cls):
    """Shared evaluate() tail for the classification models: the standard
    transform front half + multiclass metrics -> summary."""
    from ..core import _evaluate_frame
    from ..metrics import MulticlassMetrics

    out_df, y, preds, weights = _evaluate_frame(model, dataset)
    return summary_cls(
        predictions=out_df,
        metrics=MulticlassMetrics.from_predictions(y, preds, weights=weights),
    )


# ---------------------------------------------------------------------------
# RandomForestClassifier (reference classification.py RandomForestClassifier
# + tree.py shared layer)
# ---------------------------------------------------------------------------


from ..models.tree import (  # noqa: E402
    _RandomForestEstimator,
    _RandomForestModel,
)


class RandomForestClassifier(
    _RandomForestEstimator, HasProbabilityCol, HasRawPredictionCol
):
    """Distributed random forest classifier on TPU (API parity: reference
    RandomForestClassifier in classification.py + tree.py:314-528).

    Ensemble parallelism matches the reference (tree.py:330-341): each mesh
    device grows numTrees/num_workers trees on its local row shard with the
    ops/forest.py histogram builder; no collectives are needed during
    growth (the reference similarly uses no NCCL for RF, tree.py:523-524).

    Examples
    --------
    >>> import numpy as np, pandas as pd
    >>> from spark_rapids_ml_tpu.classification import RandomForestClassifier
    >>> df = pd.DataFrame({"features": [[0.0], [0.1], [0.9], [1.0]],
    ...                    "label": [0.0, 0.0, 1.0, 1.0]})
    >>> rf = RandomForestClassifier(numTrees=5, seed=7, num_workers=1)
    >>> model = rf.setFeaturesCol("features").setLabelCol("label").fit(df)
    >>> model.transform(df)["prediction"].tolist()
    [0, 0, 1, 1]
    """

    def setProbabilityCol(self, value: str):
        self._set(probabilityCol=value)
        return self

    def setRawPredictionCol(self, value: str):
        self._set(rawPredictionCol=value)
        return self

    def _is_classification(self) -> bool:
        return True

    def _validate_input(self, batch: _ArrayBatch) -> None:
        y = np.asarray(batch.y)
        classes = np.unique(y)
        if np.any(classes < 0) or not np.allclose(classes, np.round(classes)):
            # reference error remap tree.py:415-421
            raise ValueError(
                "Labels must be non-negative integers 0..numClasses-1, got "
                f"{classes[:10]}"
            )

    def _validate_device_input(self, ds) -> None:
        # device-side label check for DeviceDataset fits (same contract as
        # the host path; mirrors LogisticRegression's device validation)
        import jax

        global _label_check_jit
        if _label_check_jit is None:
            _label_check_jit = jax.jit(_label_check_kernel)
        integral, mn = jax.device_get(_label_check_jit(ds.y, ds.weight))
        if not bool(integral) or float(mn) < 0:
            raise ValueError(
                "Labels must be non-negative integers 0..numClasses-1"
            )

    def _num_stat_classes(self, fit_input: FitInput) -> int:
        import jax

        # labels are validated >= 0; padded rows are 0, so a plain max works
        # (one scalar device->host fetch)
        C = int(jax.device_get(fit_input.y.max())) + 1
        self._n_classes_ = C
        return C

    def _fit_array(self, fit_input: FitInput) -> Dict[str, Any]:
        attrs = super()._fit_array(fit_input)
        attrs["num_classes"] = self._n_classes_
        return attrs

    def _create_model(self, attrs: Dict[str, Any]) -> "RandomForestClassificationModel":
        return RandomForestClassificationModel(**attrs)

    def _cpu_fit(self, batch: _ArrayBatch) -> "RandomForestClassificationModel":
        raise NotImplementedError(
            "RandomForestClassifier has no CPU fallback; unset unsupported params"
        )


class RandomForestClassificationModel(
    _RandomForestModel, HasProbabilityCol, HasRawPredictionCol
):
    """Random forest classification model (reference
    RandomForestClassificationModel in classification.py)."""

    def __init__(self, **attrs: Any) -> None:
        super().__init__(**attrs)
        self.num_classes: int = int(attrs.get("num_classes",
                                              self.leaf_stats.shape[-1]))

    @property
    def numClasses(self) -> int:
        return self.num_classes

    def _output_columns(self) -> List[str]:
        return [
            self.getOrDefault("predictionCol"),
            self.getOrDefault("probabilityCol"),
            self.getOrDefault("rawPredictionCol"),
        ]

    def _transform_device(self, Xs) -> Dict[str, Any]:
        import jax.numpy as jnp

        from ..ops.forest import forest_apply

        leaves = forest_apply(
            Xs,
            jnp.asarray(self.feature),
            jnp.asarray(self.threshold.astype(Xs.dtype)),
            jnp.asarray(self.left_child),
            max_depth=self.max_depth,
        )  # (T, n)
        # per-tree leaf class-count distributions, normalized per tree then
        # summed (Spark rawPrediction semantics)
        stats = jnp.asarray(self.leaf_stats.astype(Xs.dtype))  # (T, L, C)
        counts = jnp.take_along_axis(stats, leaves[:, :, None], axis=1)
        sums = jnp.maximum(counts.sum(axis=2, keepdims=True), 1e-12)
        raw = (counts / sums).sum(axis=0)  # (n, C)
        probs = raw / self.numTrees
        preds = jnp.argmax(raw, axis=1).astype(jnp.int32)
        return {
            self.getOrDefault("predictionCol"): preds,
            self.getOrDefault("probabilityCol"): probs,
            self.getOrDefault("rawPredictionCol"): raw,
        }

    def cpu(self):
        """Pure-numpy predictor mirroring the fitted forest (the reference
        converts treelite -> Spark model, utils.py:585-809; here the model
        arrays themselves are the portable format)."""
        return _NumpyForestPredictor(self, classification=True)

    # single-sample API (the reference falls back to the pyspark CPU
    # model, classification.py:606-616; the node-table forest is
    # host-resident, so the numpy predictor answers directly)

    def predictProbability(self, value) -> np.ndarray:
        v = np.asarray(value, np.float64).reshape(1, -1)
        if v.shape[1] != self.n_cols:
            raise ValueError(
                f"feature vector has {v.shape[1]} entries; model expects "
                f"{self.n_cols}"
            )
        return self.cpu().predict_proba(v)[0]

    def predictRaw(self, value) -> np.ndarray:
        # rawPrediction = per-tree normalized class votes summed
        return self.predictProbability(value) * self.numTrees

    def predict(self, value) -> float:
        return float(np.argmax(self.predictProbability(value)))

    def evaluate(self, dataset) -> "RandomForestClassificationSummary":
        """Metrics of this model on `dataset` (pyspark
        RandomForestClassificationModel.evaluate; absent from the
        reference entirely)."""
        return _evaluate_classification(
            self, dataset, RandomForestClassificationSummary
        )


class _NumpyForestPredictor:
    """Host-side forest predictor over the portable model arrays."""

    def __init__(self, model: _RandomForestModel, classification: bool) -> None:
        self.feature = model.feature
        self.threshold = model.threshold
        self.leaf_stats = model.leaf_stats
        self.left_child = model.left_child
        self.max_depth = model.max_depth
        self.classification = classification

    def _leaves(self, X: np.ndarray) -> np.ndarray:
        T, n = self.feature.shape[0], X.shape[0]
        node = np.zeros((T, n), np.int64)
        for _ in range(self.max_depth):
            f = np.take_along_axis(self.feature, node, axis=1)
            thr = np.take_along_axis(self.threshold, node, axis=1)
            lc = np.take_along_axis(self.left_child, node, axis=1)
            x = X[np.arange(n)[None, :], np.maximum(f, 0)]
            child = lc + (x > thr)
            node = np.where(f < 0, node, child)
        return node

    def predict(self, X: np.ndarray) -> np.ndarray:
        leaves = self._leaves(np.asarray(X))
        stats = np.take_along_axis(
            self.leaf_stats, leaves[:, :, None], axis=1
        )
        if self.classification:
            sums = np.maximum(stats.sum(axis=2, keepdims=True), 1e-12)
            return np.argmax((stats / sums).sum(axis=0), axis=1)
        w = np.maximum(stats[:, :, 0], 1e-12)
        return (stats[:, :, 1] / w).mean(axis=0)

    def predict_proba(self, X: np.ndarray) -> np.ndarray:
        assert self.classification
        leaves = self._leaves(np.asarray(X))
        stats = np.take_along_axis(
            self.leaf_stats, leaves[:, :, None], axis=1
        )
        sums = np.maximum(stats.sum(axis=2, keepdims=True), 1e-12)
        probs = (stats / sums).sum(axis=0)
        return probs / probs.sum(axis=1, keepdims=True)
