# Implemented progressively; see models/feature.py for the pattern.
__all__: list = []
