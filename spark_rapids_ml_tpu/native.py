#
# Native host-staging bindings — loads native/staging.cpp (the analog of
# the reference's native memory layer: `_concat_and_free`/reserved-memory
# staging utils.py:358-522 and numpy_allocator.py's C hooks) via ctypes,
# building the shared library on first use with the baked-in g++.  Every
# entry point has a numpy fallback, so the package works without a
# compiler; the native path parallelizes the pad/cast/pack/densify loops
# that feed `jax.device_put`.
#
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

from .utils import get_logger
from .telemetry.locks import named_lock

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_REPO_ROOT, "native", "staging.cpp")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libstaging.so")

_lock = named_lock("native_build")
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


_BUILD_TIMEOUT_S = 300


class NativeBuildTimeout(RuntimeError):
    """The native staging build's compiler hung past the timeout.  Unlike
    a missing g++ (an expected environment, silently falls back to numpy),
    a HUNG compiler is a real fault worth surfacing loudly — and the bare
    `TimeoutExpired` loses the command line and any partial stderr, which
    is exactly what's needed to debug it."""


def _build() -> bool:
    global _load_failed
    os.makedirs(_BUILD_DIR, exist_ok=True)
    # compile to a process-unique temp path and rename into place so
    # concurrent builders never dlopen a half-written library
    tmp_path = f"{_LIB_PATH}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-march=native", "-fopenmp", "-shared", "-fPIC",
        "-std=c++17", _SRC, "-o", tmp_path,
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=_BUILD_TIMEOUT_S
        )
    except subprocess.TimeoutExpired as e:
        stderr = e.stderr or b""
        if isinstance(stderr, bytes):
            stderr = stderr.decode(errors="replace")
        # latch the failure like every other build/load path: without
        # this, each subsequent staging call re-runs the full hung
        # compile and pays the timeout again
        _load_failed = True
        raise NativeBuildTimeout(
            f"native staging build timed out after {_BUILD_TIMEOUT_S}s: "
            f"`{' '.join(cmd)}`"
            + (f"; partial stderr: {stderr[-500:]}" if stderr else "")
        ) from e
    except Exception as e:  # g++ missing etc.
        get_logger("spark_rapids_ml_tpu.native").warning(
            f"native staging build unavailable ({e}); using numpy fallback"
        )
        return False
    if proc.returncode != 0:
        get_logger("spark_rapids_ml_tpu.native").warning(
            f"native staging build failed; using numpy fallback:\n{proc.stderr[-500:]}"
        )
        return False
    os.replace(tmp_path, _LIB_PATH)
    return True


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if not os.path.exists(_LIB_PATH) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_LIB_PATH)
        ):
            if not _build():
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            get_logger("spark_rapids_ml_tpu.native").warning(
                f"native staging load failed ({e}); using numpy fallback"
            )
            _load_failed = True
            return None
        i64, f32p, f64p = ctypes.c_int64, ctypes.POINTER(ctypes.c_float), \
            ctypes.POINTER(ctypes.c_double)
        pp = ctypes.POINTER(ctypes.c_void_p)
        for name, argtypes in {
            "pad_cast_f64_f32": [f64p, i64, i64, i64, f32p],
            "pad_copy_f32": [f32p, i64, i64, i64, f32p],
            "pad_copy_f64": [f64p, i64, i64, i64, f64p],
            "pad_cast_f32_f64": [f32p, i64, i64, i64, f64p],
            "pack_rows_f64_f32": [pp, i64, i64, i64, f32p],
            "pack_rows_f32_f32": [pp, i64, i64, i64, f32p],
            "pack_rows_f64_f64": [pp, i64, i64, i64, f64p],
            "gather_strided_f64_f32": [f64p, i64, i64, i64, i64, f32p],
            "gather_strided_f32_f32": [f32p, i64, i64, i64, i64, f32p],
            "gather_strided_f64_f64": [f64p, i64, i64, i64, i64, f64p],
            "gather_strided_f32_f64": [f32p, i64, i64, i64, i64, f64p],
            "csr_densify_f32": [ctypes.POINTER(i64),
                                ctypes.POINTER(ctypes.c_int32), f32p, i64,
                                i64, i64, f32p],
            "csr_densify_f64_f32": [ctypes.POINTER(i64),
                                    ctypes.POINTER(ctypes.c_int32), f64p,
                                    i64, i64, i64, f32p],
        }.items():
            getattr(lib, name).argtypes = argtypes
            getattr(lib, name).restype = None
        lib.staging_num_threads.restype = ctypes.c_int
        _lib = lib
        get_logger("spark_rapids_ml_tpu.native").info(
            f"native staging library loaded ({lib.staging_num_threads()} threads)"
        )
    return _lib


def available() -> bool:
    return _load() is not None


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


# Below ~64MB the numpy copy is already fast; skip ctypes overhead.
_MIN_NATIVE_BYTES = 1 << 26


# set True in tests to exercise the native kernels regardless of size and
# thread-count gates
_FORCE_NATIVE = False

# pack_rows wins even single-threaded; this only amortizes the ctypes setup
_MIN_PACK_ROWS = 16384


def _parallel_lib():
    """The library, but only when OpenMP has real parallelism: numpy's
    SIMD copy/cast loops already saturate a single core, so the bandwidth-
    bound pad/densify paths only win multi-threaded."""
    lib = _load()
    if lib is not None and (_FORCE_NATIVE or lib.staging_num_threads() > 1):
        return lib
    return None


def pad_cast(arr: np.ndarray, n_pad: int, dtype: np.dtype) -> np.ndarray:
    """Zero-padded, dtype-cast, C-contiguous copy of a 2-D array — the
    staging step of mesh.shard_rows, parallelized when large."""
    dtype = np.dtype(dtype)
    n, d = arr.shape
    lib = _parallel_lib() if arr.nbytes >= _MIN_NATIVE_BYTES else None
    pair = (str(arr.dtype), str(dtype))
    fn = None
    if lib is not None and arr.flags.c_contiguous:
        fn = {
            ("float64", "float32"): ("pad_cast_f64_f32", ctypes.c_double),
            ("float32", "float32"): ("pad_copy_f32", ctypes.c_float),
            ("float64", "float64"): ("pad_copy_f64", ctypes.c_double),
            ("float32", "float64"): ("pad_cast_f32_f64", ctypes.c_float),
        }.get(pair)
    if fn is not None:
        out = np.empty((n_pad, d), dtype)
        name, src_ct = fn
        dst_ct = ctypes.c_float if dtype == np.float32 else ctypes.c_double
        getattr(lib, name)(_ptr(arr, src_ct), n, d, n_pad, _ptr(out, dst_ct))
        return out
    out = np.zeros((n_pad, d), dtype)
    out[:n] = arr
    return out


def gather_rows_strided(
    arr: np.ndarray, start: int, step: int, count: int, dtype: np.dtype
) -> np.ndarray:
    """Contiguous, dtype-cast copy of rows `arr[start + i*step]` for
    i in [0, count) — the fused interleave-permutation slice of the
    pipelined staging engine (mesh.RowStager round-robin layout),
    parallelized when large.  `step=1` is the plain contiguous chunk
    slice (still fusing the cast), so the engine has ONE producer
    primitive for both layouts."""
    dtype = np.dtype(dtype)
    d = int(np.prod(arr.shape[1:], dtype=np.int64)) if arr.ndim > 1 else 1
    out_bytes = count * d * dtype.itemsize
    lib = (
        _parallel_lib()
        if (out_bytes >= _MIN_NATIVE_BYTES or _FORCE_NATIVE)
        else None
    )
    if (
        lib is not None and arr.ndim == 2 and arr.flags.c_contiguous
        and count > 0
    ):
        name = {
            ("float64", "float32"): "gather_strided_f64_f32",
            ("float32", "float32"): "gather_strided_f32_f32",
            ("float64", "float64"): "gather_strided_f64_f64",
            ("float32", "float64"): "gather_strided_f32_f64",
        }.get((str(arr.dtype), str(dtype)))
        if name is not None:
            src_ct = (
                ctypes.c_double if arr.dtype == np.float64 else ctypes.c_float
            )
            dst_ct = (
                ctypes.c_float if dtype == np.float32 else ctypes.c_double
            )
            out = np.empty((count, d), dtype)
            getattr(lib, name)(
                _ptr(arr, src_ct), start, step, count, d, _ptr(out, dst_ct)
            )
            return out
    stop = start + count * step
    return np.ascontiguousarray(arr[start:stop:step], dtype=dtype)


def pack_rows(rows: np.ndarray, n_pad: int, dtype: np.dtype) -> np.ndarray:
    """Pack an object array of n per-row vectors into a padded (n_pad, d)
    matrix — the np.stack replacement for array-valued feature columns."""
    dtype = np.dtype(dtype)
    n = len(rows)
    first = np.asarray(rows[0])
    d = first.shape[0]
    # wins even single-threaded (np.stack pays per-row Python overhead),
    # so gate only on the row count amortizing the ctypes setup
    lib = _load() if (n >= _MIN_PACK_ROWS or _FORCE_NATIVE) else None
    if lib is not None and dtype in (np.float32, np.float64):
        name = {
            ("float64", "float32"): "pack_rows_f64_f32",
            ("float32", "float32"): "pack_rows_f32_f32",
            ("float64", "float64"): "pack_rows_f64_f64",
        }.get((str(first.dtype), str(dtype)))
        if name is not None:
            ptrs = (ctypes.c_void_p * n)()
            ok = True
            for i in range(n):
                r = rows[i]
                if (
                    not isinstance(r, np.ndarray)
                    or r.dtype != first.dtype
                    or r.shape != (d,)
                    or not r.flags.c_contiguous
                ):
                    ok = False
                    break
                ptrs[i] = r.ctypes.data
            if ok:
                out = np.empty((n_pad, d), dtype)
                dst_ct = (
                    ctypes.c_float if dtype == np.float32 else ctypes.c_double
                )
                getattr(lib, name)(
                    ctypes.cast(ptrs, ctypes.POINTER(ctypes.c_void_p)),
                    n, d, n_pad, _ptr(out, dst_ct),
                )
                return out
    stacked = np.ascontiguousarray(
        np.stack([np.asarray(v, dtype=dtype) for v in rows])
    )
    if n_pad == n:
        return stacked
    out = np.zeros((n_pad, d), dtype)
    out[:n] = stacked
    return out


def densify_csr(csr, n_pad: int, dtype: np.dtype) -> np.ndarray:
    """CSR -> padded dense (n_pad, d) block (the per-block densify of the
    TPU sparse strategy), parallelized over rows."""
    dtype = np.dtype(dtype)
    n, d = csr.shape
    lib = (
        _parallel_lib()
        if (n * d * dtype.itemsize >= _MIN_NATIVE_BYTES or _FORCE_NATIVE)
        else None
    )
    if lib is not None and dtype == np.float32:
        if not csr.has_canonical_format:
            # the native kernel assigns (last write wins); scipy's toarray
            # SUMS duplicate entries — canonicalize to match
            csr.sum_duplicates()
        indptr = np.ascontiguousarray(csr.indptr, dtype=np.int64)
        indices = np.ascontiguousarray(csr.indices, dtype=np.int32)
        data = np.ascontiguousarray(csr.data)
        name = {
            "float32": "csr_densify_f32",
            "float64": "csr_densify_f64_f32",
        }.get(str(data.dtype))
        if name is not None:
            out = np.empty((n_pad, d), np.float32)
            getattr(lib, name)(
                _ptr(indptr, ctypes.c_int64),
                _ptr(indices, ctypes.c_int32),
                _ptr(data, ctypes.c_float if data.dtype == np.float32
                     else ctypes.c_double),
                n, d, n_pad, _ptr(out, ctypes.c_float),
            )
            return out
    dense = csr.toarray()
    if n_pad == n:
        return np.ascontiguousarray(dense.astype(dtype, copy=False))
    out = np.zeros((n_pad, d), dtype)
    out[:n] = dense
    return out


__all__ = [
    "NativeBuildTimeout", "available", "pad_cast", "pack_rows",
    "densify_csr", "gather_rows_strided",
]
