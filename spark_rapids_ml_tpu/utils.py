#
# Utilities — the analog of reference utils.py (982 LoC): logging
# (utils.py:555-576), PartitionDescriptor (utils.py:300-355),
# memory-efficient concat (utils.py:358-400), and small array helpers.
# The GPU-id / RMM pieces have no TPU analog (XLA owns HBM); host staging
# helpers live in data.py.
#
from __future__ import annotations

import logging
import sys
from dataclasses import dataclass
from typing import Any, List, Optional, Type, Union

import numpy as np

_logger_initialized = set()


def get_logger(cls: Union[Type, str], level: int = logging.INFO) -> logging.Logger:
    """Per-class stderr logger (reference utils.py:555-576)."""
    name = cls if isinstance(cls, str) else f"spark_rapids_ml_tpu.{cls.__name__}"
    logger = logging.getLogger(name)
    if name not in _logger_initialized:
        logger.setLevel(level)
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
        logger.addHandler(handler)
        logger.propagate = False
        _logger_initialized.add(name)
    return logger


@dataclass
class PartitionDescriptor:
    """Global partition layout for a distributed fit (reference
    `PartitionDescriptor`, utils.py:300-355, built there via barrier
    allGather; here computed by the single controller that shards rows).

    m: total number of rows
    n: number of features (data dim)
    parts_rank_size: (rank, row-count) per shard
    rank: this process's rank (always 0 single-controller)
    """

    m: int
    n: int
    parts_rank_size: List[tuple]
    rank: int = 0
    max_nnz: int = 0

    @classmethod
    def build(cls, partition_rows: List[int], total_cols: int, rank: int = 0,
              max_nnz: int = 0) -> "PartitionDescriptor":
        return cls(
            m=int(sum(partition_rows)),
            n=int(total_cols),
            parts_rank_size=[(i, int(r)) for i, r in enumerate(partition_rows)],
            rank=rank,
            max_nnz=max_nnz,
        )


def _concat_and_free(arrays: List[np.ndarray], order: str = "C") -> np.ndarray:
    """Concatenate row blocks into a preallocated output, freeing inputs as
    we go to halve peak host memory (reference `_concat_and_free`,
    utils.py:358-400)."""
    if len(arrays) == 1:
        return np.ascontiguousarray(arrays[0]) if order == "C" else np.asfortranarray(arrays[0])
    rows = sum(a.shape[0] for a in arrays)
    if arrays[0].ndim == 1:
        out = np.empty((rows,), dtype=arrays[0].dtype)
    else:
        out = np.empty((rows, arrays[0].shape[1]), dtype=arrays[0].dtype, order=order)  # type: ignore[call-overload]
    offset = 0
    while arrays:
        a = arrays.pop(0)
        out[offset : offset + a.shape[0]] = a
        offset += a.shape[0]
        del a
    return out


def _standardize_stats(X: np.ndarray, sample_weight: Optional[np.ndarray] = None):
    """Weighted column mean/std matching Spark's summarizer semantics
    (ddof=1-style scaling, reference `_standardize_dataset` utils.py:876-982).
    Host-side helper for the CPU path; the distributed version is
    ops/stats.py."""
    if sample_weight is None:
        mean = X.mean(axis=0)
        std = X.std(axis=0, ddof=1)
    else:
        w = sample_weight / sample_weight.sum()
        mean = (X * w[:, None]).sum(axis=0)
        var = (w[:, None] * (X - mean) ** 2).sum(axis=0) * (
            sample_weight.sum() / max(sample_weight.sum() - 1, 1)
        )
        std = np.sqrt(var)
    std = np.where(std == 0.0, 1.0, std)
    return mean, std


def array_equal_tol(
    a: Any, b: Any, unit_tol: float = 1e-4, total_tol: float = 0.0
) -> bool:
    """Tolerant array comparison used throughout tests (reference
    tests/utils.py:150-165)."""
    a = np.asarray(a)
    b = np.asarray(b)
    if a.shape != b.shape:
        return False
    close = np.isclose(a, b, atol=unit_tol, rtol=0)
    return bool((~close).sum() <= total_tol * close.size)


@dataclass
class _ArrayBatch:
    """A staged host batch: features plus optional label/weight/id columns."""

    X: np.ndarray
    y: Optional[np.ndarray] = None
    weight: Optional[np.ndarray] = None
    row_id: Optional[np.ndarray] = None


def prefetch_iter(it, depth: int):
    """Run iterator `it` on a daemon thread up to `depth` items ahead of
    the consumer (bounded queue of depth-1 + the one in the producer's
    hand) — the shared overlap primitive behind
    `streaming.iter_chunks_prefetch` (parquet decode ahead of the device)
    and the staging pipeline's producer (`mesh.run_staging_pipeline`).
    Bounded puts so an abandoned consumer (exception/GC closes the
    generator) cannot pin the producer thread + item copies forever;
    producer exceptions re-raise on the consumer.  depth <= 1: plain
    iteration, no thread."""
    if depth <= 1:
        yield from it
        return
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=depth - 1)
    _DONE = object()
    stop = threading.Event()

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    # the producer runs the caller's iterator (chunk-cache inserts emit
    # spill/evict trace events; device_puts emit compile events): adopt
    # the caller's trace buffer + run context so they attribute to the
    # fit that is consuming, not to an anonymous worker thread
    from .tracing import adopt_trace_context

    adopt = adopt_trace_context()

    def producer() -> None:
        adopt()
        try:
            for item in it:
                if not _put(item):
                    return
            _put(_DONE)
        except BaseException as e:  # surface producer errors on the consumer
            _put(e)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _DONE:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()


def shard_map_compat(*args: Any, **kwargs: Any):
    """`jax.shard_map`, version-tolerant: the API moved from
    `jax.experimental.shard_map.shard_map` to the top level (jax >= 0.6);
    older runtimes (0.4.x pins of the tunnel image) only have the
    experimental path.  One accessor so every shard_map kernel runs on
    both.

    The experimental fallback gets `check_rep=False`: our kernels are
    written for the NEW typed-varying discipline (explicit `pcast` where
    a carry becomes device-varying — `pcast_compat`), which the old
    checker cannot see; it also has no replication rule at all for
    control-flow primitives the kernels rely on (`jax.random` internals
    under while_loop).  The check is a static safety lint, not part of
    the computation — out_specs still shape the outputs identically."""
    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn  # type: ignore

        kwargs.setdefault("check_rep", False)
    return fn(*args, **kwargs)


def pcast_compat(x: Any, axes: Any, to: str = "varying") -> Any:
    """`jax.lax.pcast`, version-tolerant: the replicated->varying cast
    exists only on runtimes with typed shard_map (jax >= 0.6 / the tunnel
    image).  Older shard_map (0.4.x) has no varying-type checking, so the
    cast is semantically a no-op there — return the operand unchanged."""
    import jax

    fn = getattr(jax.lax, "pcast", None)
    if fn is None:
        return x
    return fn(x, axes, to=to)


def host_load_metadata() -> dict:
    """Self-describing-artifact host metadata (bench/rehearsal/ANN JSON):
    loadavg, cpu count, and a `contended` flag meaning FOREIGN load —
    ~1.0 is allowed for the measuring process itself, which alone pins
    loadavg to 1 on a 1-core host.  One owner so the bench and the
    run-once scripts can never disagree on what 'contended' means."""
    import os

    try:
        load = os.getloadavg()
    except OSError:
        return {}
    ncpu = os.cpu_count() or 1
    return {
        "host_loadavg_start": [round(v, 2) for v in load],
        "host_cpus": ncpu,
        "contended": load[0] > 1.0 + 0.5 * ncpu,
    }
