#
# Summarizer surface — the reference-compatible face of the statistic
# -program engine (the analog of `pyspark.ml.stat.Summarizer` metrics
# and `DataFrame.describe()`).  `summarize(data, metrics=[...])`
# resolves every requested metric to its registered program, runs the
# UNION of programs in ONE fused pass (stats/engine.py), and maps the
# finalized statistics back onto the requested metric names — asking
# for mean+variance+min+max+quantiles+distinctCount costs one scan, not
# six.
#
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import numpy as np

# metric name -> (program, result key); metrics mapping to the same
# program share its single accumulator in the fused pass
_METRICS: Dict[str, tuple] = {
    "count": ("moments", "count"),
    "weightSum": ("moments", "weight_sum"),
    "mean": ("moments", "mean"),
    "sum": ("moments", "sum"),
    "variance": ("moments", "variance"),
    "std": ("moments", "std"),
    "min": ("moments", "min"),
    "max": ("moments", "max"),
    "normL1": ("moments", "norm_l1"),
    "normL2": ("moments", "norm_l2"),
    "numNonZeros": ("moments", "num_nonzeros"),
    "covariance": ("covariance", "covariance"),
    "correlation": ("covariance", "correlation"),
    "standardization": ("standardization", None),
    "quantiles": ("quantile_sketch", "quantiles"),
    "median": ("quantile_sketch", None),
    "frequentItems": ("frequent_items", None),
    "distinctCount": ("distinct_count", "distinct"),
    "ttest": ("ttest", None),
    "chi2": ("chi2", None),
}

SUPPORTED_METRICS = frozenset(_METRICS)


def summarize(
    data,
    metrics: Sequence[str] = ("count", "mean", "variance"),
    *,
    features_col: Optional[str] = "features",
    features_cols: Sequence[str] = (),
    label_col: Optional[str] = None,
    weight_col: Optional[str] = None,
    quantiles: Sequence[float] = (0.25, 0.5, 0.75),
    dtype=None,
) -> Dict[str, Any]:
    """Compute every requested metric in ONE pass over `data` (a numpy
    batch, `(X, y)` tuple, pandas frame, or parquet path).  Returns
    `{metric: value}`; vector-valued metrics are per-column arrays in
    column order."""
    from .engine import run_programs

    metrics = list(dict.fromkeys(metrics))
    unknown = [m for m in metrics if m not in _METRICS]
    if unknown:
        raise ValueError(
            f"unknown summarizer metrics {unknown}; supported: "
            + ", ".join(sorted(_METRICS))
        )
    programs = list(dict.fromkeys(_METRICS[m][0] for m in metrics))
    qs = list(dict.fromkeys(float(q) for q in quantiles))
    if "median" in metrics and 0.5 not in qs:
        qs.append(0.5)
    results = run_programs(
        programs, data,
        features_col=features_col, features_cols=features_cols,
        label_col=label_col, weight_col=weight_col,
        dtype=dtype, quantiles=qs, label="summarize",
    )
    out: Dict[str, Any] = {}
    for m in metrics:
        prog, key = _METRICS[m]
        r = results[prog]
        if m == "median":
            out[m] = r["quantiles"][0.5]
        elif m == "frequentItems":
            out[m] = r["items"]
        elif key is None:
            out[m] = r
        else:
            out[m] = r[key]
    return out


class Summarizer:
    """Reference-style metric builder: ``Summarizer.metrics("mean",
    "variance").summary(df)`` computes the requested metrics in one
    fused pass.  `describe` is the `DataFrame.describe()` analog."""

    def __init__(self, *metric_names: str) -> None:
        self._metrics = list(metric_names) or ["count", "mean", "variance"]

    @classmethod
    def metrics(cls, *metric_names: str) -> "Summarizer":
        return cls(*metric_names)

    def summary(self, data, **kwargs) -> Dict[str, Any]:
        return summarize(data, metrics=self._metrics, **kwargs)

    @staticmethod
    def describe(
        data,
        *,
        features_col: Optional[str] = "features",
        features_cols: Sequence[str] = (),
        weight_col: Optional[str] = None,
        column_names: Optional[Sequence[str]] = None,
    ):
        """`DataFrame.describe()`-style summary table: one fused pass
        computing count/mean/std/min/25%/50%/75%/max, returned as a
        pandas DataFrame with one column per feature."""
        import pandas as pd

        s = summarize(
            data,
            metrics=("count", "mean", "std", "min", "quantiles", "max"),
            features_col=features_col, features_cols=features_cols,
            weight_col=weight_col, quantiles=(0.25, 0.5, 0.75),
        )
        d = int(np.asarray(s["mean"]).shape[0])
        if column_names is None:
            column_names = (
                list(features_cols)
                if features_cols
                else [f"x{i}" for i in range(d)]
            )
        rows = {
            "count": np.full((d,), s["count"], np.float64),
            "mean": np.asarray(s["mean"]),
            "std": np.asarray(s["std"]),
            "min": np.asarray(s["min"]),
            "25%": np.asarray(s["quantiles"][0.25]),
            "50%": np.asarray(s["quantiles"][0.5]),
            "75%": np.asarray(s["quantiles"][0.75]),
            "max": np.asarray(s["max"]),
        }
        return pd.DataFrame(rows, index=list(column_names)).T


def describe(data, **kwargs):
    """Module-level convenience over `Summarizer.describe`."""
    return Summarizer.describe(data, **kwargs)
