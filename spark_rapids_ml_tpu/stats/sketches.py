#
# Mergeable sketch state for the statistic-program engine — the host-side
# accumulator math behind the `quantile_sketch` and `frequent_items`
# programs (stats/programs.py) plus the HyperLogLog finalizer shared by
# the device-side `distinct_count` program.  All three are MERGEABLE
# summaries in the Agarwal et al. sense: combining per-chunk (or
# per-reader, or per-process) partial states loses no more accuracy than
# streaming the concatenated data through one state, so the engine may
# fold chunks in ANY order (the parallel parquet readers deliver them in
# any order) and tests may split a batch 1/4/8 ways and merge.
#
# Determinism: the quantile compaction keeps the even-indexed items of a
# sorted buffer (classic KLL randomizes the offset); the frequent-items
# decrement is the batched Misra-Gries step.  Same data + same chunking
# -> bit-identical state, which is what the restart-not-double-count
# retry contract needs to be testable.
#
# Sketch weights: the engine feeds the padded-tail validity vector, and
# the sketches treat `w` as a VALIDITY mask (w > 0 rows participate
# once) — multiplicity-weighted quantiles/frequencies are out of scope
# and documented so in docs/statistics.md.
#
from __future__ import annotations

import io
import struct
from typing import Dict, Tuple

import numpy as np

# quantile sketch geometry: levels hold `k` items each, level l items
# carry weight 2^l.  28 levels * k=256 covers ~2^36 rows before the top
# level would overflow — far past the 1B-row north star.
QUANTILE_LEVELS = 28


def quantile_init(d: int, k: int) -> Dict[str, np.ndarray]:
    """Fresh per-column quantile-sketch state.  `sizes` is shared by all
    columns (every column sees the same valid rows), so the per-level
    bookkeeping stays O(L) not O(cols * L)."""
    return {
        "items": np.zeros((d, QUANTILE_LEVELS, k), np.float64),
        "sizes": np.zeros((QUANTILE_LEVELS,), np.int64),
        "n": np.zeros((), np.int64),
    }


def _compact_level(acc: Dict[str, np.ndarray], level: int, k: int) -> None:
    """Sort level's buffer per column, keep the even-indexed half at
    weight 2^(level+1) (promoted into the next level), empty this level.
    Cascades when the promotion overflows the next level."""
    size = int(acc["sizes"][level])
    if size < 2:
        return
    buf = np.sort(acc["items"][:, level, :size], axis=1)
    keep = buf[:, 0:2 * (size // 2):2]  # even indices of the sorted pairs
    odd_one = buf[:, -1:] if size % 2 else None
    promoted = keep.shape[1]
    nxt = level + 1
    if nxt >= QUANTILE_LEVELS:  # pragma: no cover - 2^36-row guard
        raise RuntimeError("quantile sketch level overflow")
    if int(acc["sizes"][nxt]) + promoted > k:
        _compact_level(acc, nxt, k)
    at = int(acc["sizes"][nxt])
    acc["items"][:, nxt, at:at + promoted] = keep
    acc["sizes"][nxt] = at + promoted
    # an odd leftover item stays at this level (weight unchanged)
    acc["sizes"][level] = 0
    if odd_one is not None:
        acc["items"][:, level, :1] = odd_one
        acc["sizes"][level] = 1


def quantile_update(
    acc: Dict[str, np.ndarray], X: np.ndarray, valid: np.ndarray, k: int
) -> Dict[str, np.ndarray]:
    """Fold one (rows, cols) chunk into the sketch (rows with
    `valid`=False are padding and never enter)."""
    vals = np.asarray(X[valid], np.float64).T  # (cols, m)
    m = vals.shape[1]
    acc["n"] = acc["n"] + m
    pos = 0
    while pos < m:
        size0 = int(acc["sizes"][0])
        take = min(k - size0, m - pos)
        if take == 0:
            _compact_level(acc, 0, k)
            continue
        acc["items"][:, 0, size0:size0 + take] = vals[:, pos:pos + take]
        acc["sizes"][0] = size0 + take
        pos += take
    return acc


def quantile_merge(
    a: Dict[str, np.ndarray], b: Dict[str, np.ndarray], k: int
) -> Dict[str, np.ndarray]:
    """Fold state `b` into `a` level-by-level (same-weight items land in
    the same level, so the merged error bound matches the streamed
    one)."""
    a = {kk: np.array(v) for kk, v in a.items()}
    for level in range(QUANTILE_LEVELS):
        sb = int(b["sizes"][level])
        pos = 0
        while pos < sb:
            at = int(a["sizes"][level])
            take = min(k - at, sb - pos)
            if take == 0:  # full: compact (leaves <= 1 item) and retry
                _compact_level(a, level, k)
                continue
            a["items"][:, level, at:at + take] = (
                b["items"][:, level, pos:pos + take]
            )
            a["sizes"][level] = at + take
            pos += take
    a["n"] = a["n"] + b["n"]
    return a


def quantile_query(
    acc: Dict[str, np.ndarray], qs
) -> np.ndarray:
    """(cols, len(qs)) estimated quantiles: gather every retained item
    with its level weight, per-column weighted rank lookup."""
    qs = np.atleast_1d(np.asarray(qs, np.float64))
    d = acc["items"].shape[0]
    cols_items = []
    weights = []
    for level in range(QUANTILE_LEVELS):
        size = int(acc["sizes"][level])
        if size == 0:
            continue
        cols_items.append(acc["items"][:, level, :size])
        weights.append(np.full((size,), float(2 ** level)))
    if not cols_items:
        return np.full((d, qs.size), np.nan)
    items = np.concatenate(cols_items, axis=1)  # (cols, t)
    w = np.concatenate(weights)  # (t,)
    order = np.argsort(items, axis=1, kind="stable")
    sorted_items = np.take_along_axis(items, order, axis=1)
    cum = np.cumsum(w[order], axis=1)
    total = cum[:, -1:]
    out = np.empty((d, qs.size))
    for j, q in enumerate(qs):
        target = np.clip(q, 0.0, 1.0) * total[:, 0]
        idx = np.minimum(
            (cum < target[:, None]).sum(axis=1), items.shape[1] - 1
        )
        out[:, j] = sorted_items[np.arange(d), idx]
    return out


# ---------------------------------------------------------------------------
# Misra-Gries frequent items (per column)
# ---------------------------------------------------------------------------


def frequent_init(d: int, cap: int) -> Dict[str, np.ndarray]:
    """keys are NaN-marked-empty; counts carry the MG lower bounds;
    `err` is the cumulative decrement per column (the +/- bound every
    reported count carries)."""
    return {
        "keys": np.full((d, cap), np.nan),
        "counts": np.zeros((d, cap), np.int64),
        "err": np.zeros((d,), np.int64),
        "n": np.zeros((), np.int64),
    }


def _mg_fold_column(
    keys: np.ndarray, counts: np.ndarray, err: int,
    new_keys: np.ndarray, new_counts: np.ndarray, cap: int,
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Batched Misra-Gries merge of one column's (value -> count) table
    with fresh chunk counts: combine, then subtract the (cap+1)-largest
    count from everything and drop the non-positive survivors (the
    classic mergeable-summaries step; error grows by the subtracted
    amount)."""
    live = ~np.isnan(keys)
    table: Dict[float, int] = dict(
        zip(keys[live].tolist(), counts[live].tolist())
    )
    for kv, cv in zip(new_keys.tolist(), new_counts.tolist()):
        table[kv] = table.get(kv, 0) + int(cv)
    if len(table) > cap:
        by_count = sorted(table.values(), reverse=True)
        t = by_count[cap]  # the (cap+1)-th largest
        table = {kv: cv - t for kv, cv in table.items() if cv - t > 0}
        err += t
    out_k = np.full((cap,), np.nan)
    out_c = np.zeros((cap,), np.int64)
    ordered = sorted(table.items(), key=lambda it: (-it[1], it[0]))[:cap]
    for i, (kv, cv) in enumerate(ordered):
        out_k[i] = kv
        out_c[i] = cv
    return out_k, out_c, err


def frequent_update(
    acc: Dict[str, np.ndarray], X: np.ndarray, valid: np.ndarray, cap: int
) -> Dict[str, np.ndarray]:
    vals = np.asarray(X[valid], np.float64)
    acc["n"] = acc["n"] + vals.shape[0]
    for j in range(vals.shape[1]):
        col = vals[:, j]
        # NaN is the empty-slot sentinel and never compares equal to
        # itself: real NaN data would mint a fresh never-matching entry
        # per chunk and evict genuine frequent items — missing values
        # are excluded from the frequency table instead
        col = col[~np.isnan(col)]
        if col.size == 0:
            continue
        uniq, cnts = np.unique(col, return_counts=True)
        acc["keys"][j], acc["counts"][j], e = _mg_fold_column(
            acc["keys"][j], acc["counts"][j], int(acc["err"][j]),
            uniq, cnts, cap,
        )
        acc["err"][j] = e
    return acc


def frequent_merge(
    a: Dict[str, np.ndarray], b: Dict[str, np.ndarray], cap: int
) -> Dict[str, np.ndarray]:
    a = {kk: np.array(v) for kk, v in a.items()}
    for j in range(a["keys"].shape[0]):
        live = ~np.isnan(b["keys"][j])
        a["keys"][j], a["counts"][j], e = _mg_fold_column(
            a["keys"][j], a["counts"][j],
            int(a["err"][j]) + int(b["err"][j]),
            b["keys"][j][live], b["counts"][j][live], cap,
        )
        a["err"][j] = e
    a["n"] = a["n"] + b["n"]
    return a


def frequent_items_result(acc: Dict[str, np.ndarray]) -> list:
    """Per-column [(value, count_lower_bound), ...] sorted by count; the
    per-column `err` is the +/- slack every bound carries (<= n/cap)."""
    out = []
    for j in range(acc["keys"].shape[0]):
        live = ~np.isnan(acc["keys"][j])
        pairs = sorted(
            zip(acc["keys"][j][live].tolist(),
                acc["counts"][j][live].tolist()),
            key=lambda it: (-it[1], it[0]),
        )
        out.append(pairs)
    return out


# ---------------------------------------------------------------------------
# HyperLogLog finalizer (registers accumulate on device, estimate on host)
# ---------------------------------------------------------------------------


def hll_init(d: int, p_bits: int) -> Dict[str, np.ndarray]:
    """Fresh host-side HyperLogLog state: (cols, 2^p_bits) int32 max-rank
    registers — the same register layout as the device `distinct_count`
    program, so `hll_estimate` serves both."""
    return {"regs": np.zeros((d, 2 ** p_bits), np.int32)}


def hll_update(
    acc: Dict[str, np.ndarray], X: np.ndarray, valid: np.ndarray,
    p_bits: int,
) -> Dict[str, np.ndarray]:
    """Numpy twin of the device `distinct_count` step (stats/programs.py
    `_hll_make_step`): same -0.0 canonicalization, same murmur3
    finalizer over the f32 bit pattern, same bucket/rank split — so a
    host-folded register table estimates with identical accuracy.  Rows
    with `valid`=False never enter."""
    vals = np.asarray(X[np.asarray(valid, bool)])
    if vals.size == 0:
        return acc
    h = (np.asarray(vals, np.float32) + 0.0).view(np.uint32)
    h = h ^ (h >> np.uint32(16))
    h = h * np.uint32(0x85EBCA6B)
    h = h ^ (h >> np.uint32(13))
    h = h * np.uint32(0xC2B2AE35)
    h = h ^ (h >> np.uint32(16))
    bucket = (h >> np.uint32(32 - p_bits)).astype(np.int64)
    rest = (h << np.uint32(p_bits)).astype(np.uint32)
    # clz(rest) + 1 without a hardware clz: 32 - bit_length(rest); the
    # float64 log2 is exact for every uint32 (52-bit mantissa)
    nz = rest > 0
    bitlen = np.zeros(rest.shape, np.int32)
    bitlen[nz] = np.floor(np.log2(rest[nz].astype(np.float64))).astype(
        np.int32
    ) + 1
    rho = np.minimum(32 - bitlen + 1, 32 - p_bits + 1).astype(np.int32)
    m = 2 ** p_bits
    regs = acc["regs"].reshape(-1)
    cols = np.broadcast_to(
        np.arange(vals.shape[1], dtype=np.int64)[None, :], bucket.shape
    )
    np.maximum.at(regs, (cols * m + bucket).reshape(-1), rho.reshape(-1))
    return acc


def hll_estimate(registers: np.ndarray) -> np.ndarray:
    """(cols,) distinct-count estimates from (cols, m) max-rank
    registers — the standard HLL estimator with the small-range
    linear-counting correction (Flajolet et al.)."""
    regs = np.asarray(registers, np.float64)
    m = regs.shape[1]
    alpha = {16: 0.673, 32: 0.697, 64: 0.709}.get(
        m, 0.7213 / (1.0 + 1.079 / m)
    )
    raw = alpha * m * m / np.power(2.0, -regs).sum(axis=1)
    zeros = (regs == 0).sum(axis=1)
    small = zeros > 0
    est = np.where(
        small & (raw <= 2.5 * m),
        m * np.log(m / np.maximum(zeros, 1)),
        raw,
    )
    return est


# ---------------------------------------------------------------------------
# Versioned wire format for sketch state (KLL quantiles, Misra-Gries,
# HyperLogLog) — the persistence the drift monitor's baseline
# fingerprints (monitor/fingerprint.py) stand on.  A serialized state
# restores to NUMERICALLY IDENTICAL arrays (np.savez round-trip), so
# merging two round-tripped states is byte-exact with merging the
# originals (asserted by tests/test_drift_monitor.py).  The version is
# checked on load and a mismatch REJECTS: silently reinterpreting an
# old layout would corrupt every divergence computed from it.
# ---------------------------------------------------------------------------

SKETCH_WIRE_MAGIC = b"SRSK"
SKETCH_WIRE_VERSION = 1

_SKETCH_KINDS = ("quantile", "frequent", "hll")


def sketch_to_bytes(kind: str, state: Dict[str, np.ndarray]) -> bytes:
    """Serialize one sketch state dict.  `kind` names which sketch
    family the arrays belong to (quantile | frequent | hll); the state
    arrays are stored compressed (sketch buffers are mostly zeros)."""
    if kind not in _SKETCH_KINDS:
        raise ValueError(
            f"unknown sketch kind {kind!r}; known: {_SKETCH_KINDS}"
        )
    buf = io.BytesIO()
    np.savez_compressed(
        buf, **{k: np.asarray(v) for k, v in state.items()}
    )
    payload = buf.getvalue()
    kind_b = kind.encode()
    return (
        SKETCH_WIRE_MAGIC
        + struct.pack("<HH", SKETCH_WIRE_VERSION, len(kind_b))
        + kind_b
        + payload
    )


def sketch_from_bytes(blob: bytes) -> Tuple[str, Dict[str, np.ndarray]]:
    """Inverse of `sketch_to_bytes`: (kind, state).  Raises ValueError
    on a bad magic or a version this build does not speak — a sketch
    from a different wire version must be re-captured, never guessed
    at."""
    if blob[:4] != SKETCH_WIRE_MAGIC:
        raise ValueError("not a serialized sketch (bad magic)")
    version, klen = struct.unpack("<HH", blob[4:8])
    if version != SKETCH_WIRE_VERSION:
        raise ValueError(
            f"sketch wire version {version} unsupported (this build "
            f"speaks {SKETCH_WIRE_VERSION}); re-capture the sketch"
        )
    kind = blob[8:8 + klen].decode()
    if kind not in _SKETCH_KINDS:
        raise ValueError(f"unknown sketch kind {kind!r} in payload")
    with np.load(io.BytesIO(blob[8 + klen:]), allow_pickle=False) as z:
        state = {k: z[k] for k in z.files}
    return kind, state
