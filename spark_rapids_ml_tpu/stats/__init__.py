#
# spark_rapids_ml_tpu.stats — the declarative one-pass statistics
# subsystem (ROADMAP item 5): statistic programs registered in
# `STAT_PROGRAMS` (programs.py), a fused multi-program engine that runs
# any set of them in ONE pass over every existing chunk path
# (engine.py), mergeable sketch state (sketches.py), and the
# reference-compatible `Summarizer` / `describe()` surface
# (summarizer.py).  See docs/statistics.md for the program contract,
# the registered-program table and registration how-to.
#
from .engine import STAT_METRICS, iter_chunk_accs, run_program, run_programs
from .programs import (
    STAT_PROGRAMS,
    Field,
    StatProgram,
    get_program,
    merge_accs,
    register_program,
)
from .summarizer import SUPPORTED_METRICS, Summarizer, describe, summarize

__all__ = [
    "Field",
    "STAT_METRICS",
    "STAT_PROGRAMS",
    "SUPPORTED_METRICS",
    "StatProgram",
    "Summarizer",
    "describe",
    "get_program",
    "iter_chunk_accs",
    "merge_accs",
    "register_program",
    "run_program",
    "run_programs",
    "summarize",
]
