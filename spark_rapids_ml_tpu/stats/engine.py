#
# Statistic-program engine — run ANY set of registered programs
# (stats/programs.py STAT_PROGRAMS) in ONE pass over the data, on every
# chunk path the package already has:
#
#   - in-memory batches chunk through `fused.iter_host_chunks` (the
#     fused engine's prepared fixed-shape chunks),
#   - parquet paths stream through `fused.iter_parquet_chunks` — the
#     row-group-pruned parallel range readers AND the chunk cache, so a
#     second summarize of the same file replays from memory,
#   - chunk prep runs `staging_pipeline_depth` ahead on the producer
#     thread while the mesh folds the previous chunk (the PR-8 overlap).
#
# Device programs fold through ONE jitted combined step with the whole
# accumulator dict donated; host (sketch) programs fold on the consumer
# thread from the same decoded chunk — still one pass, no extra IO.
#
# Resilience: the per-chunk `stat_program_step` fault site fails the
# WHOLE pass, and the retry restarts it with FRESH accumulators
# (re-creatable state, never resumed mid-pass), so a retried chunk can
# never double-count — the `fused_accumulate` contract, inherited.
#
from __future__ import annotations

import functools

from ..telemetry.locks import named_lock
import time
from typing import Any, Callable, Dict, Iterable, Optional, Sequence, Tuple

import numpy as np

from ..config import get_config
from ..telemetry.registry import counter, dict_view, histogram
from ..utils import get_logger

logger = get_logger("spark_rapids_ml_tpu.stats")

# last engine run (stamped), copied into the fit report's `stats`
# section and read by bench.py's `summarize` section: programs/chunks/
# bytes folded, wall + prep/accumulate split, measured overlap
STAT_METRICS = dict_view(
    "stat_program_last",
    "Last statistic-program engine run (programs/chunks/overlap)",
)

_runs_total = counter(
    "stat_program_runs_total",
    "Statistic-program executions by program name",
)
_pass_seconds = histogram(
    "stat_program_pass_seconds",
    "Wall seconds per fused statistic pass by run label",
)

# STAT_METRICS is process-wide LAST-RUN state: two concurrent passes
# (a caller running describe() from several threads) must not
# interleave their clear+update into a chimera of both runs — the
# writes are ATOMIC under this lock (whichever pass finishes last wins,
# a consistent single-run view), and a pass that overlapped another —
# in EITHER direction: every live pass is marked when a new one starts,
# so the first starter finishing last still knows — records
# `concurrent_passes` so readers know the engine counters around it are
# process-level (the PR-5 concurrent-fits report guard, mirrored)
_stat_metrics_lock = named_lock("stat_metrics")
_PASS_STATE: Dict[str, Any] = {"live": []}  # per-pass mutable tokens

# CONCURRENT one-pass statistics folds serialize their DEVICE step on
# this lock: two threads dispatching multi-device (mesh-sharded) jitted
# accumulator steps simultaneously can interleave their per-device
# executions into a resource-ordering deadlock inside the runtime
# (observed wedging the full CPU-mesh suite at the concurrent-describe
# test — both threads frozen inside the jitted call, zero CPU).  The
# lock is SHARED with the fused stage-and-solve engine
# (fused.accumulate_chunks — the other mesh-sharded accumulator
# dispatch site), so a describe() racing a fused fit serializes too.
# Chunk prep and the prefetch producers still interleave freely; the
# host sketch folds run INSIDE the held region, between the async
# dispatch and the sync, so a lone pass keeps its device/host overlap
# and pays one uncontended acquire per chunk.
_device_step_lock = named_lock("device_step")


def _chunk_rows_for(n: int, d: int, itemsize: int, n_dev: int) -> int:
    from ..fused import fused_chunk_rows

    return fused_chunk_rows(n, d, itemsize, n_dev)


@functools.lru_cache(maxsize=32)
def _combined_step(
    names: Tuple[str, ...], d: int, dtype_str: str, has_y: bool,
    weighted: bool, opts_token: Tuple, precision: str, compensated: bool,
):
    """One donated jitted step folding EVERY requested device program's
    chunk contribution — repeated runs at the same (programs, shape,
    dtype, precision) reuse the compiled program (the fused engine's
    `_jitted_steps` discipline).  `precision`/`compensated` key the
    conf values baked in at trace time, and `opts_token` carries the
    RESOLVED per-program options (sketch/bin geometry included), so a
    conf change between runs re-traces instead of reusing a step built
    for the old shapes.  The `weighted=False` variant dispatches each
    program's unweighted fast step where it has one (full unweighted
    chunks skip the X*w chunk-sized copy and the weight transfer —
    ops/stats.py's unweighted-variant rationale)."""
    import jax

    from .programs import get_program

    opts = {name: dict(o) for name, o in opts_token}
    dtype = np.dtype(dtype_str)
    steps: Dict[str, Tuple[Callable, Optional[Callable], bool]] = {}
    for name in names:
        p = get_program(name)
        step_w, unw = p.make_step(d, dtype, opts.get(name, {}))
        steps[name] = (step_w, unw, p.needs_y)

    def _one(name, fn_w, unw, ny, acc, X, w, y):
        if w is None and unw is not None and not ny:
            return unw(acc[name], X)
        import jax.numpy as jnp

        wv = jnp.ones((X.shape[0],), X.dtype) if w is None else w
        if ny:
            return fn_w(acc[name], X, wv, y)
        return fn_w(acc[name], X, wv)

    if has_y:
        if weighted:
            def combined(acc, X, w, y):
                return {
                    name: _one(name, fw, unw, ny, acc, X, w, y)
                    for name, (fw, unw, ny) in steps.items()
                }
        else:
            def combined(acc, X, y):
                return {
                    name: _one(name, fw, unw, ny, acc, X, None, y)
                    for name, (fw, unw, ny) in steps.items()
                }
    else:
        if weighted:
            def combined(acc, X, w):
                return {
                    name: _one(name, fw, unw, ny, acc, X, w, None)
                    for name, (fw, unw, ny) in steps.items()
                }
        else:
            def combined(acc, X):
                return {
                    name: _one(name, fw, unw, ny, acc, X, None, None)
                    for name, (fw, unw, ny) in steps.items()
                }

    return jax.jit(combined, donate_argnums=0)


def _normalize_source(
    source, features_col, features_cols, label_col, weight_col, dtype,
    needs_y: bool,
):
    """(producer_factory, d, n_or_None, dtype): producer_factory(n_dev)
    yields prepared `(X, y, w)` fixed-shape chunks (fused.py contract;
    `w` None = full unweighted chunk)."""
    from ..streaming import is_parquet_path

    dtype = np.dtype(dtype or np.float32)
    if is_parquet_path(source):
        from ..streaming import (
            chunk_rows_for,
            parquet_row_count,
            probe_num_features,
        )

        d = probe_num_features(source, features_col, features_cols)
        n = parquet_row_count(source)
        if n == 0:
            raise ValueError("Dataset is empty: nothing to summarize")
        chunk_rows = min(chunk_rows_for(d, dtype.itemsize), max(n, 1))

        def factory(n_dev: int):
            from ..fused import iter_parquet_chunks

            rows = -(-min(chunk_rows, n) // n_dev) * n_dev
            prep: Dict[str, Any] = {"s": 0.0, "iv": []}
            return (
                # with_offsets: each chunk carries its GLOBAL first-row
                # index, so offset-addressed host programs (the
                # kmeans_sample reservoir) fill the same slots from the
                # same rows at any process/reader count
                iter_parquet_chunks(
                    source, features_col, features_cols,
                    label_col if needs_y else None, weight_col,
                    rows, dtype, prep=prep, with_offsets=True,
                ),
                prep,
            )

        return factory, d, n, dtype

    from ..data import _is_sparse, extract_arrays

    batch = extract_arrays(
        source,
        features_col=features_col,
        features_cols=features_cols,
        label_col=label_col if needs_y else None,
        weight_col=weight_col,
        dtype=None,
        supervised=needs_y,
    )
    X = batch.X
    if _is_sparse(X):
        X = np.asarray(X.todense())
    X = np.asarray(X, dtype)
    if X.ndim == 1:
        X = X.reshape(-1, 1)
    n, d = int(X.shape[0]), int(X.shape[1])
    if n == 0:
        raise ValueError("Dataset is empty: nothing to summarize")
    y, w = batch.y, batch.weight

    def factory(n_dev: int):
        from ..fused import iter_host_chunks

        rows = _chunk_rows_for(n, d, dtype.itemsize, n_dev)
        return iter_host_chunks(X, y, w, rows, dtype)

    return factory, d, n, dtype


def run_programs(
    names: Sequence[str],
    source,
    *,
    features_col: Optional[str] = "features",
    features_cols: Sequence[str] = (),
    label_col: Optional[str] = None,
    weight_col: Optional[str] = None,
    dtype=None,
    opts: Optional[Dict[str, Dict[str, Any]]] = None,
    quantiles: Optional[Sequence[float]] = None,
    label: str = "summarize",
) -> Dict[str, Dict[str, Any]]:
    """Run the named registered programs in ONE fused pass over
    `source` (in-memory batch, pandas frame, or parquet path).  Returns
    `{program_name: finalized statistics}`.

    The pass runs under the standard retry policy with the accumulators
    treated as re-creatable state: a mid-pass OOM/device-loss (the
    `stat_program_step` fault site) restarts the whole pass fresh on
    the (possibly shrunken) mesh — never resuming half-folded sums, so
    a retried chunk cannot double-count."""
    from ..resilience import retry_call

    names = tuple(dict.fromkeys(names))  # preserve order, drop dups
    if not names:
        raise ValueError("no statistic programs requested")
    from .programs import get_program

    progs = [get_program(n) for n in names]
    for p in progs:
        if p.extra_args:
            raise ValueError(
                f"program {p.name!r} requires extra step arguments "
                f"{p.extra_args} and runs only through its dedicated "
                "caller (the fused estimator path), not the generic "
                "engine dispatch"
            )
    needs_y = any(p.needs_y for p in progs)
    if needs_y and label_col is None and not _has_label(source):
        raise ValueError(
            "programs "
            + ", ".join(p.name for p in progs if p.needs_y)
            + " need a label column (label_col=...)"
        )
    factory, d, n, dtype = _normalize_source(
        source, features_col, features_cols, label_col, weight_col,
        dtype, needs_y,
    )
    return retry_call(
        lambda: _one_pass(
            progs, factory, d, dtype, needs_y,
            dict(opts or {}), quantiles, label,
        ),
        label="stat_programs",
        log=logger,
    )


def run_program(name: str, source, **kwargs) -> Dict[str, Any]:
    """Single-program convenience over `run_programs`."""
    return run_programs([name], source, **kwargs)[name]


def _has_label(source) -> bool:
    return isinstance(source, (tuple, list)) and len(source) == 2


def _one_pass(
    progs, factory, d: int, dtype, needs_y: bool,
    opts: Dict[str, Dict[str, Any]], quantiles, label: str,
) -> Dict[str, Dict[str, Any]]:
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from ..fused import _interval_overlap_s, _merge_intervals, _resolve_producer
    from ..ops.precision import stats_compensated
    from ..ops.stats import acc_to_host_f64
    from ..parallel.mesh import (
        DATA_AXIS, _staging_depth, data_pspec, get_mesh, timed_iter,
    )
    from ..resilience import maybe_inject
    from ..telemetry.compile import compile_label
    from ..telemetry.heartbeat import Heartbeat
    from ..telemetry.memory import record_prediction
    from ..tracing import current_run_id, mint_run_id, run_context
    from ..utils import prefetch_iter

    from .programs import resolve_opts

    dtype = np.dtype(dtype)
    device_progs = [p for p in progs if p.kind == "device"]
    host_progs = [p for p in progs if p.kind == "host"]
    mesh = get_mesh()
    if jax.process_count() > 1:
        # multi-process: fold on the LOCAL devices only — chunks and the
        # accumulators never leave this host; the per-rank partials meet
        # in ONE cross-process reduction after the chunk loop (psum on
        # collective-capable backends, the coordination-service wire on
        # CPU builds) — see _reduce_pass_across_processes
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(jax.local_devices()), (DATA_AXIS,))
    n_dev = mesh.devices.size

    popts = {p.name: resolve_opts(p, opts.get(p.name)) for p in progs}
    dev_acc = {
        p.name: p.init(d, dtype, popts[p.name]) for p in device_progs
    }
    host_acc = {
        p.name: p.init(d, dtype, popts[p.name]) for p in host_progs
    }
    host_steps = {
        p.name: p.make_step(d, dtype, popts[p.name]) for p in host_progs
    }
    step_for = None
    if device_progs:
        dev_names = tuple(p.name for p in device_progs)
        opts_token = tuple(
            (p.name, tuple(sorted(popts[p.name].items())))
            for p in device_progs
        )
        precision = str(get_config("stats_precision")).lower()
        comp = stats_compensated()

        def step_for(weighted: bool):
            return _combined_step(
                dev_names, d, dtype.str, needs_y, weighted, opts_token,
                precision, comp,
            )
    # budget accounting: the pass holds one sharded chunk + the
    # accumulators — record the prediction so the drift watermarks see it
    acc_bytes = sum(
        int(np.asarray(v).nbytes)
        for acc in dev_acc.values()
        for v in jax.tree_util.tree_leaves(acc)
    )
    record_prediction("stat_programs", float(acc_bytes))

    mat_sh = NamedSharding(mesh, data_pspec(2))
    row_sh = NamedSharding(mesh, PartitionSpec(DATA_AXIS))
    rep_sh = NamedSharding(mesh, PartitionSpec())
    if device_progs:
        dev_acc = jax.device_put(dev_acc, rep_sh)

    chunks, prep = _resolve_producer(factory(n_dev))
    self_timed = prep is not None
    if prep is None:
        prep = {"s": 0.0, "iv": []}
        chunks = timed_iter(chunks, prep)

    t0 = time.perf_counter()
    acc_s = 0.0
    acc_iv = []
    n_chunks = 0
    nbytes = 0
    offset = 0
    # ad-hoc describe()/summarize() calls must not leave live solver
    # series behind: beats run under a minted run id and the gauges are
    # end-marked on NORMAL completion (Heartbeat.close); a pass that
    # dies mid-loop deliberately leaves its last state visible for the
    # flight recorder
    rid = current_run_id() or mint_run_id("summarize")
    # pod observatory (telemetry/fleet.py): pod-global pass id for this
    # statistics pass — SPMD site, every rank mints/receives here
    from ..telemetry import fleet as _fleet

    _fleet.begin_pod_pass()
    pass_token = {"overlapped": False}
    with _stat_metrics_lock:
        if _PASS_STATE["live"]:
            pass_token["overlapped"] = True
            for t in _PASS_STATE["live"]:
                t["overlapped"] = True
        _PASS_STATE["live"].append(pass_token)
    try:
        with run_context(rid), compile_label("stat_programs"):
            hb = Heartbeat("stat_programs")
            for item in prefetch_iter(chunks, _staging_depth()):
                # the engine's fault site: a failure here fails the WHOLE
                # pass; the retry restarts with fresh accumulators
                maybe_inject("stat_program_step")
                # parquet producers yield 4-tuples carrying the chunk's
                # GLOBAL first-row offset (iter_parquet_chunks
                # with_offsets); in-memory producers yield 3-tuples and
                # the rank-local running offset is already global there
                cX, cy, cw = item[0], item[1], item[2]
                goff = item[3] if len(item) > 3 else None
                chunk_rows = int(cX.shape[0])
                ta = time.perf_counter()

                def _fold_host() -> None:
                    if not host_progs:
                        return
                    from ..streaming import _weights_host

                    # cached read-only ones for the common full-
                    # unweighted chunk: the validity mask allocates
                    # nothing
                    w_host = cw if cw is not None else _weights_host(
                        None, chunk_rows, chunk_rows, dtype
                    )
                    ctx = {
                        "offset": offset if goff is None else goff,
                        "n_valid": int(np.count_nonzero(w_host > 0)),
                    }
                    for p in host_progs:
                        host_acc[p.name] = host_steps[p.name](
                            host_acc[p.name], cX, w_host, cy, ctx
                        )

                if step_for is not None:
                    # full unweighted chunks (cw None) dispatch the
                    # unweighted fast variant: no weight transfer, no
                    # X*w chunk copy for programs that declare an unw
                    # step.  Dispatch-to-sync holds _device_step_lock
                    # (see the lock's comment); the host folds run
                    # between dispatch and sync so the async device
                    # execution still overlaps them
                    with _device_step_lock:
                        args = [jax.device_put(cX, mat_sh)]
                        if cw is not None:
                            args.append(jax.device_put(cw, row_sh))
                        if needs_y:
                            args.append(jax.device_put(cy, row_sh))
                        dev_acc = step_for(cw is not None)(dev_acc, *args)
                        _fold_host()
                        jax.block_until_ready(dev_acc)
                else:
                    _fold_host()
                tb = time.perf_counter()
                acc_s += tb - ta
                acc_iv.append((ta, tb))
                offset += chunk_rows
                n_chunks += 1
                nbytes += cX.nbytes + (
                    cw.nbytes if cw is not None else 0
                ) + (cy.nbytes if needs_y and cy is not None else 0)
                hb.beat(n_chunks)
            hb.close()

        folded: Dict[str, Dict[str, Any]] = {}
        for p in device_progs:
            folded[p.name] = acc_to_host_f64(dev_acc[p.name])
        folded.update(host_acc)
        # topology view (parallel/context.py): a post-rank-loss survivor
        # group of one skips the reduce instead of waiting on the dead
        from ..parallel.context import process_topology

        if process_topology()[0] > 1:
            folded, offset = _reduce_pass_across_processes(
                progs, popts, d, folded, offset
            )
        wall = time.perf_counter() - t0

        ctx = {"d": d, "rows": offset, "quantiles": tuple(quantiles or ())}
        results = {p.name: p.finalize(folded[p.name], ctx) for p in progs}

        prep_iv = _merge_intervals(prep["iv"]) if self_timed else prep["iv"]
        # the pass's device/prep windows feed the run's utilization
        # timeline (telemetry/utilization.py) — same evidence the
        # overlap fraction below is computed from
        from ..telemetry import utilization

        utilization.note_intervals("device", acc_iv, cause="stat_programs")
        utilization.note_intervals("host_prep", prep_iv, cause="chunk_prep")
        # close the pod pass after the intervals land (the straggler
        # blob reads the timeline); its exchange is the pass's last
        # SPMD site
        _fleet.complete_pod_pass(run_id=rid)
        overlap_s = _interval_overlap_s(prep_iv, acc_iv)
        overlap = 0.0
        if min(prep["s"], acc_s) > 1e-9:
            overlap = max(0.0, min(overlap_s / min(prep["s"], acc_s), 1.0))
        for p in progs:
            _runs_total.inc(program=p.name)
        _pass_seconds.observe(wall, label=label)
        # the clear+update is ATOMIC under the lock: a reader (or the
        # other pass's writer) sees one complete run's record, never an
        # interleaving of two (asserted by the concurrent-describe test)
        with _stat_metrics_lock:
            overlapped = pass_token["overlapped"]
            STAT_METRICS.clear()
            STAT_METRICS.update(
                stamp=round(time.time(), 3),
                label=label,
                programs=len(progs),
                passes=1,
                chunks=n_chunks,
                bytes=int(nbytes),
                wall_s=round(wall, 4),
                host_prep_s=round(prep["s"], 4),
                device_acc_s=round(acc_s, 4),
                overlap_s=round(overlap_s, 4),
                overlap_fraction=round(overlap, 4),
                **({"concurrent_passes": True} if overlapped else {}),
            )
    finally:
        with _stat_metrics_lock:
            _PASS_STATE["live"].remove(pass_token)
    from ..tracing import event

    event(
        f"stat_programs[{label}]",
        detail=(
            f"programs={len(progs)} chunks={n_chunks} "
            f"{nbytes / 1e6:.1f}MB wall={wall:.2f}s overlap={overlap:.2f}"
        ),
    )
    return results


def _reduce_pass_across_processes(progs, popts, d, folded, rows):
    """Cross-process reduction at pass completion: every rank folded
    only its ingest share (streaming.process_ingest_ranges /
    fused.process_row_group_shares), so the per-rank partials combine
    here into the GLOBAL accumulators every rank then finalizes
    identically.

    Pure-sum device fields — plus the pass row count — collapse through
    ONE reduce_host_arrays call (a single jitted psum when the backend
    supports cross-process collectives, the deterministic rank-ordered
    wire fold otherwise).  min/max device fields and the host sketch
    programs (KLL quantiles, Misra-Gries, k-means sample) travel as one
    wire blob per rank and merge with each program's own merge
    (stats.programs.merge_accs) in ascending rank order, so every rank
    computes byte-identical results — the 2-process parity suite
    asserts describe() equality against a single-process run.

    Host-step `ctx["offset"]` is GLOBAL under sharded ingest (the
    parquet producer labels every chunk with its first-row index in the
    file — iter_parquet_chunks with_offsets), so offset-addressed slot
    programs (kmeans_sample) fill the same reservoir slots from the
    same rows at any process count and their merge is byte-identical
    to the single-process fill (the 2-process parity suite asserts a
    k-means fit equal against a 1-process run)."""
    import io

    from ..parallel.context import reduce_blob_list, reduce_host_arrays

    sums: Dict[str, Any] = {"__rows__": np.asarray(float(rows))}
    wire: Dict[str, Any] = {}
    modes: Dict[str, str] = {}
    for p in progs:
        if p.kind == "host":
            for f, v in folded[p.name].items():
                wire[f"{p.name}:{f}"] = np.asarray(v)
            continue
        declared = p.shapes(d, popts[p.name])
        for f, v in folded[p.name].items():
            if declared[f].merge == "sum":
                sums[f"{p.name}:{f}"] = np.asarray(v)
            else:
                wire[f"{p.name}:{f}"] = np.asarray(v)
                modes[f"{p.name}:{f}"] = declared[f].merge

    summed = reduce_host_arrays(sums, "stat_pass")
    rows_global = int(round(float(summed.pop("__rows__"))))
    for key, v in summed.items():
        name, f = key.split(":", 1)
        folded[name][f] = v

    if wire:
        from .programs import merge_accs

        buf = io.BytesIO()
        np.savez(buf, **wire)
        blobs = reduce_blob_list("stat_sketches", buf.getvalue())
        states = []
        for blob in blobs:
            with np.load(io.BytesIO(blob)) as z:
                states.append({k: np.array(z[k]) for k in z.files})
        for key, mode in modes.items():
            out = states[0][key]
            for s in states[1:]:
                out = (
                    np.minimum(out, s[key]) if mode == "min"
                    else np.maximum(out, s[key])
                )
            name, f = key.split(":", 1)
            folded[name][f] = out
        for p in progs:
            if p.kind != "host":
                continue
            fields = list(folded[p.name])
            acc = {f: states[0][f"{p.name}:{f}"] for f in fields}
            for s in states[1:]:
                acc = merge_accs(
                    p, acc,
                    {f: s[f"{p.name}:{f}"] for f in fields},
                    popts[p.name],
                )
            folded[p.name] = acc
    return folded, rows_global


def iter_chunk_accs(
    name: str,
    chunks: Iterable,
    d: int,
    dtype=np.float32,
    opts: Optional[Dict[str, Any]] = None,
    offset0: int = 0,
) -> Dict[str, Any]:
    """Fold an explicit in-order `(X, y, w, n_valid)` chunk iterator
    (streaming.iter_chunks contract) through ONE program and return the
    HOST accumulator — the light entry the epoch-streaming paths use
    (e.g. the k-means|| seeding sample), where the caller owns the
    chunk loop and row range.  `offset0` is the GLOBAL row index of the
    stream's first row (multi-process per-partition reads)."""
    import jax

    from ..ops.stats import acc_to_host_f64
    from ..streaming import _weights_host
    from .programs import get_program

    from .programs import resolve_opts

    p = get_program(name)
    dtype = np.dtype(dtype)
    popts = resolve_opts(p, opts)
    acc = p.init(d, dtype, popts)
    if p.kind == "host":
        step = p.make_step(d, dtype, popts)
        offset = int(offset0)
        for cX, cy, cw, n_c in chunks:
            chunk_rows = int(cX.shape[0])
            w_host = np.asarray(
                _weights_host(cw, n_c, chunk_rows, dtype)
            )
            acc = step(
                acc, np.asarray(cX), w_host, cy,
                {"offset": offset, "n_valid": int(n_c)},
            )
            offset += n_c
        return acc
    import jax.numpy as jnp

    step_w, _unw = p.make_step(d, dtype, popts)
    step_j = jax.jit(step_w, donate_argnums=0)
    for cX, cy, cw, n_c in chunks:
        chunk_rows = int(cX.shape[0])
        w_host = _weights_host(cw, n_c, chunk_rows, dtype)
        args = [jnp.asarray(np.asarray(cX, dtype)), jnp.asarray(w_host)]
        if p.needs_y:
            args.append(jnp.asarray(np.asarray(cy, dtype)))
        acc = step_j(acc, *args)
    return acc_to_host_f64(acc)
