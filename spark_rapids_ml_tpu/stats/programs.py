#
# Statistic programs — the declarative contract ROADMAP item 5 promotes
# PR 8's accumulator specs into.  A program is four functions over
# fixed-shape `(X, w[, y])` chunks:
#
#   init(d, dtype, opts)       fresh accumulator dict (DECLARED shapes)
#   step(acc, X, w[, y])       fold one chunk (device: jax, donated;
#                              host: numpy, in-place-and-return)
#   merge(a, b)                combine two partial accumulators (device
#                              programs derive it from each field's
#                              declared merge mode: sum | min | max)
#   finalize(host_acc, ctx)    accumulator -> user-facing statistics
#
# Programs register in `STAT_PROGRAMS` with declared accumulator
# shapes/dtypes; the declaration is VERIFIED against a probe init on
# first use (`get_program` — import-light registration), and the
# graft-lint `stat-program` rule anchors `run_program(...)` call sites
# and the docs/statistics.md program table against this registry.  The engine (stats/engine.py)
# fuses any set of registered programs into ONE pass over the data on
# every existing chunk path (fused stage-and-solve overlap, epoch
# chunk-cache replay, plain in-memory batches).
#
# The PR-8 estimator specs (`ops/stats.py` pca/linreg accumulators) are
# REGISTERED here rather than re-implemented: fused.py and streaming.py
# resolve their specs through this registry, so the migrated paths stay
# numerically identical to the pre-registry outputs (asserted by
# tests/test_stat_programs.py).
#
from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import numpy as np

from ..config import get_config
from ..ops.stats import CARRY_SUFFIX, _kahan_add


class Field(NamedTuple):
    """One declared accumulator field: shape (in terms of the feature
    dimension d), dtype (None = follows the requested accumulation
    dtype), and how two partial accumulators combine on this field."""

    shape: Tuple[int, ...]
    dtype: Optional[str] = None
    merge: str = "sum"  # sum | min | max | slot (host slot-disjoint)


@dataclass(frozen=True)
class StatProgram:
    """A registered statistic program.  `kind` is "device" (jax step,
    donated accumulator, runs inside the engine's one jitted combined
    step) or "host" (numpy step on the decoded chunk — the mergeable
    sketches whose data-dependent updates have no fixed-shape jax
    form).  `make_step(d, dtype, opts)` returns the step callable(s):
    device programs return `(weighted_step, unweighted_step_or_None)`
    so the fused engine keeps its full-chunk fast path; host programs
    return one `step(acc, X, w, y, ctx)`."""

    name: str
    kind: str
    shapes: Callable[[int, Dict[str, Any]], Dict[str, Field]]
    init: Callable[[int, Any, Dict[str, Any]], Dict[str, Any]]
    make_step: Callable[[int, Any, Dict[str, Any]], Any]
    finalize: Callable[[Dict[str, Any], Dict[str, Any]], Dict[str, Any]]
    merge: Optional[Callable[..., Dict[str, Any]]] = None
    needs_y: bool = False
    mergeable: bool = True
    # precision modes the device step honors (ops/precision.py
    # stats_precision levels; host sketches are precision-independent)
    precision_modes: Tuple[str, ...] = ("exact",)
    doc: str = ""
    opts_defaults: Dict[str, Any] = dc_field(default_factory=dict)
    # resolves CONF-derived option values (sketch sizes, bin counts)
    # into explicit dict entries, so the engine's compiled-step cache
    # keys on the effective geometry — a `set_config` change between
    # runs must re-trace, never reuse a step built for the old shapes
    resolve: Optional[Callable[[Dict[str, Any]], Dict[str, Any]]] = None
    # extra per-pass step arguments (e.g. the randomized range-finder's
    # omega): programs declaring them run only through their dedicated
    # callers (fused.py), never the generic engine dispatch
    extra_args: Tuple[str, ...] = ()


STAT_PROGRAMS: Dict[str, StatProgram] = {}

_PROBE_D = 3

# programs whose declaration has been verified against a probe init
# (first-use, via `get_program`): device inits build jax arrays, and a
# probe at REGISTRATION time would initialize the XLA backend on bare
# `import spark_rapids_ml_tpu` — which must stay legal before
# `init_distributed()` (parallel/context.py rejects distributed init
# once a backend exists)
_VALIDATED: set = set()


def register_program(p: StatProgram) -> StatProgram:
    """Register a program.  The declared shapes/dtypes are VERIFIED
    against a probe `init` the first time the program is fetched
    (`get_program`) — the runtime half of the graft-lint `stat-program`
    rule — so a program cannot drift from its declaration, while
    registration itself stays import-light (no accelerator arrays are
    built at package import)."""
    if p.name in STAT_PROGRAMS:
        raise ValueError(f"statistic program {p.name!r} already registered")
    if p.kind not in ("device", "host"):
        raise ValueError(f"program {p.name!r}: kind must be device|host")
    STAT_PROGRAMS[p.name] = p
    return p


def _validate(p: StatProgram) -> None:
    """Probe-init at d=3 and compare against the declaration."""
    opts = resolve_opts(p, None)
    declared = p.shapes(_PROBE_D, opts)
    acc = p.init(_PROBE_D, np.float32, opts)
    got = {k: v for k, v in acc.items() if not k.endswith(CARRY_SUFFIX)}
    if set(got) != set(declared):
        raise ValueError(
            f"program {p.name!r}: init fields {sorted(got)} != declared "
            f"{sorted(declared)}"
        )
    for fname, spec in declared.items():
        v = got[fname]
        want_shape = tuple(spec.shape)
        if tuple(v.shape) != want_shape:
            raise ValueError(
                f"program {p.name!r}: field {fname!r} shape "
                f"{tuple(v.shape)} != declared {want_shape}"
            )
        want_dtype = np.dtype(spec.dtype or np.float32)
        if np.dtype(v.dtype) != want_dtype:
            raise ValueError(
                f"program {p.name!r}: field {fname!r} dtype {v.dtype} != "
                f"declared {want_dtype}"
            )


def get_program(name: str) -> StatProgram:
    p = STAT_PROGRAMS.get(name)
    if p is None:
        raise KeyError(
            f"unknown statistic program {name!r}; registered: "
            + ", ".join(sorted(STAT_PROGRAMS))
        )
    if name not in _VALIDATED:
        _validate(p)
        _VALIDATED.add(name)
    return p


def merge_accs(
    p: StatProgram, a: Dict[str, Any], b: Dict[str, Any],
    opts: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Combine two HOST-side partial accumulators of one program.  Host
    programs bring their own merge; device programs merge field-wise by
    the declared mode (their accumulators are plain commutative
    reductions)."""
    if not p.mergeable:
        raise ValueError(f"program {p.name!r} is not mergeable")
    if p.merge is not None:
        return p.merge(a, b, resolve_opts(p, opts))
    declared = p.shapes(_infer_d(p, a), resolve_opts(p, opts))
    out: Dict[str, Any] = {}
    for k, v in a.items():
        if k.endswith(CARRY_SUFFIX):
            continue
        mode = declared[k].merge
        if mode == "sum":
            out[k] = np.asarray(v) + np.asarray(b[k])
        elif mode == "min":
            out[k] = np.minimum(v, b[k])
        elif mode == "max":
            out[k] = np.maximum(v, b[k])
        else:
            raise ValueError(
                f"program {p.name!r}: field {k!r} merge mode {mode!r}"
            )
    return out


def resolve_opts(
    p: StatProgram, opts: Optional[Dict[str, Any]]
) -> Dict[str, Any]:
    """Effective per-program options: defaults, caller overrides, then
    the program's conf resolution (explicit sketch/bin sizes)."""
    merged = dict(p.opts_defaults)
    merged.update(opts or {})
    if p.resolve is not None:
        merged = p.resolve(merged)
    return merged


def _infer_d(p: StatProgram, acc: Dict[str, Any]) -> int:
    """The feature dimension a host accumulator was built at, read back
    off a field whose declared shape leads with d."""
    for fname, spec in p.shapes(_PROBE_D, resolve_opts(p, None)).items():
        if spec.shape and spec.shape[0] == _PROBE_D:
            return int(np.shape(acc[fname])[0])
    return _PROBE_D


def _zeros(
    shapes: Dict[str, Field], d_actual: Dict[str, Field], dtype,
    compensated_fields: Tuple[str, ...] = (),
):
    """Device zeros accumulator honoring per-field dtypes, with Kahan
    carry twins on the compensated sum fields when the
    `stats_precision` conf asks for them (ops/stats.py discipline)."""
    import jax.numpy as jnp

    from ..ops.precision import stats_compensated

    del shapes  # declared probe shapes; d_actual carries the real ones
    comp = stats_compensated()
    acc = {}
    for k, spec in d_actual.items():
        dt = np.dtype(spec.dtype or dtype)
        if spec.merge == "min":
            acc[k] = jnp.full(spec.shape, jnp.inf, dt)
        elif spec.merge == "max":
            acc[k] = jnp.full(spec.shape, -jnp.inf, dt)
        else:
            acc[k] = jnp.zeros(spec.shape, dt)
        if comp and k in compensated_fields:
            acc[k + CARRY_SUFFIX] = jnp.zeros(spec.shape, dt)
    return acc


# ---------------------------------------------------------------------------
# column moments / min / max  (count, mean, variance, std, norms, nnz)
# ---------------------------------------------------------------------------

_MOMENT_COMP = ("sw", "s1", "s2", "l1")


def _moments_shapes(d: int, opts: Dict[str, Any]) -> Dict[str, Field]:
    return {
        "sw": Field(()),
        "n": Field((), "int32"),
        "s1": Field((d,)),
        "s2": Field((d,)),
        "l1": Field((d,)),
        "nnz": Field((d,), "int32"),
        "min": Field((d,), merge="min"),
        "max": Field((d,), merge="max"),
    }


def _moments_init(d: int, dtype, opts: Dict[str, Any]):
    return _zeros(
        _moments_shapes(_PROBE_D, opts), _moments_shapes(d, opts),
        dtype, _MOMENT_COMP,
    )


def _moments_make_step(d: int, dtype, opts: Dict[str, Any]):
    def step(acc, X, w):
        import jax.numpy as jnp

        valid = w > 0
        Xw = X * w[:, None]
        out = dict(acc)
        out.update(_kahan_add(acc, "s1", Xw.sum(axis=0)))
        out.update(_kahan_add(acc, "s2", (Xw * X).sum(axis=0)))
        out.update(
            _kahan_add(acc, "l1", (jnp.abs(X) * w[:, None]).sum(axis=0))
        )
        out.update(_kahan_add(acc, "sw", w.sum()))
        # exact integer counts (int32: f32 would round past 2^24 rows)
        out["n"] = acc["n"] + valid.sum(dtype=jnp.int32)
        out["nnz"] = acc["nnz"] + (
            (X != 0) & valid[:, None]
        ).sum(axis=0, dtype=jnp.int32)
        lo = jnp.where(valid[:, None], X, jnp.inf)
        hi = jnp.where(valid[:, None], X, -jnp.inf)
        out["min"] = jnp.minimum(acc["min"], lo.min(axis=0))
        out["max"] = jnp.maximum(acc["max"], hi.max(axis=0))
        return out

    return step, None


def _moments_finalize(acc: Dict[str, Any], ctx: Dict[str, Any]):
    sw = float(acc["sw"])
    mean = np.asarray(acc["s1"]) / max(sw, 1e-300)
    # Spark MultivariateOnlineSummarizer variance: ddof-1-scaled weighted
    # central moment (ops/stats.py weighted_moments semantics)
    var = (np.asarray(acc["s2"]) - sw * mean * mean) / max(sw - 1.0, 1.0)
    var = np.maximum(var, 0.0)
    return {
        "count": int(acc["n"]),
        "weight_sum": sw,
        "mean": mean,
        "sum": np.asarray(acc["s1"]),
        "variance": var,
        "std": np.sqrt(var),
        "min": np.asarray(acc["min"]),
        "max": np.asarray(acc["max"]),
        "norm_l1": np.asarray(acc["l1"]),
        "norm_l2": np.sqrt(np.maximum(np.asarray(acc["s2"]), 0.0)),
        "num_nonzeros": np.asarray(acc["nnz"]),
    }


register_program(StatProgram(
    name="moments",
    kind="device",
    shapes=_moments_shapes,
    init=_moments_init,
    make_step=_moments_make_step,
    finalize=_moments_finalize,
    precision_modes=("exact", "high_compensated"),
    doc="per-column count/mean/variance/std/min/max/norms/nonzeros",
))


def _standardization_finalize(acc: Dict[str, Any], ctx: Dict[str, Any]):
    """Standardization stats with the solver contract applied: zero
    variance columns scale by 1.0 (ops/stats.py weighted_moments)."""
    out = _moments_finalize(acc, ctx)
    std = np.where(out["std"] == 0.0, 1.0, out["std"])
    return {"mean": out["mean"], "std": std, "weight_sum": out["weight_sum"]}


register_program(StatProgram(
    name="standardization",
    kind="device",
    shapes=_moments_shapes,
    init=_moments_init,
    make_step=_moments_make_step,
    finalize=_standardization_finalize,
    precision_modes=("exact", "high_compensated"),
    doc="solver standardization mean/std (zero-variance columns -> 1.0)",
))


# ---------------------------------------------------------------------------
# covariance / correlation  (shares the PCA second-moment accumulator)
# ---------------------------------------------------------------------------


def _second_moment_shapes(d: int, opts: Dict[str, Any]) -> Dict[str, Field]:
    return {"S": Field((d, d)), "s1": Field((d,)), "sw": Field(())}


def _second_moment_init(d: int, dtype, opts: Dict[str, Any]):
    from ..ops.stats import pca_moment_acc

    acc, _ = pca_moment_acc(d, np.dtype(dtype))
    return acc


def _second_moment_make_step(d: int, dtype, opts: Dict[str, Any]):
    from ..ops.stats import pca_moment_acc, pca_moment_step_unw

    _, step = pca_moment_acc(d, np.dtype(dtype))
    return step, pca_moment_step_unw


def _covariance_finalize(acc: Dict[str, Any], ctx: Dict[str, Any]):
    sw = float(acc["sw"])
    mean = np.asarray(acc["s1"]) / max(sw, 1e-300)
    cov = (
        np.asarray(acc["S"]) - sw * np.outer(mean, mean)
    ) / max(sw - 1.0, 1.0)
    cov = (cov + cov.T) / 2.0  # symmetrize away accumulation round-off
    sd = np.sqrt(np.maximum(np.diag(cov), 0.0))
    denom = np.outer(sd, sd)
    corr = np.divide(
        cov, denom, out=np.full_like(cov, np.nan), where=denom > 0
    )
    np.fill_diagonal(corr, 1.0)
    return {"mean": mean, "covariance": cov, "correlation": corr,
            "weight_sum": sw}


register_program(StatProgram(
    name="covariance",
    kind="device",
    shapes=_second_moment_shapes,
    init=_second_moment_init,
    make_step=_second_moment_make_step,
    finalize=_covariance_finalize,
    precision_modes=("exact", "high_compensated"),
    doc="covariance + correlation matrices from one Gram pass",
))


# ---------------------------------------------------------------------------
# migrated estimator specs (PR 8): fused.py / streaming.py resolve their
# accumulators THROUGH these registrations
# ---------------------------------------------------------------------------


def _pca_moments_finalize(acc: Dict[str, Any], ctx: Dict[str, Any]):
    return dict(acc)  # PCA._attrs_from_moments consumes S/s1/sw raw


register_program(StatProgram(
    name="pca_moments",
    kind="device",
    shapes=_second_moment_shapes,
    init=_second_moment_init,
    make_step=_second_moment_make_step,
    finalize=_pca_moments_finalize,
    precision_modes=("exact", "high_compensated"),
    doc="PCA exact second moments (migrated ops/stats.py pca_moment_acc)",
))


def _pca_projected_shapes(d: int, opts: Dict[str, Any]) -> Dict[str, Field]:
    l = int(opts.get("l", 8))
    return {
        "SOm": Field((d, l)), "s1": Field((d,)), "ssq": Field((d,)),
        "sw": Field(()),
    }


def _pca_projected_init(d: int, dtype, opts: Dict[str, Any]):
    from ..ops.stats import pca_projected_acc

    acc, _ = pca_projected_acc(d, int(opts.get("l", 8)), np.dtype(dtype))
    return acc


def _pca_projected_make_step(d: int, dtype, opts: Dict[str, Any]):
    from ..ops.stats import pca_projected_acc, pca_projected_step_unw

    _, step = pca_projected_acc(d, int(opts.get("l", 8)), np.dtype(dtype))
    return step, pca_projected_step_unw


register_program(StatProgram(
    name="pca_projected",
    kind="device",
    shapes=_pca_projected_shapes,
    init=_pca_projected_init,
    make_step=_pca_projected_make_step,
    finalize=lambda acc, ctx: dict(acc),
    precision_modes=("exact", "high_compensated"),
    doc="randomized-PCA projected moments (takes the range-finder's "
        "omega as an extra step argument)",
    opts_defaults={"l": 8},
    extra_args=("omega",),
))


def _linreg_shapes(d: int, opts: Dict[str, Any]) -> Dict[str, Field]:
    return {
        "gram": Field((d, d)), "sxy": Field((d,)), "s1": Field((d,)),
        "sw": Field(()), "sy": Field(()), "syy": Field(()),
    }


def _linreg_init(d: int, dtype, opts: Dict[str, Any]):
    from ..ops.stats import linreg_acc

    acc, _ = linreg_acc(d, np.dtype(dtype))
    return acc


def _linreg_make_step(d: int, dtype, opts: Dict[str, Any]):
    from ..ops.stats import linreg_acc, linreg_step_unw

    _, step = linreg_acc(d, np.dtype(dtype))
    return step, linreg_step_unw


register_program(StatProgram(
    name="linreg",
    kind="device",
    shapes=_linreg_shapes,
    init=_linreg_init,
    make_step=_linreg_make_step,
    finalize=lambda acc, ctx: dict(acc),
    needs_y=True,
    precision_modes=("exact", "high_compensated"),
    doc="weighted Gram/moment/cross statistics (migrated ops/stats.py "
        "linreg_acc)",
))


# ---------------------------------------------------------------------------
# hypothesis tests: grouped moments (t-test) and contingency (chi-squared)
# ---------------------------------------------------------------------------


def _grouped_shapes(d: int, opts: Dict[str, Any]) -> Dict[str, Field]:
    return {
        "gn": Field((2,)), "gs1": Field((2, d)), "gs2": Field((2, d)),
    }


def _grouped_init(d: int, dtype, opts: Dict[str, Any]):
    return _zeros(
        _grouped_shapes(_PROBE_D, opts), _grouped_shapes(d, opts),
        dtype, ("gs1", "gs2"),
    )


def _grouped_make_step(d: int, dtype, opts: Dict[str, Any]):
    def step(acc, X, w, y):
        import jax.numpy as jnp

        g1 = (y > 0.5).astype(X.dtype)
        gw = jnp.stack([w * (1.0 - g1), w * g1])  # (2, rows)
        out = dict(acc)
        out["gn"] = acc["gn"] + gw.sum(axis=1)
        out.update(_kahan_add(acc, "gs1", gw @ X))
        out.update(_kahan_add(acc, "gs2", gw @ (X * X)))
        return out

    return step, None


def _ttest_finalize(acc: Dict[str, Any], ctx: Dict[str, Any]):
    """Per-column Welch two-sample t-test between label groups 0/1."""
    n = np.asarray(acc["gn"], np.float64)  # (2,)
    s1 = np.asarray(acc["gs1"], np.float64)
    s2 = np.asarray(acc["gs2"], np.float64)
    mean = s1 / np.maximum(n[:, None], 1e-300)
    var = (s2 - n[:, None] * mean * mean) / np.maximum(
        n[:, None] - 1.0, 1.0
    )
    var = np.maximum(var, 0.0)
    se2 = var[0] / max(n[0], 1.0) + var[1] / max(n[1], 1.0)
    t = (mean[0] - mean[1]) / np.sqrt(np.maximum(se2, 1e-300))
    df_num = se2 * se2
    df_den = (
        (var[0] / max(n[0], 1.0)) ** 2 / max(n[0] - 1.0, 1.0)
        + (var[1] / max(n[1], 1.0)) ** 2 / max(n[1] - 1.0, 1.0)
    )
    df = df_num / np.maximum(df_den, 1e-300)
    return {
        "t": t, "df": df, "p_value": _t_sf(np.abs(t), df) * 2.0,
        "group_counts": n, "group_means": mean, "group_variances": var,
    }


def _t_sf(t: np.ndarray, df: np.ndarray) -> np.ndarray:
    try:
        from scipy.stats import t as t_dist

        return t_dist.sf(t, np.maximum(df, 1e-9))
    except ImportError:  # pragma: no cover - scipy ships in the image
        from math import erf, sqrt

        return np.asarray(
            [0.5 * (1.0 - erf(float(x) / sqrt(2.0))) for x in np.ravel(t)]
        ).reshape(np.shape(t))


register_program(StatProgram(
    name="ttest",
    kind="device",
    shapes=_grouped_shapes,
    init=_grouped_init,
    make_step=_grouped_make_step,
    finalize=_ttest_finalize,
    needs_y=True,
    precision_modes=("exact", "high_compensated"),
    doc="per-column Welch two-sample t-test between label groups 0/1",
))


def _contingency_bins(opts: Dict[str, Any]) -> int:
    return int(opts.get("bins") or get_config("summarizer_chi2_bins"))


def _contingency_shapes(d: int, opts: Dict[str, Any]) -> Dict[str, Field]:
    b = _contingency_bins(opts)
    return {"counts": Field((d, b, b))}


def _contingency_init(d: int, dtype, opts: Dict[str, Any]):
    return _zeros(
        _contingency_shapes(_PROBE_D, opts), _contingency_shapes(d, opts),
        dtype,
    )


def _contingency_make_step(d: int, dtype, opts: Dict[str, Any]):
    b = _contingency_bins(opts)

    def step(acc, X, w, y):
        import jax.numpy as jnp

        counts = acc["counts"]
        xi = jnp.clip(jnp.round(X).astype(jnp.int32), 0, b - 1)
        yi = jnp.clip(jnp.round(y).astype(jnp.int32), 0, b - 1)
        flat = counts.reshape(-1)
        cols = jnp.arange(X.shape[1], dtype=jnp.int32)[None, :]
        idx = (cols * (b * b) + xi * b + yi[:, None]).reshape(-1)
        upd = jnp.broadcast_to(
            w[:, None].astype(counts.dtype), xi.shape
        ).reshape(-1)
        out = dict(acc)
        out["counts"] = flat.at[idx].add(upd).reshape(counts.shape)
        return out

    return step, None


def _chi2_finalize(acc: Dict[str, Any], ctx: Dict[str, Any]):
    """Per-column chi-squared test of independence between the (integer
    -coded, clipped to `summarizer_chi2_bins`) feature and the label."""
    counts = np.asarray(acc["counts"], np.float64)
    d = counts.shape[0]
    stat = np.zeros((d,))
    dof = np.zeros((d,), np.int64)
    p = np.ones((d,))
    for j in range(d):
        O = counts[j]
        O = O[O.sum(axis=1) > 0][:, O.sum(axis=0) > 0]
        if O.shape[0] < 2 or O.shape[1] < 2:
            continue
        n = O.sum()
        E = np.outer(O.sum(axis=1), O.sum(axis=0)) / n
        stat[j] = float(((O - E) ** 2 / E).sum())
        dof[j] = (O.shape[0] - 1) * (O.shape[1] - 1)
        p[j] = _chi2_sf(stat[j], int(dof[j]))
    return {"statistic": stat, "dof": dof, "p_value": p}


def _chi2_sf(x: float, dof: int) -> float:
    try:
        from scipy.stats import chi2 as chi2_dist

        return float(chi2_dist.sf(x, dof))
    except ImportError:  # pragma: no cover - scipy ships in the image
        from math import exp

        return float(exp(-x / 2.0))


register_program(StatProgram(
    name="chi2",
    kind="device",
    shapes=_contingency_shapes,
    init=_contingency_init,
    make_step=_contingency_make_step,
    finalize=_chi2_finalize,
    needs_y=True,
    doc="per-column chi-squared independence test vs the label (binned "
        "contingency counts)",
    resolve=lambda opts: dict(opts, bins=_contingency_bins(opts)),
))


# ---------------------------------------------------------------------------
# HyperLogLog distinct counts (device; int32 registers exercise the
# dtype-preserving accumulator fold)
# ---------------------------------------------------------------------------


def _hll_bits(opts: Dict[str, Any]) -> int:
    return int(opts.get("bits") or get_config("summarizer_hll_bits"))


def _hll_shapes(d: int, opts: Dict[str, Any]) -> Dict[str, Field]:
    return {"regs": Field((d, 2 ** _hll_bits(opts)), "int32", merge="max")}


def _hll_init(d: int, dtype, opts: Dict[str, Any]):
    import jax.numpy as jnp

    return {"regs": jnp.zeros((d, 2 ** _hll_bits(opts)), jnp.int32)}


def _hll_make_step(d: int, dtype, opts: Dict[str, Any]):
    p_bits = _hll_bits(opts)
    m = 2 ** p_bits

    def step(acc, X, w):
        import jax
        import jax.numpy as jnp

        # canonicalize -0.0 -> +0.0 so equal values hash equal, then
        # murmur3-finalize the f32 bit pattern
        h = jax.lax.bitcast_convert_type(
            (X + 0.0).astype(jnp.float32), jnp.uint32
        )
        h = h ^ (h >> 16)
        h = h * jnp.uint32(0x85EBCA6B)
        h = h ^ (h >> 13)
        h = h * jnp.uint32(0xC2B2AE35)
        h = h ^ (h >> 16)
        bucket = (h >> (32 - p_bits)).astype(jnp.int32)
        rest = jax.lax.bitcast_convert_type(h << p_bits, jnp.int32)
        rho = jnp.minimum(jax.lax.clz(rest) + 1, 32 - p_bits + 1)
        rho = jnp.where((w > 0)[:, None], rho, 0).astype(jnp.int32)
        cols = jnp.arange(X.shape[1], dtype=jnp.int32)[None, :]
        idx = (cols * m + bucket).reshape(-1)
        regs = acc["regs"].reshape(-1).at[idx].max(rho.reshape(-1))
        return {"regs": regs.reshape(acc["regs"].shape)}

    return step, None


def _hll_finalize(acc: Dict[str, Any], ctx: Dict[str, Any]):
    from .sketches import hll_estimate

    return {"distinct": hll_estimate(np.asarray(acc["regs"]))}


register_program(StatProgram(
    name="distinct_count",
    kind="device",
    shapes=_hll_shapes,
    init=_hll_init,
    make_step=_hll_make_step,
    finalize=_hll_finalize,
    doc="per-column HyperLogLog approximate distinct counts",
    resolve=lambda opts: dict(opts, bits=_hll_bits(opts)),
))


# ---------------------------------------------------------------------------
# host sketch programs: KLL-style quantiles, Misra-Gries frequent items
# ---------------------------------------------------------------------------


def _qk(opts: Dict[str, Any]) -> int:
    return int(opts.get("k") or get_config("summarizer_sketch_k"))


def _quantile_shapes(d: int, opts: Dict[str, Any]) -> Dict[str, Field]:
    from .sketches import QUANTILE_LEVELS

    k = _qk(opts)
    return {
        "items": Field((d, QUANTILE_LEVELS, k), "float64", merge="slot"),
        "sizes": Field((QUANTILE_LEVELS,), "int64", merge="slot"),
        "n": Field((), "int64"),
    }


def _quantile_init(d: int, dtype, opts: Dict[str, Any]):
    from .sketches import quantile_init

    return quantile_init(d, _qk(opts))


def _quantile_make_step(d: int, dtype, opts: Dict[str, Any]):
    from .sketches import quantile_update

    k = _qk(opts)

    def step(acc, X, w, y, ctx):
        return quantile_update(acc, X, np.asarray(w) > 0, k)

    return step


def _quantile_merge(a, b, opts: Dict[str, Any]):
    from .sketches import quantile_merge

    return quantile_merge(a, b, _qk(opts))


def _quantile_finalize(acc: Dict[str, Any], ctx: Dict[str, Any]):
    from .sketches import quantile_query

    qs = ctx.get("quantiles") or (0.25, 0.5, 0.75)
    vals = quantile_query(acc, qs)
    return {
        "n": int(acc["n"]),
        "quantiles": {float(q): vals[:, i] for i, q in enumerate(qs)},
        "state": acc,
    }


register_program(StatProgram(
    name="quantile_sketch",
    kind="host",
    shapes=_quantile_shapes,
    init=_quantile_init,
    make_step=_quantile_make_step,
    finalize=_quantile_finalize,
    merge=_quantile_merge,
    doc="mergeable KLL-style per-column quantile sketch",
    resolve=lambda opts: dict(opts, k=_qk(opts)),
))


def _fk(opts: Dict[str, Any]) -> int:
    return int(opts.get("cap") or get_config("summarizer_frequent_k"))


def _frequent_shapes(d: int, opts: Dict[str, Any]) -> Dict[str, Field]:
    cap = _fk(opts)
    return {
        "keys": Field((d, cap), "float64", merge="slot"),
        "counts": Field((d, cap), "int64", merge="slot"),
        "err": Field((d,), "int64"),
        "n": Field((), "int64"),
    }


def _frequent_init(d: int, dtype, opts: Dict[str, Any]):
    from .sketches import frequent_init

    return frequent_init(d, _fk(opts))


def _frequent_make_step(d: int, dtype, opts: Dict[str, Any]):
    from .sketches import frequent_update

    cap = _fk(opts)

    def step(acc, X, w, y, ctx):
        return frequent_update(acc, X, np.asarray(w) > 0, cap)

    return step


def _frequent_merge(a, b, opts: Dict[str, Any]):
    from .sketches import frequent_merge

    return frequent_merge(a, b, _fk(opts))


def _frequent_finalize(acc: Dict[str, Any], ctx: Dict[str, Any]):
    from .sketches import frequent_items_result

    return {
        "n": int(acc["n"]),
        "items": frequent_items_result(acc),
        "error_bound": np.asarray(acc["err"]),
        "state": acc,
    }


register_program(StatProgram(
    name="frequent_items",
    kind="host",
    shapes=_frequent_shapes,
    init=_frequent_init,
    make_step=_frequent_make_step,
    finalize=_frequent_finalize,
    merge=_frequent_merge,
    doc="Misra-Gries per-column frequent items (count lower bounds with "
        "a declared error slack)",
    resolve=lambda opts: dict(opts, cap=_fk(opts)),
))


# ---------------------------------------------------------------------------
# seeded k-means|| init sampling (migrated from the inline
# streaming.kmeans_streaming_fit collection loop): a strided global
# subsample assembled slot-disjointly from chunks, so any chunk order /
# chunk split reconstructs the IDENTICAL sample (byte parity asserted)
# ---------------------------------------------------------------------------


def _ks_opts(opts: Dict[str, Any]) -> Tuple[int, int]:
    return int(opts.get("stride", 1)), int(opts.get("cap", 8))


def _kmeans_sample_shapes(d: int, opts: Dict[str, Any]) -> Dict[str, Field]:
    _, cap = _ks_opts(opts)
    return {
        "rows": Field((cap, d), "float64", merge="slot"),
        "w": Field((cap,), "float64", merge="slot"),
        "mask": Field((cap,), "int64", merge="slot"),
    }


def _kmeans_sample_init(d: int, dtype, opts: Dict[str, Any]):
    _, cap = _ks_opts(opts)
    return {
        "rows": np.zeros((cap, d), np.float64),
        "w": np.zeros((cap,), np.float64),
        "mask": np.zeros((cap,), np.int64),
    }


def _kmeans_sample_make_step(d: int, dtype, opts: Dict[str, Any]):
    stride, cap = _ks_opts(opts)

    def step(acc, X, w, y, ctx):
        offset = int(ctx["offset"])
        n_c = int(ctx["n_valid"])
        gidx = np.arange(offset, offset + n_c)
        pick = (gidx % stride) == 0
        if pick.any():
            slots = gidx[pick] // stride
            slots = slots[slots < cap]
            pick = np.flatnonzero(pick)[: slots.size]
            acc["rows"][slots] = np.asarray(X[:n_c][pick], np.float64)
            acc["w"][slots] = np.asarray(w[:n_c][pick], np.float64)
            acc["mask"][slots] = 1
        return acc

    return step


def _kmeans_sample_merge(a, b, opts: Dict[str, Any]):
    take = np.asarray(b["mask"]) > 0
    out = {k: np.array(v) for k, v in a.items()}
    out["rows"][take] = np.asarray(b["rows"])[take]
    out["w"][take] = np.asarray(b["w"])[take]
    out["mask"][take] = 1
    return out


def _kmeans_sample_finalize(acc: Dict[str, Any], ctx: Dict[str, Any]):
    filled = np.asarray(acc["mask"]) > 0
    return {
        "X": np.asarray(acc["rows"])[filled],
        "w": np.asarray(acc["w"])[filled],
        "count": int(filled.sum()),
    }


register_program(StatProgram(
    name="kmeans_sample",
    kind="host",
    shapes=_kmeans_sample_shapes,
    init=_kmeans_sample_init,
    make_step=_kmeans_sample_make_step,
    finalize=_kmeans_sample_finalize,
    merge=_kmeans_sample_merge,
    doc="strided global row subsample feeding the seeded k-means|| init "
        "(slot-disjoint: any chunking assembles the identical sample)",
    opts_defaults={"stride": 1, "cap": 8},
))
