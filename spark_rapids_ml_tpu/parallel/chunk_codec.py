#
# Spill codecs for the chunk cache (parallel/device_cache.py
# `ChunkCache`) — the compressed host tier of the Snap ML-style memory
# hierarchy: decoded chunks evicted from device/host residency are
# serialized through one of these codecs before they land in the spill
# tier, and every spilled buffer carries a crc32 of its RAW bytes so a
# torn or bit-rotted blob is detected at re-serve time instead of
# silently corrupting an epoch.
#
# The registry is pluggable: `register_codec` accepts any
# (compress, decompress) pair operating on bytes.  `lz4` / `zstd` are
# registered lazily and only resolve where the optional wheels exist
# (the CI image bakes neither — `zlib` is the stdlib-always-available
# compressed option, `none` the zero-cost raw option).  Deliberately
# numpy/jax-free: resolving a codec must never pay an accelerator
# import.
#
from __future__ import annotations


from ..telemetry.locks import named_lock
import zlib
from typing import Callable, Dict, Tuple

Compress = Callable[[bytes], bytes]
Decompress = Callable[[bytes], bytes]

_lock = named_lock("chunk_codec")


def _zlib_pair() -> Tuple[Compress, Decompress]:
    # level 1: the spill path sits on the epoch hot loop — favor speed
    # (decoded float chunks rarely reward higher levels anyway)
    return (lambda b: zlib.compress(b, 1)), zlib.decompress


def _none_pair() -> Tuple[Compress, Decompress]:
    return (lambda b: b), (lambda b: b)


def _lz4_pair() -> Tuple[Compress, Decompress]:
    import lz4.frame  # gated: optional wheel

    return lz4.frame.compress, lz4.frame.decompress


def _zstd_pair() -> Tuple[Compress, Decompress]:
    import zstandard  # gated: optional wheel

    c = zstandard.ZstdCompressor(level=1)
    d = zstandard.ZstdDecompressor()
    return c.compress, d.decompress


# name -> zero-arg factory returning (compress, decompress); factories
# defer optional imports to resolve time
_FACTORIES: Dict[str, Callable[[], Tuple[Compress, Decompress]]] = {
    "none": _none_pair,
    "zlib": _zlib_pair,
    "lz4": _lz4_pair,
    "zstd": _zstd_pair,
}


def register_codec(name: str, compress: Compress, decompress: Decompress) -> None:
    """Plug in a custom spill codec under `name` (overrides builtins)."""
    with _lock:
        _FACTORIES[str(name)] = lambda: (compress, decompress)


def available_codecs() -> Tuple[str, ...]:
    with _lock:
        names = tuple(sorted(_FACTORIES))
    out = []
    for n in names:
        try:
            resolve_codec(n)
        except (ImportError, ValueError):
            continue
        out.append(n)
    return tuple(out)


def resolve_codec(name: str) -> Tuple[str, Compress, Decompress]:
    """(name, compress, decompress) for a registered codec.  Raises
    ValueError for an unknown name and ImportError when the codec's
    optional dependency is absent from the image (the caller surfaces
    the conf fix; nothing is pip-installed on its behalf)."""
    name = str(name).lower()
    with _lock:
        factory = _FACTORIES.get(name)
    if factory is None:
        raise ValueError(
            f"unknown chunk_cache_codec {name!r}; registered: "
            f"{', '.join(sorted(_FACTORIES))}"
        )
    try:
        compress, decompress = factory()
    except ImportError as e:
        raise ImportError(
            f"chunk_cache_codec={name!r} needs an optional dependency "
            f"this image lacks ({e}); use 'zlib' (stdlib) or 'none'"
        ) from e
    return name, compress, decompress


def checksum(data: bytes) -> int:
    """crc32 over the RAW (uncompressed) chunk bytes — verified on every
    re-serve from the spill tier."""
    return zlib.crc32(data) & 0xFFFFFFFF


__all__ = [
    "available_codecs",
    "checksum",
    "register_codec",
    "resolve_codec",
]
