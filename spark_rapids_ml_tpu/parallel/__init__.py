#
# parallel/ — the communication + device layer: the analog of the
# reference's `common/cuml_context.py` (NCCL/UCX bootstrap over Spark
# barrier allGather, reference cuml_context.py:35-206) and the GPU-placement
# half of utils.py.  On TPU the whole layer collapses into JAX's SPMD model:
# a `jax.sharding.Mesh` over the pod slice, XLA collectives over ICI/DCN,
# and `jax.distributed.initialize` for the multi-host bootstrap.
#
from .mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    RowStager,
    active_devices,
    exclude_devices,
    get_mesh,
    replicate,
    restore_devices,
    shard_rows,
    data_pspec,
    replicated_pspec,
)
from .context import (  # noqa: F401
    DeviceLoss,
    TpuContext,
    init_distributed,
    probe_device_health,
    reinit_distributed,
    shutdown_distributed,
)
from .device_cache import (  # noqa: F401
    ChunkCache,
    DeviceDatasetCache,
    clear_chunk_cache,
    clear_device_cache,
    get_chunk_cache,
    get_device_cache,
)
