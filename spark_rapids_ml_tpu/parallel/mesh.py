#
# Device mesh + row-sharding helpers — the TPU-native replacement for the
# reference's partition->GPU placement (`_get_gpu_id` utils.py:138-170,
# `_CumlCommon._set_gpu_device` core.py:366-411) and the data-parallel rank
# layout.  One 1-D mesh axis "data" carries the reference's row-sharded
# data parallelism (SURVEY.md §2.12 strategy 1); a second axis name is
# reserved for model/feature sharding extensions.
#
from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def ensure_x64(dtype) -> None:
    """Enable jax x64 on demand when the user requests float64
    (`float32_inputs=False`, reference core.py:514-537 keeps f64 inputs in
    f64).  Scoped to the explicit request rather than an import-time global
    flip so importing this library never changes the numerics of unrelated
    JAX code in the process."""
    if np.dtype(dtype) == np.float64 and not jax.config.jax_enable_x64:
        from ..utils import get_logger

        get_logger("spark_rapids_ml_tpu").info(
            "Enabling jax_enable_x64 for float64 inputs (float32_inputs=False)."
        )
        jax.config.update("jax_enable_x64", True)

DATA_AXIS = "data"
MODEL_AXIS = "model"

_mesh_cache = {}


def get_mesh(num_workers: Optional[int] = None) -> Mesh:
    """A 1-D mesh over the first `num_workers` visible devices.  `num_workers`
    is the analog of the reference's `num_workers` (= #GPUs = #barrier tasks,
    reference params.py:556-588); on TPU it is the number of chips
    participating in the SPMD fit."""
    devices = jax.devices()
    n = num_workers or len(devices)
    if n > len(devices):
        raise ValueError(
            f"num_workers={n} exceeds the {len(devices)} visible devices. "
            f"On multi-host pods initialize jax.distributed first."
        )
    key = (n, tuple(d.id for d in devices[:n]))
    if key not in _mesh_cache:
        _mesh_cache[key] = Mesh(np.array(devices[:n]), (DATA_AXIS,))
    return _mesh_cache[key]


def data_pspec(ndim: int = 2) -> PartitionSpec:
    """Rows sharded over the data axis, features replicated."""
    return PartitionSpec(DATA_AXIS, *([None] * (ndim - 1)))


def replicated_pspec() -> PartitionSpec:
    return PartitionSpec()


def shard_rows(
    arr: np.ndarray,
    mesh: Mesh,
    dtype: Optional[np.dtype] = None,
) -> Tuple[jax.Array, int]:
    """Stage a host array onto the mesh with rows sharded over DATA_AXIS.

    This is the host->device staging hot loop of the reference
    (core.py:886-957 pandas->cupy conversion + `_concat_and_free`); here a
    single `jax.device_put` with a NamedSharding splits rows across chips.
    Returns (global sharded jax.Array, true row count before padding).
    """
    dtype = np.dtype(dtype) if dtype is not None else arr.dtype
    ensure_x64(dtype)
    n_valid = arr.shape[0]
    rem = (-n_valid) % mesh.devices.size
    if rem or arr.dtype != dtype:
        if arr.ndim == 2:
            # single host copy fusing the dtype cast and the zero-padding;
            # OpenMP-parallel via the native staging library when large
            from ..native import pad_cast

            padded = pad_cast(arr, n_valid + rem, dtype)
        else:
            padded = np.zeros((n_valid + rem,) + arr.shape[1:], dtype)
            padded[:n_valid] = arr
    else:
        padded = arr
    sharding = NamedSharding(mesh, data_pspec(padded.ndim))
    return jax.device_put(padded, sharding), n_valid


def row_mask(n_valid: int, n_padded: int, mesh: Mesh, dtype=np.float32) -> jax.Array:
    """Validity weights for padded rows (1 real, 0 pad), sharded like data."""
    w = np.zeros((n_padded,), dtype=dtype)
    w[:n_valid] = 1.0
    sharding = NamedSharding(mesh, PartitionSpec(DATA_AXIS))
    return jax.device_put(w, sharding)


def replicate(arr: Union[np.ndarray, jax.Array], mesh: Mesh) -> jax.Array:
    """Replicate an array on every device of the mesh (model/centroid
    arrays — the analog of NCCL-broadcast model state)."""
    sharding = NamedSharding(mesh, PartitionSpec())
    return jax.device_put(arr, sharding)
